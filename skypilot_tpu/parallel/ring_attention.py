"""Ring attention: context parallelism over the `sp` mesh axis.

Long-context capability the reference framework lacks entirely (SURVEY.md
§2.6: no sequence/context parallelism anywhere in the reference). Native
here: the sequence axis of q/k/v is sharded over `sp`; each device computes
blockwise attention of its local queries against the KV chunk it currently
holds, accumulates with online softmax, and passes KV around the ring with
`lax.ppermute` — collectives ride the ICI torus, overlap comes from XLA
scheduling the permute against the chunk matmuls.

Only the `sp` axis is manual (`jax.shard_map(..., axis_names={'sp'})`);
dp/fsdp/tp stay automatic, so the same rule table governs the rest of the
model around this op.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from skypilot_tpu.parallel import mesh as mesh_lib

_NEG_INF = -1e30


# KV sub-block width inside one ring chunk: bounds the live score
# matrix to (B, H, Sl, _KV_BLOCK) regardless of per-shard length.
_KV_BLOCK = 512
# Below this block width the scan's per-step cost dominates the einsum;
# chunk lengths with no divisor >= the floor take the pad-and-mask path.
_KV_BLOCK_FLOOR = 128


def _chunk_update(q, kc, vc, qpos, kpos0, m, l, acc, *, causal, scale):
    """One online-softmax update of local queries against one KV chunk,
    BLOCKWISE over the chunk's KV axis.

    q: (B, Sl, H, D) bf16; kc/vc: (B, Sl, KVH, D) bf16; m/l:
    (B, H, Sl, 1) f32; acc: (B, H, Sl, D) f32. kpos0 is the chunk's
    absolute start position (chunk positions are contiguous).

    Two properties real context lengths need: matmuls take bf16 INPUTS
    with f32 accumulation (fp32 inputs run the MXU ~4x below peak), and
    scores exist only one (Sl x _KV_BLOCK) sub-block at a time — a full
    (Sl x Sl) chunk score matrix is gigabytes at 8k+ per shard.
    """
    b, sl, h, d = q.shape
    kvh = kc.shape[2]
    groups = h // kvh
    # Largest divisor of the chunk length <= _KV_BLOCK (any divisor,
    # not only powers of two): halving alone degenerates to 1-2-wide
    # blocks for lengths with small odd factors, wrecking the MXU.
    n = kc.shape[1]
    block = max(dv for dv in range(1, min(_KV_BLOCK, n) + 1)
                if n % dv == 0)
    if block < _KV_BLOCK_FLOOR and n > block:
        # Prime / small-odd-factor chunk lengths have no decent
        # divisor: the exact-divisor path would scan thousands of
        # 1-2-wide einsum steps (ADVICE r3 #2). Pad the chunk to a
        # multiple of _KV_BLOCK instead and mask the tail slots out of
        # the softmax below.
        block = min(_KV_BLOCK, n)
        pad = (-n) % block
        if pad:
            kc = jnp.pad(kc, ((0, 0), (0, pad), (0, 0), (0, 0)))
            vc = jnp.pad(vc, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = kc.shape[1] // block
    padded = kc.shape[1] != n
    # Grouped-query form: keep K/V at KVH heads and fold the group axis
    # into the einsum instead of materializing repeated K/V.
    qg = q.reshape(b, sl, kvh, groups, d)

    def body(carry, j):
        m, l, acc = carry
        # Slice in place: staging a blocks-leading copy of the chunk
        # would re-write (B, Sl, KVH, D) every ring step (twice with
        # the checkpoint recompute) — real HBM traffic at long context.
        kcj = lax.dynamic_slice_in_dim(kc, j * block, block, axis=1)
        vcj = lax.dynamic_slice_in_dim(vc, j * block, block, axis=1)
        s = jnp.einsum("bqkgd,bskd->bkgqs", qg, kcj,
                       preferred_element_type=jnp.float32) * scale
        s = s.reshape(b, h, sl, block)
        idx = j * block + jnp.arange(block)
        if causal:
            kpos = kpos0 + idx
            mask = qpos[:, None] >= kpos[None, :]
            if padded:
                # Zero-padded tail slots would score s=0 and leak
                # exp(-m) weight into the softmax: mask them too.
                mask = mask & (idx < n)[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        elif padded:
            s = jnp.where((idx < n)[None, None, None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # Guard fully-masked rows: exp(-inf - (-inf)) -> stable max.
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pg = p.reshape(b, kvh, groups, sl, block)
        av = jnp.einsum("bkgqs,bskd->bkgqd", pg.astype(vcj.dtype), vcj,
                        preferred_element_type=jnp.float32)
        acc_new = acc * alpha + av.reshape(b, h, sl, d)
        return (m_new, l_new, acc_new), None

    (m, l, acc), _ = lax.scan(body, (m, l, acc),
                              jnp.arange(n_blocks))
    return m, l, acc


def _ring_local(q, k, v, *, axis_name: str, causal: bool,
                scale: float, axis_size: int):
    idx = lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    qpos = idx * sl + jnp.arange(sl)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(carry, step):
        m, l, acc, kc, vc = carry
        chunk_idx = (idx - step) % axis_size
        m, l, acc = _chunk_update(q, kc, vc, qpos, chunk_idx * sl,
                                  m, l, acc, causal=causal, scale=scale)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (m, l, acc, kc, vc), None

    m0 = jnp.full((b, h, sl, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, sl, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((b, h, sl, d), dtype=jnp.float32)
    body = jax.checkpoint(body, prevent_cse=False)
    (m, l, acc, _, _), _ = lax.scan(body, (m0, l0, acc0, k, v),
                                    jnp.arange(axis_size))
    out = acc / jnp.maximum(l, 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B, Sl, H, D)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh, sp_axis: str = mesh_lib.SP,
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Context-parallel causal attention.

    q: (B, S, H, D); k/v: (B, S, KVH, D), S sharded over `sp_axis`.
    Falls back to single-chunk local attention when the mesh has no sp axis.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    if sp_axis not in mesh.axis_names or mesh.shape[sp_axis] == 1:
        from skypilot_tpu.ops import attention as attention_ops
        return attention_ops.attention(q, k, v, causal=causal, scale=scale)
    axis_size = mesh.shape[sp_axis]
    spec = P(None, sp_axis, None, None)
    inner = jax.shard_map(
        functools.partial(_ring_local, axis_name=sp_axis, causal=causal,
                          scale=scale, axis_size=axis_size),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={sp_axis},
        check_vma=False,
    )
    return inner(q, k, v)


def ring_attention_from_context(q: jax.Array, k: jax.Array,
                                v: jax.Array) -> jax.Array:
    """Model-side entrypoint: resolve the mesh from the ambient context
    installed by the trainer (`mesh_lib.use_mesh`)."""
    pair = mesh_lib.current_mesh_rules()
    if pair is None:
        raise RuntimeError(
            "attention_impl='ring' requires an ambient mesh: wrap the "
            "forward call in `with mesh_lib.use_mesh(mesh, rules): ...` "
            "(make_train_step does this automatically).")
    mesh, _ = pair
    return ring_attention(q, k, v, mesh=mesh)
