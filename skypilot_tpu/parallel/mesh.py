"""Device-mesh construction and logical-axis sharding rules.

TPU-first design: parallelism is expressed as a `jax.sharding.Mesh` with
named axes plus a table of rules mapping *logical* tensor axes (batch, seq,
embed, heads, ...) onto mesh axes. XLA inserts the collectives; recipes pick
rules, not collectives.

The reference framework has no parallelism math of its own -- it only ships
the env-var scaffolding for torch DDP (reference:
sky/backends/cloud_vm_ray_backend.py:570-636). Here the mesh/rules layer IS
the native equivalent: dp/fsdp/tp/sp/ep/pp are all axis assignments over one
mesh.

Canonical mesh axes:
  dp    data parallel (pure replication of params, batch-sharded)
  fsdp  fully-sharded data parallel (batch- AND param-sharded)
  pp    pipeline stage axis
  tp    tensor (model) parallel axis; also hosts Megatron-style sequence
        parallelism of activations outside attention/mlp blocks
  sp    context/sequence parallelism for ring attention (long context)
  ep    expert parallel axis for MoE (may alias onto dp/fsdp via rules)
"""
from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Mapping, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisName = Union[str, Sequence[str], None]

DP = "dp"
FSDP = "fsdp"
PP = "pp"
TP = "tp"
SP = "sp"
EP = "ep"


def _resolve_axis_sizes(axes: Mapping[str, int], n: int,
                        what: str = "device count") -> dict:
    """Resolve one optional -1 axis against `n` and validate the product
    (shared by the flat and hybrid mesh builders)."""
    sizes = dict(axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"At most one axis may be -1, got {unknown}")
    known = math.prod(v for v in sizes.values() if v != -1)
    if unknown:
        if n % known:
            raise ValueError(
                f"{what} {n} not divisible by fixed axes {sizes}")
        sizes[unknown[0]] = n // known
    if math.prod(sizes.values()) != n:
        raise ValueError(
            f"Mesh axes {sizes} do not multiply to {what} {n}")
    return sizes


def make_mesh(axes: Mapping[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with the given named axis sizes.

    Axis sizes must multiply to the device count; an axis size of -1 is
    inferred. Axis order follows insertion order of `axes`, which also
    controls physical layout: put the fastest-communicating axis (tp/sp)
    last so it lands on adjacent devices (ICI neighbors on a real slice).
    """
    if devices is None:
        devices = jax.devices()
    sizes = _resolve_axis_sizes(axes, len(devices))
    dev_array = np.asarray(devices).reshape(tuple(sizes.values()))
    return Mesh(dev_array, tuple(sizes.keys()))


def make_multislice_mesh(ici_axes: Mapping[str, int], num_slices: int,
                         dcn_axis: str = DP,
                         devices: Optional[Sequence[jax.Device]] = None
                         ) -> Mesh:
    """Hybrid DCN x ICI mesh for multi-slice (pod-to-pod) training.

    The leading ``dcn_axis`` spans slices — collectives on it ride the
    data-center network — while ``ici_axes`` live inside one slice's ICI
    domain. Standard layout: data parallelism over DCN, fsdp/tp/sp over
    ICI (the "How to Scale Your Model" recipe; the env contract's
    MEGASCALE_* variables bring up the DCN transport).

    On real multislice hardware devices carry ``slice_index`` and are
    grouped by it so the leading axis truly crosses slices; on virtual
    or single-slice platforms devices are split evenly (same program,
    simulated topology).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if num_slices < 1 or n % num_slices:
        raise ValueError(
            f"{n} devices not divisible into {num_slices} slices")
    per_slice = n // num_slices
    sizes = _resolve_axis_sizes(ici_axes, per_slice,
                                "per-slice device count")
    if dcn_axis in sizes:
        raise ValueError(f"dcn axis {dcn_axis!r} also named in ici_axes")
    # Group by slice: real multislice devices expose slice_index, and
    # then the claimed num_slices MUST match the physical topology —
    # a silent mismatch would put the "DCN" axis inside a slice (and an
    # ICI axis across DCN), inverting the layout with no error.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if slice_ids != {None} and None not in slice_ids:
        counts: dict = {}
        for d in devices:
            counts[d.slice_index] = counts.get(d.slice_index, 0) + 1
        if len(counts) != num_slices or set(counts.values()) != {per_slice}:
            raise ValueError(
                f"devices span {len(counts)} physical slice(s) "
                f"{dict(sorted(counts.items()))}, but num_slices="
                f"{num_slices} x {per_slice} was requested — the DCN "
                f"axis would not align with slice boundaries.")
    # Intra-slice order follows PHYSICAL coordinates when the platform
    # exposes them: raw device ids need not walk the ICI torus, and an
    # id-ordered reshape can land a "fast" axis on non-adjacent chips
    # (correct results, degraded collective bandwidth). Virtual/CPU
    # devices have no coords and keep the id order.
    def _physical_key(d):
        coords = getattr(d, "coords", None)
        core = getattr(d, "core_on_chip", 0)
        if coords is not None:
            return (getattr(d, "slice_index", 0) or 0, tuple(coords),
                    core)
        return (getattr(d, "slice_index", 0) or 0, (),
                getattr(d, "id", 0))

    order = sorted(devices, key=_physical_key)
    dev_array = np.asarray(order).reshape(
        (num_slices,) + tuple(sizes.values()))
    return Mesh(dev_array, (dcn_axis,) + tuple(sizes.keys()))


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Logical-axis -> mesh-axis mapping.

    Any logical axis not listed resolves to None (replicated). A mesh axis
    named in a rule but absent from the mesh is dropped at resolution time,
    so one rule set works across meshes of different shapes (e.g. the same
    FSDP+TP rules on a ('dp','tp') mesh simply ignore 'fsdp').
    """
    rules: Mapping[str, AxisName]

    def resolve_axis(self, logical: Optional[str],
                     mesh: Mesh) -> AxisName:
        if logical is None:
            return None
        axis = self.rules.get(logical)
        if axis is None:
            return None
        names = (axis,) if isinstance(axis, str) else tuple(axis)
        present = tuple(a for a in names if a in mesh.axis_names)
        if not present:
            return None
        return present if len(present) > 1 else present[0]

    def spec(self, logical_axes: Sequence[Optional[str]],
             mesh: Mesh) -> P:
        resolved = []
        used: set = set()
        for la in logical_axes:
            axis = self.resolve_axis(la, mesh)
            # A mesh axis can shard at most one tensor dim; later dims fall
            # back to replicated rather than erroring (matches t5x behavior).
            flat = ((axis,) if isinstance(axis, str) else
                    tuple(axis) if axis else ())
            if any(a in used for a in flat):
                axis = None
                flat = ()
            used.update(flat)
            resolved.append(axis)
        while resolved and resolved[-1] is None:
            resolved.pop()
        return P(*resolved)

    def sharding(self, logical_axes: Sequence[Optional[str]],
                 mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes, mesh))


# Preset rule tables ---------------------------------------------------------

# Llama-class dense model, DP/FSDP/TP (+ megatron-SP via 'act_seq').
DEFAULT_RULES = ShardingRules(rules={
    # activations
    "batch": (DP, FSDP),
    "act_seq": SP,          # ring/context parallel shards the sequence
    "act_embed": None,
    "heads": TP,
    "kv_heads": TP,
    # params
    "embed": FSDP,
    "mlp": TP,
    "q_heads_x_dim": TP,
    "kv_heads_x_dim": TP,
    "vocab": TP,
    # MoE
    "expert": EP,
    # pipeline: leading stacked-layer axis of stage-stacked params
    "stage": PP,
    "layers": None,
})

# Pipelined runs shard the stored (L, ...) layer stack over pp so the
# in-jit reshape to (P, L/P, ...) is a purely local view change.
PIPELINE_RULES = ShardingRules(rules={**DEFAULT_RULES.rules, "layers": PP})


def resolve(rules: ShardingRules, mesh: Mesh,
            logical_axes: Sequence[Optional[str]]) -> NamedSharding:
    return rules.sharding(logical_axes, mesh)


def constrain(x: jax.Array, mesh: Mesh, rules: ShardingRules,
              logical_axes: Sequence[Optional[str]]) -> jax.Array:
    """with_sharding_constraint by logical axis names."""
    return jax.lax.with_sharding_constraint(
        x, rules.sharding(logical_axes, mesh))


_AMBIENT = threading.local()


class use_mesh:
    """Context manager installing (mesh, rules) as the ambient pair.

    Trainers enter this around model forward so ops that need the concrete
    mesh at trace time (ring attention's shard_map, MoE dispatch) can find
    it without threading it through every model signature. Thread-local so
    concurrent traces for different meshes don't cross-talk.
    """

    def __init__(self, mesh: Mesh, rules: ShardingRules):
        self.pair = (mesh, rules)

    def __enter__(self):
        if not hasattr(_AMBIENT, "stack"):
            _AMBIENT.stack = []
        _AMBIENT.stack.append(self.pair)
        return self.pair

    def __exit__(self, *exc):
        _AMBIENT.stack.pop()
        return False


def current_mesh_rules() -> Optional[tuple]:
    stack = getattr(_AMBIENT, "stack", None)
    return stack[-1] if stack else None


def tree_shardings(mesh: Mesh, rules: ShardingRules,
                   specs_tree: Any) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda spec: rules.sharding(spec, mesh),
        specs_tree,
        is_leaf=lambda s: isinstance(s, tuple) and all(
            a is None or isinstance(a, str) for a in s))
