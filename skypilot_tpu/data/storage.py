"""Storage: buckets synced or FUSE-mounted onto cluster hosts.

Reference analog: sky/data/storage.py (Storage:383, StorageMode COPY/MOUNT
:191, AbstractStore:196, GcsStore:1496, S3Store:1079). GCS-first (TPU VMs
live in GCP); S3 is supported as a COPY/MOUNT source via its CLI the same
way. A hermetic LocalStore (a directory posing as a bucket) makes the whole
path — upload, COPY fetch, MOUNT — testable without credentials, mirroring
how the local provider stands in for GCP slices.

All store methods that touch a cluster return *shell command strings*; the
backend runs them on each host via its command runner (reference pattern:
mounting_utils.get_mounting_script).
"""
from __future__ import annotations

import enum
import os
import shlex
import subprocess
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.data import mounting_utils


class StorageMode(enum.Enum):
    MOUNT = "MOUNT"
    COPY = "COPY"


def shell_path(p: str) -> str:
    """Quote a destination path for a generated shell command, keeping a
    leading ``~`` expandable (quoted tildes never expand; translated
    workdir mounts target ``~/stpu_workdir`` on every host)."""
    if p == "~":
        return '"$HOME"'
    if p.startswith("~/"):
        return '"$HOME"/' + shlex.quote(p[2:])
    return shlex.quote(p)


class StoreType(enum.Enum):
    GCS = "gcs"
    S3 = "s3"
    R2 = "r2"
    IBM = "ibm"
    AZURE = "azure"
    LOCAL = "local"


class AbstractStore:
    """One bucket in one object store."""

    def __init__(self, name: str, source: Optional[str] = None):
        self.name = name
        self.source = source

    # -- client-side ops ------------------------------------------------
    def upload(self) -> None:
        """Sync ``source`` (local path) into the bucket, creating it if
        needed. Runs on the client."""
        raise NotImplementedError

    def delete(self) -> None:
        raise NotImplementedError

    # -- cluster-side command generation --------------------------------
    def fetch_command(self, dst: str) -> str:
        """Shell: copy bucket contents into ``dst`` (COPY mode)."""
        raise NotImplementedError

    def mount_fuse_command(self, dst: str) -> str:
        """Shell: FUSE-mount the bucket at ``dst`` (MOUNT mode)."""
        raise NotImplementedError

    def _run(self, cmd: List[str]) -> None:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise exceptions.StorageUploadError(
                f"{' '.join(cmd)} failed: {proc.stderr.strip()}")


class GcsStore(AbstractStore):
    """GCS via gsutil/gcsfuse (reference: GcsStore:1496 +
    mounting_utils gcsfuse :60-90)."""

    def upload(self) -> None:
        if not self._bucket_exists():
            self._run(["gsutil", "mb", f"gs://{self.name}"])
        if self.source:
            src = os.path.abspath(os.path.expanduser(self.source))
            if os.path.isdir(src):
                self._run(["gsutil", "-m", "rsync", "-r", src,
                           f"gs://{self.name}"])
            else:
                self._run(["gsutil", "cp", src, f"gs://{self.name}/"])

    def _bucket_exists(self) -> bool:
        proc = subprocess.run(
            ["gsutil", "ls", "-b", f"gs://{self.name}"],
            capture_output=True, text=True)
        return proc.returncode == 0

    def delete(self) -> None:
        self._run(["gsutil", "-m", "rm", "-r", f"gs://{self.name}"])

    def fetch_command(self, dst: str) -> str:
        d = shell_path(dst)
        return (f"mkdir -p {d} && "
                f"gsutil -m rsync -r gs://{self.name} {d}")

    def mount_fuse_command(self, dst: str) -> str:
        return mounting_utils.get_gcs_mount_command(self.name, dst)


class S3Store(AbstractStore):
    """S3 via the aws CLI (reference: S3Store:1079). COPY works anywhere
    the CLI + credentials exist; MOUNT uses goofys like the reference.

    ``_aws_extra`` / ``_aws_extra_shell`` are the S3-compatibility seam:
    R2 (and any other S3-compatible endpoint) reuses every operation by
    appending its ``--endpoint-url``/``--profile`` flags.
    """

    _aws_extra: List[str] = []       # client-side argv suffix
    _aws_extra_shell: str = ""       # cluster-side shell suffix

    def upload(self) -> None:
        if not self._bucket_exists():
            self._run(["aws", "s3", "mb", f"s3://{self.name}"]
                      + self._aws_extra)
        if self.source:
            src = os.path.abspath(os.path.expanduser(self.source))
            if os.path.isdir(src):
                self._run(["aws", "s3", "sync", src,
                           f"s3://{self.name}"] + self._aws_extra)
            else:
                self._run(["aws", "s3", "cp", src,
                           f"s3://{self.name}/"] + self._aws_extra)

    def _bucket_exists(self) -> bool:
        proc = subprocess.run(
            ["aws", "s3api", "head-bucket", "--bucket", self.name]
            + self._aws_extra,
            capture_output=True, text=True)
        return proc.returncode == 0

    def delete(self) -> None:
        self._run(["aws", "s3", "rb", f"s3://{self.name}", "--force"]
                  + self._aws_extra)

    def fetch_command(self, dst: str) -> str:
        d = shell_path(dst)
        return (f"mkdir -p {d} && "
                f"aws s3 sync s3://{self.name} {d}"
                f"{self._aws_extra_shell}")

    def mount_fuse_command(self, dst: str) -> str:
        return mounting_utils.get_s3_mount_command(self.name, dst)


def r2_endpoint_url() -> str:
    """Cloudflare R2's S3-compatible endpoint for this account.

    Account id from $R2_ACCOUNT_ID or ~/.cloudflare/accountid (the
    reference's convention, sky/adaptors/cloudflare.py)."""
    acct = os.environ.get("R2_ACCOUNT_ID")
    if not acct:
        path = os.path.expanduser("~/.cloudflare/accountid")
        if os.path.exists(path):
            with open(path) as f:
                acct = f.read().strip()
    if not acct:
        raise exceptions.StorageUploadError(
            "Cloudflare R2 needs an account id: set $R2_ACCOUNT_ID or "
            "write ~/.cloudflare/accountid.")
    return f"https://{acct}.r2.cloudflarestorage.com"


class R2Store(S3Store):
    """Cloudflare R2 through its S3-compatible endpoint (reference:
    R2Store, sky/data/storage.py:2666 — R2 'uses s3:// as a prefix for
    various aws cli commands' with --endpoint-url + --profile r2).
    Credentials live in the aws CLI's ``r2`` profile."""

    def __init__(self, name: str, source: Optional[str] = None):
        super().__init__(name, source)
        endpoint = r2_endpoint_url()
        self._aws_extra = ["--endpoint-url", endpoint, "--profile", "r2"]
        # Quoted: the account id comes from a user file and must not be
        # able to smuggle shell into cluster-side commands.
        self._aws_extra_shell = (f" --endpoint-url "
                                 f"{shlex.quote(endpoint)} --profile r2")
        self.endpoint = endpoint

    def mount_fuse_command(self, dst: str) -> str:
        return mounting_utils.get_r2_mount_command(self.name, dst,
                                                   self.endpoint)


class IBMCosStore(S3Store):
    """IBM Cloud Object Storage through its S3-compatible endpoint
    (reference: IBMCosStore, sky/data/storage.py:3050 — rclone-based
    there; here the same aws-CLI seam as R2, with HMAC credentials in
    the aws ``ibm`` profile). Region from config ``ibm.cos_region``
    or $IBM_COS_REGION (default us-east, the reference's default)."""

    def __init__(self, name: str, source: Optional[str] = None,
                 region: Optional[str] = None):
        super().__init__(name, source)
        self.region = region or ibm_cos_region()
        endpoint = ibm_cos_endpoint(self.region)
        self._aws_extra = ["--endpoint-url", endpoint,
                           "--profile", "ibm"]
        self._aws_extra_shell = (f" --endpoint-url "
                                 f"{shlex.quote(endpoint)} "
                                 "--profile ibm")
        self.endpoint = endpoint

    def mount_fuse_command(self, dst: str) -> str:
        return mounting_utils.get_s3_compat_mount_command(
            self.name, dst, self.endpoint, "ibm")


def ibm_cos_region() -> str:
    from skypilot_tpu import config as config_lib
    return (os.environ.get("IBM_COS_REGION")
            or config_lib.get_nested(("ibm", "cos_region"), None)
            or "us-east")


def ibm_cos_endpoint(region: str) -> str:
    """The ONE place the IBM COS endpoint shape lives (COPY fetches and
    cos:// downloads must never drift apart)."""
    return f"https://s3.{region}.cloud-object-storage.appdomain.cloud"


class AzureBlobStore(AbstractStore):
    """Azure Blob Storage via the az CLI (reference: AzureBlobStore,
    sky/data/storage.py:1941). A "bucket" is a container; the storage
    account comes from config ``azure.storage_account`` (the az-CLI
    login supplies credentials). COPY fetches with `az storage blob
    download-batch`; MOUNT uses blobfuse2 like the reference. Cluster
    hosts need an Azure identity for either mode — sync `az login`
    state (~/.azure) via file_mounts, or use a managed identity.
    """

    @staticmethod
    def _account() -> str:
        from skypilot_tpu import config as config_lib
        account = config_lib.get_nested(("azure", "storage_account"),
                                        None)
        if not account:
            raise exceptions.StorageError(
                "Azure storage needs `azure.storage_account` in "
                "~/.stpu/config.yaml (containers live in an account).")
        return str(account)

    def upload(self) -> None:
        account = self._account()
        if not self._container_exists(account):
            self._run(["az", "storage", "container", "create",
                       "--name", self.name, "--account-name", account,
                       "--auth-mode", "login"])
        if self.source:
            src = os.path.abspath(os.path.expanduser(self.source))
            if os.path.isdir(src):
                self._run(["az", "storage", "blob", "upload-batch",
                           "--destination", self.name, "--source", src,
                           "--account-name", account,
                           "--auth-mode", "login", "--overwrite"])
            else:
                self._run(["az", "storage", "blob", "upload",
                           "--container-name", self.name,
                           "--file", src,
                           "--name", os.path.basename(src),
                           "--account-name", account,
                           "--auth-mode", "login", "--overwrite"])

    def _container_exists(self, account: str) -> bool:
        proc = subprocess.run(
            ["az", "storage", "container", "exists",
             "--name", self.name, "--account-name", account,
             "--auth-mode", "login", "-o", "tsv"],
            capture_output=True, text=True)
        return proc.returncode == 0 and "true" in proc.stdout.lower()

    def delete(self) -> None:
        self._run(["az", "storage", "container", "delete",
                   "--name", self.name,
                   "--account-name", self._account(),
                   "--auth-mode", "login"])

    def fetch_command(self, dst: str) -> str:
        d = shell_path(dst)
        return (f"{mounting_utils._INSTALL_AZ_CLI} && "
                f"mkdir -p {d} && "
                f"az storage blob download-batch --destination {d} "
                f"--source {self.name} "
                f"--account-name {shlex.quote(self._account())} "
                f"--auth-mode login")

    def mount_fuse_command(self, dst: str) -> str:
        return mounting_utils.get_az_mount_command(
            self.name, self._account(), dst)


class LocalStore(AbstractStore):
    """A directory posing as a bucket — hermetic tests' stand-in.

    The "bucket" lives under $STPU_HOME/buckets/<name>; COPY is a cp -r,
    MOUNT is a symlink (same visibility semantics as a FUSE mount for
    everything the framework itself does with mounts)."""

    def __init__(self, name: str, source: Optional[str] = None):
        super().__init__(name, source)
        import pathlib

        from skypilot_tpu.utils import paths
        # STPU_BUCKET_ROOT makes the fake bucket namespace GLOBAL across
        # the simulated topology (client + controller + task hosts all on
        # one machine with different STPU_HOMEs) — the local analog of
        # GCS buckets being visible from anywhere. controller_command
        # exports it so self-hosted controllers resolve client-uploaded
        # buckets.
        root = os.environ.get("STPU_BUCKET_ROOT")
        base = pathlib.Path(root) if root else paths.home() / "buckets"
        self.bucket_dir = base / name

    def upload(self) -> None:
        self.bucket_dir.mkdir(parents=True, exist_ok=True)
        if self.source:
            # Pure-python sync: the dev image may lack rsync.
            import shutil
            src = os.path.abspath(os.path.expanduser(self.source))
            if os.path.isdir(src):
                shutil.copytree(src, self.bucket_dir, dirs_exist_ok=True)
            elif os.path.exists(src):
                shutil.copy2(src, self.bucket_dir)
            else:
                raise exceptions.StorageError(
                    f"Storage source {src} does not exist.")

    def delete(self) -> None:
        import shutil
        shutil.rmtree(self.bucket_dir, ignore_errors=True)

    def fetch_command(self, dst: str) -> str:
        q = shlex.quote
        d = shell_path(dst)
        return (f"mkdir -p {d} && "
                f"cp -r {q(str(self.bucket_dir))}/. {d}/")

    def mount_fuse_command(self, dst: str) -> str:
        # rm -rf first: if dst already exists as a real directory,
        # `ln -s` would create the link *inside* it at the wrong path.
        # (On a symlink, rm -rf removes only the link.)
        q = shlex.quote
        d = shell_path(dst)
        return (f"mkdir -p $(dirname {d}) && rm -rf {d} && "
                f"ln -s {q(str(self.bucket_dir))} {d}")


_STORE_CLASSES = {
    StoreType.GCS: GcsStore,
    StoreType.S3: S3Store,
    StoreType.R2: R2Store,
    StoreType.IBM: IBMCosStore,
    StoreType.AZURE: AzureBlobStore,
    StoreType.LOCAL: LocalStore,
}


class Storage:
    """User-facing storage object: a named bucket + desired mode.

    YAML shape (reference schema):
        file_mounts:
          /data:
            name: my-bucket
            source: ./local_dir       # optional
            store: gcs                # gcs | s3 | r2 | ibm | azure | local
            mode: MOUNT               # MOUNT | COPY
            persistent: true
    """

    def __init__(self, name: Optional[str] = None,
                 source: Optional[str] = None,
                 store: Union[str, StoreType] = StoreType.GCS,
                 persistent: bool = True,
                 mode: Union[str, StorageMode] = StorageMode.MOUNT):
        if source is not None and not isinstance(source, str):
            # The YAML schema admits list sources for reference parity,
            # but multi-source buckets aren't implemented yet.
            raise exceptions.StorageError(
                f"Storage source must be a single path, got "
                f"{type(source).__name__}: {source!r}")
        if name is None:
            if source is None:
                raise exceptions.StorageError(
                    "Storage needs a bucket `name` (or a `source` to "
                    "derive one from).")
            name = os.path.basename(
                os.path.abspath(os.path.expanduser(source))).lower()
        self.name = name
        self.source = source
        self.store_type = (StoreType(store.lower())
                           if isinstance(store, str) else store)
        self.persistent = persistent
        self.mode = (StorageMode(mode.upper())
                     if isinstance(mode, str) else mode)
        self.store: AbstractStore = _STORE_CLASSES[self.store_type](
            name, source)

    # ------------------------------------------------------------------
    def sync(self) -> None:
        """Create the bucket and upload `source` (client side); records
        the storage object in the state DB."""
        self.store.upload()
        global_user_state.add_or_update_storage(
            self.name, {"store": self.store_type.value,
                        "source": self.source,
                        "persistent": self.persistent}, "READY")

    def delete(self) -> None:
        self.store.delete()
        global_user_state.remove_storage(self.name)

    def mount_command(self, dst: str) -> str:
        """The shell command a host runs to make this storage visible at
        ``dst`` (dispatches on mode)."""
        if self.mode == StorageMode.COPY:
            return self.store.fetch_command(dst)
        return self.store.mount_fuse_command(dst)

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> "Storage":
        return cls(
            name=config.get("name"),
            source=config.get("source"),
            store=config.get("store", "gcs"),
            persistent=config.get("persistent", True),
            mode=config.get("mode", "MOUNT"),
        )

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name,
                               "store": self.store_type.value,
                               "mode": self.mode.value}
        if self.source is not None:
            out["source"] = self.source
        if not self.persistent:
            out["persistent"] = False
        return out

    def __repr__(self) -> str:
        return (f"Storage({self.name}, {self.store_type.value}, "
                f"{self.mode.value})")
