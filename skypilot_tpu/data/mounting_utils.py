"""FUSE mount command generation for cluster hosts.

Reference analog: sky/data/mounting_utils.py:24-160 (goofys/gcsfuse/
blobfuse2/rclone install + mount scripts). GCS-first: TPU VMs mount GCS
via gcsfuse, exactly the mechanism the reference uses — no new native
code needed (SURVEY §2.5 FUSE row).
"""
from __future__ import annotations

import shlex

GCSFUSE_VERSION = "2.2.0"

_INSTALL_GCSFUSE = (
    "command -v gcsfuse >/dev/null || ("
    "ARCH=$(uname -m | grep -q aarch64 && echo arm64 || echo amd64) && "
    "curl -fsSL -o /tmp/gcsfuse.deb "
    "https://github.com/GoogleCloudPlatform/gcsfuse/releases/download/"
    f"v{GCSFUSE_VERSION}/gcsfuse_{GCSFUSE_VERSION}_$ARCH.deb && "
    "sudo dpkg -i /tmp/gcsfuse.deb)")

_INSTALL_GOOFYS = (
    "command -v goofys >/dev/null || ("
    "sudo curl -fsSL -o /usr/local/bin/goofys "
    "https://github.com/romange/goofys/releases/latest/download/goofys && "
    "sudo chmod +x /usr/local/bin/goofys)")


def get_gcs_mount_command(bucket: str, mount_path: str) -> str:
    """Install gcsfuse if needed and mount the bucket; idempotent."""
    q = shlex.quote
    return (f"{_INSTALL_GCSFUSE} && "
            f"mkdir -p {q(mount_path)} && "
            f"(mountpoint -q {q(mount_path)} || "
            f"gcsfuse --implicit-dirs {q(bucket)} {q(mount_path)})")


def get_s3_mount_command(bucket: str, mount_path: str) -> str:
    q = shlex.quote
    return (f"{_INSTALL_GOOFYS} && "
            f"mkdir -p {q(mount_path)} && "
            f"(mountpoint -q {q(mount_path)} || "
            f"goofys {q(bucket)} {q(mount_path)})")


def get_s3_compat_mount_command(bucket: str, mount_path: str,
                                endpoint_url: str,
                                profile: str) -> str:
    """goofys against any S3-compatible endpoint (R2, IBM COS) with the
    given aws credentials profile (reference:
    mounting_utils.get_r2_mount_cmd / get_cos_mount_cmd)."""
    q = shlex.quote
    return (f"{_INSTALL_GOOFYS} && "
            f"mkdir -p {q(mount_path)} && "
            f"(mountpoint -q {q(mount_path)} || "
            f"AWS_PROFILE={q(profile)} goofys "
            f"--endpoint {q(endpoint_url)} "
            f"{q(bucket)} {q(mount_path)})")


def get_r2_mount_command(bucket: str, mount_path: str,
                         endpoint_url: str) -> str:
    return get_s3_compat_mount_command(bucket, mount_path,
                                       endpoint_url, "r2")


BLOBFUSE2_VERSION = "2.3.2"

_INSTALL_BLOBFUSE2 = (
    "command -v blobfuse2 >/dev/null || ("
    "sudo curl -fsSL -o /tmp/blobfuse2.deb "
    "https://github.com/Azure/azure-storage-fuse/releases/download/"
    f"blobfuse2-{BLOBFUSE2_VERSION}/blobfuse2-{BLOBFUSE2_VERSION}"
    "-Ubuntu-22.04-x86-64.deb && "
    "sudo dpkg -i /tmp/blobfuse2.deb)")


# az CLI bootstrap for COPY-mode fetches on fresh cluster VMs.
_INSTALL_AZ_CLI = (
    "command -v az >/dev/null || "
    "(curl -sL https://aka.ms/InstallAzureCLIDeb | sudo bash)")


def get_az_mount_command(container: str, storage_account: str,
                         mount_path: str) -> str:
    """Install blobfuse2 if needed and mount the container; idempotent
    (reference: mounting_utils blobfuse2 branch,
    sky/data/mounting_utils.py:100-130). AZURE_STORAGE_AUTH_TYPE=azcli
    is blobfuse2's knob for az-CLI-login credentials (the host needs an
    Azure identity: `az login` state synced via file_mounts, or a
    managed identity)."""
    q = shlex.quote
    return (f"{_INSTALL_BLOBFUSE2} && "
            f"mkdir -p {q(mount_path)} /tmp/blobfuse2-cache && "
            f"(mountpoint -q {q(mount_path)} || "
            f"AZURE_STORAGE_AUTH_TYPE=azcli blobfuse2 mount "
            f"{q(mount_path)} --container-name {q(container)} "
            f"--account-name {q(storage_account)} "
            f"--tmp-path /tmp/blobfuse2-cache)")


def get_unmount_command(mount_path: str) -> str:
    q = shlex.quote
    return (f"mountpoint -q {q(mount_path)} && "
            f"fusermount -u {q(mount_path)} || true")
