"""Native training stack: sharded train step (trainer), multi-host
bring-up (distributed), and crash-consistent checkpointing
(checkpoint) — the workload half of the managed-jobs preemption
contract."""
