"""Crash-consistent training checkpoints: save/restore the full train
state so a preempted slice costs seconds of recomputed work, not hours.

The managed-jobs layer (jobs/controller.py) can relaunch a preempted
task cluster, but relaunching is worthless if training restarts from
step 0 — this module is the workload's half of the preemption contract.
Reference analog: the torchtune/orbax checkpoint-to-bucket pattern in
the reference's llm recipes (llama-3_1-finetuning/lora.yaml), made
native, stdlib+numpy-only, and crash-consistent:

  * **Atomicity.** Every durable write goes write-to-temp → flush →
    ``os.fsync`` → ``os.rename`` (+ directory fsync), so a checkpoint
    either exists completely or not at all. A SIGKILL mid-save leaves
    a ``.tmp`` the restore path never looks at.
  * **Integrity.** Each payload carries a sha256 in its manifest;
    ``restore_latest`` verifies it and *falls back* to the previous
    valid checkpoint when the newest one is torn or corrupt (a torn
    checkpoint must cost one save interval, never the run).
  * **Off the step path.** ``Checkpointer`` starts the D2H copy of
    every device leaf asynchronously, then hands the host arrays to a
    background writer thread — the training loop resumes while bytes
    hit disk. One save is in flight at a time; a newer save joins the
    previous first so on-disk order equals step order.
  * **Retention.** ``keep`` newest checkpoints survive; older pairs
    are GC'd after each successful save (never the one just written).

On-disk layout (one directory per run)::

    <dir>/ckpt-00000040.bin    raw concatenated leaf buffers
    <dir>/ckpt-00000040.json   manifest: step, sha256, leaf index
                               (key/dtype/shape/offset), user meta

The tree may be any nesting of dict / list / tuple (incl. NamedTuple
optimizer states) / dataclass with array-like leaves (jax or numpy
arrays, python scalars, None). Leaves round-trip as raw bytes —
restore is bit-identical, including bfloat16 — which is what makes
"resume == uninterrupted run" testable as byte equality of the final
checkpoint payloads.

Observability: ``stpu_ckpt_save_seconds`` / ``stpu_ckpt_restore_seconds``
histograms, ``stpu_ckpt_last_step`` gauge, per-outcome counters, and
``ckpt.save`` / ``ckpt.restore`` tracing spans. Chaos: the payload
write passes the ``ckpt.write`` fault-injection point *between* the
payload bytes and the rename, so an injected ``kill`` proves the
torn-file fallback (utils/fault_injection.py).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pathlib
import re
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.utils import fault_injection

# Env var the jobs controller stamps into every managed task (and every
# recovery relaunch) pointing at the job's stable checkpoint directory;
# recipes use it as the default --checkpoint-dir.
CKPT_DIR_ENV = "STPU_JOB_CKPT_DIR"

FORMAT_VERSION = 1
_PAYLOAD_FMT = "ckpt-{step:08d}.bin"
_MANIFEST_FMT = "ckpt-{step:08d}.json"
_MANIFEST_RE = re.compile(r"^ckpt-(\d{8})\.json$")
DEFAULT_KEEP = 3

_SAVE_SECONDS = metrics.histogram(
    "stpu_ckpt_save_seconds",
    "Wall time of one checkpoint save (D2H + serialize + fsync).",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120))
_RESTORE_SECONDS = metrics.histogram(
    "stpu_ckpt_restore_seconds",
    "Wall time of one checkpoint restore (read + verify + unflatten).",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120))
_SAVES = metrics.counter(
    "stpu_ckpt_saves_total", "Checkpoint save attempts.", ("outcome",))
_RESTORES = metrics.counter(
    "stpu_ckpt_restores_total", "Checkpoint restore attempts.",
    ("outcome",))
_SKIPPED = metrics.counter(
    "stpu_ckpt_restore_skipped_total",
    "Checkpoints skipped by restore_latest as torn/corrupt.")
_LAST_STEP = metrics.gauge(
    "stpu_ckpt_last_step", "Step of the newest durable checkpoint.")


class CheckpointError(Exception):
    """A checkpoint could not be saved or restored."""


# ------------------------------------------------------------ atomic IO
def _fsync_dir(path: pathlib.Path) -> None:
    """Durably record a rename in its directory (POSIX: the rename is
    only crash-durable once the directory entry itself is synced)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(path: os.PathLike, data: bytes) -> None:
    """THE durable-write primitive: temp + fsync + rename + dir fsync.

    Every state write in this module and jobs/state.py goes through
    here (enforced by the stpu-atomic rule of `stpu check`): a crash at any
    instant leaves either the old file or the new one, never a torn
    hybrid.
    """
    path = pathlib.Path(path)
    tmp = path.with_name(path.name + f".tmp-{os.getpid()}")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(path.parent)


# ------------------------------------------------------- tree flattening
def _is_leaf(obj: Any) -> bool:
    if obj is None:
        return True
    if isinstance(obj, (dict, list)):
        return False
    if isinstance(obj, tuple):  # incl. NamedTuple optimizer states
        return False
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return False
    return True


def flatten_tree(tree: Any, prefix: str = "") -> List[Tuple[str, Any]]:
    """Deterministic (key, leaf) list for dict/list/tuple/dataclass
    nests. Dict keys sort lexically; sequences keep positional order —
    the flattening order IS the payload byte order, so two identical
    states always produce byte-identical payloads."""
    if _is_leaf(tree):
        return [(prefix or ".", tree)]
    items: List[Tuple[str, Any]] = []
    if isinstance(tree, dict):
        for key in sorted(tree, key=str):
            sub = f"{prefix}/{key}" if prefix else str(key)
            items.extend(flatten_tree(tree[key], sub))
    elif dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        for field in sorted(dataclasses.fields(tree),
                            key=lambda f: f.name):
            sub = f"{prefix}/{field.name}" if prefix else field.name
            items.extend(flatten_tree(getattr(tree, field.name), sub))
    else:  # list / tuple / NamedTuple
        for i, child in enumerate(tree):
            sub = f"{prefix}/{i}" if prefix else str(i)
            items.extend(flatten_tree(child, sub))
    return items


def unflatten_like(like: Any, flat: Dict[str, Any],
                   prefix: str = "") -> Any:
    """Rebuild ``like``'s structure with leaves taken from ``flat``
    (keyed as flatten_tree produces). Missing keys raise — a structure
    mismatch must fail loudly, not half-restore."""
    if _is_leaf(like):
        key = prefix or "."
        if key not in flat:
            raise CheckpointError(
                f"checkpoint is missing leaf {key!r} required by the "
                "restore template (model/optimizer shape changed?)")
        return flat[key]
    if isinstance(like, dict):
        return type(like)(
            (key, unflatten_like(
                like[key], flat,
                f"{prefix}/{key}" if prefix else str(key)))
            for key in like)
    if dataclasses.is_dataclass(like) and not isinstance(like, type):
        kwargs = {
            field.name: unflatten_like(
                getattr(like, field.name), flat,
                f"{prefix}/{field.name}" if prefix else field.name)
            for field in dataclasses.fields(like)}
        return type(like)(**kwargs)
    children = [
        unflatten_like(child, flat, f"{prefix}/{i}" if prefix else str(i))
        for i, child in enumerate(like)]
    if isinstance(like, tuple) and hasattr(like, "_fields"):
        return type(like)(*children)  # NamedTuple (optax states)
    return type(like)(children)


class _FlatLeaves(list):
    """Pre-flattened ordered (key, leaf) pairs. Internal: lets the
    async Checkpointer hand _save_locked the ORIGINAL flattening order
    (re-flattening a {full-key: leaf} dict would sort sequence indices
    lexically — 'x/10' before 'x/2' — and silently change the payload
    byte order vs a sync save of the same tree)."""


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes  # bfloat16 & friends register via ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))
    except (TypeError, AttributeError, ImportError) as e:
        # CheckpointError so restore_latest's torn/corrupt fallback
        # absorbs it (an unknown dtype — newer writer, corrupt
        # manifest — must cost one checkpoint, never the run).
        raise CheckpointError(
            f"unresolvable leaf dtype {name!r}") from e


def _to_host(leaf: Any) -> Optional[np.ndarray]:
    if leaf is None:
        return None
    return np.asarray(leaf)


def _start_d2h(tree: Any) -> None:
    """Kick device-to-host copies for every jax leaf without blocking;
    the later np.asarray then finds the bytes already on their way."""
    for _key, leaf in flatten_tree(tree):
        start = getattr(leaf, "copy_to_host_async", None)
        if callable(start):
            try:
                start()
            except RuntimeError:
                pass  # deleted/donated buffer: asarray will raise


# ------------------------------------------------------------------ save
def save(ckpt_dir: os.PathLike, step: int, tree: Any,
         meta: Optional[Dict[str, Any]] = None,
         keep: Optional[int] = DEFAULT_KEEP) -> pathlib.Path:
    """Durably write ``tree`` as the step-``step`` checkpoint.

    Blocking (use ``Checkpointer`` for the async step-path variant).
    Returns the manifest path. ``meta`` is an arbitrary JSON-able dict
    stored in the manifest (never in the payload, so payload bytes stay
    comparable across runs).
    """
    ckpt_dir = pathlib.Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    t0 = time.perf_counter()
    with tracing.start_span("ckpt.save", kind="ckpt",
                            attrs={"step": int(step),
                                   "dir": str(ckpt_dir)}) as span:
        try:
            path = _save_locked(ckpt_dir, int(step), tree, meta, keep,
                                span)
        except BaseException:
            _SAVES.labels(outcome="error").inc()
            raise
    _SAVES.labels(outcome="ok").inc()
    _SAVE_SECONDS.observe(time.perf_counter() - t0)
    _LAST_STEP.set(int(step))
    return path


def _save_locked(ckpt_dir: pathlib.Path, step: int, tree: Any,
                 meta: Optional[Dict[str, Any]], keep: Optional[int],
                 span) -> pathlib.Path:
    leaves = tree if isinstance(tree, _FlatLeaves) else \
        flatten_tree(tree)
    entries: List[Dict[str, Any]] = []
    offset = 0

    payload = ckpt_dir / _PAYLOAD_FMT.format(step=step)
    manifest = ckpt_dir / _MANIFEST_FMT.format(step=step)
    sha = hashlib.sha256()
    tmp = payload.with_name(payload.name + f".tmp-{os.getpid()}")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)  # noqa: stpu-atomic streams chunks+checksum through the temp+fsync+rename protocol inline (atomic_write_bytes would double-buffer the payload)
    try:
        with os.fdopen(fd, "wb") as f:
            # Stream one leaf at a time: the serialized copy of a
            # multi-GB param set must never exist in full beside the
            # host arrays (peak extra memory is one leaf's bytes).
            for key, leaf in leaves:
                arr = _to_host(leaf)
                if arr is None:
                    entries.append({"key": key, "dtype": "none",
                                    "shape": [], "offset": offset,
                                    "nbytes": 0})
                    continue
                buf = np.ascontiguousarray(arr).tobytes()
                entries.append({"key": key, "dtype": arr.dtype.name,
                                "shape": list(arr.shape),
                                "offset": offset, "nbytes": len(buf)})
                f.write(buf)
                sha.update(buf)
                offset += len(buf)
            f.flush()
            # Chaos seam: fires between the payload bytes and the
            # rename — an injected `kill` here leaves exactly the torn
            # .tmp that restore_latest must skip.
            if fault_injection.ENABLED:
                fault_injection.fire("ckpt.write", step=step,
                                     path=str(payload))
            os.fsync(f.fileno())
        os.rename(tmp, payload)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(ckpt_dir)

    doc = {
        "version": FORMAT_VERSION,
        "step": step,
        "sha256": sha.hexdigest(),
        "payload": payload.name,
        "payload_bytes": offset,
        "created_at": time.time(),
        "leaves": entries,
        "meta": meta or {},
    }
    atomic_write_bytes(manifest, json.dumps(doc).encode())
    span.set_attr("bytes", offset)
    if keep is not None:
        gc(ckpt_dir, keep=keep)
    return manifest


# ------------------------------------------------------------- retention
def steps(ckpt_dir: os.PathLike) -> List[int]:
    """Steps with a manifest on disk, ascending (no integrity check)."""
    ckpt_dir = pathlib.Path(ckpt_dir)
    if not ckpt_dir.is_dir():
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = _MANIFEST_RE.match(name)
        if m:
            found.append(int(m.group(1)))
    return sorted(found)


def latest_step(ckpt_dir: os.PathLike) -> Optional[int]:
    """Newest manifest's step, or None. Cheap (no checksum): used by
    the jobs controller to report resume progress each poll."""
    found = steps(ckpt_dir)
    return found[-1] if found else None


def gc(ckpt_dir: os.PathLike, keep: int = DEFAULT_KEEP) -> List[int]:
    """Delete all but the ``keep`` newest checkpoints (manifest first,
    so a crash mid-GC never leaves a manifest pointing at a deleted
    payload). Also sweeps stray .tmp files from crashed saves. Returns
    the deleted steps."""
    ckpt_dir = pathlib.Path(ckpt_dir)
    doomed = steps(ckpt_dir)[:-keep] if keep > 0 else []
    for step in doomed:
        for fmt in (_MANIFEST_FMT, _PAYLOAD_FMT):
            try:
                os.unlink(ckpt_dir / fmt.format(step=step))
            except OSError:
                pass
    if ckpt_dir.is_dir():
        for name in os.listdir(ckpt_dir):
            if ".tmp-" in name:
                tmp = ckpt_dir / name
                try:
                    # Only sweep dead writers' leftovers: a live save's
                    # tmp is younger than a minute or owned by us.
                    # (mtime is a wall stamp from a possibly-dead
                    # process, so wall clock is the right comparison.)
                    if time.time() - tmp.stat().st_mtime > 60:  # noqa: stpu-wallclock mtime is a wall stamp from a possibly-dead process
                        os.unlink(tmp)
                except OSError:
                    pass
    return doomed


# --------------------------------------------------------------- restore
@dataclasses.dataclass
class Restored:
    step: int
    tree: Any                      # template shape, or flat {key: array}
    meta: Dict[str, Any]
    manifest_sha256: str           # payload sha — byte-parity handle


def _load_one(ckpt_dir: pathlib.Path, step: int) -> Restored:
    manifest = ckpt_dir / _MANIFEST_FMT.format(step=step)
    doc = json.loads(manifest.read_text())
    payload = ckpt_dir / doc["payload"]
    data = payload.read_bytes()
    if len(data) != doc["payload_bytes"]:
        raise CheckpointError(
            f"step {step}: payload is {len(data)} bytes, manifest "
            f"says {doc['payload_bytes']} (torn write)")
    digest = hashlib.sha256(data).hexdigest()
    if digest != doc["sha256"]:
        raise CheckpointError(
            f"step {step}: payload checksum mismatch (corrupt)")
    flat: Dict[str, Any] = {}
    for entry in doc["leaves"]:
        if entry["dtype"] == "none":
            flat[entry["key"]] = None
            continue
        dtype = _resolve_dtype(entry["dtype"])
        arr = np.frombuffer(
            data, dtype=dtype, count=entry["nbytes"] // dtype.itemsize,
            offset=entry["offset"]).reshape(entry["shape"])
        flat[entry["key"]] = arr
    return Restored(step=step, tree=flat, meta=doc.get("meta", {}),
                    manifest_sha256=doc["sha256"])


def restore_latest(ckpt_dir: os.PathLike,
                   like: Any = None) -> Optional[Restored]:
    """Load the newest VALID checkpoint, skipping torn/corrupt ones.

    Walks manifests newest-first; a missing payload, size mismatch,
    checksum mismatch, or unreadable manifest increments
    ``stpu_ckpt_restore_skipped_total`` and falls back to the previous
    step. Returns None when no valid checkpoint exists (fresh start).
    With ``like``, the result tree mirrors the template's structure;
    otherwise it is the flat {key: ndarray} mapping.
    """
    ckpt_dir = pathlib.Path(ckpt_dir)
    t0 = time.perf_counter()
    with tracing.start_span("ckpt.restore", kind="ckpt",
                            attrs={"dir": str(ckpt_dir)}) as span:
        for step in reversed(steps(ckpt_dir)):
            try:
                result = _load_one(ckpt_dir, step)
            except (OSError, ValueError, KeyError, json.JSONDecodeError,
                    CheckpointError) as e:
                _SKIPPED.inc()
                span.event("skipped", step=step, reason=str(e)[:200])
                from skypilot_tpu.observability import events
                events.emit("ckpt", str(ckpt_dir), "skip_torn",
                            step=step, reason=str(e)[:200])
                continue
            if like is not None:
                result = dataclasses.replace(
                    result, tree=unflatten_like(like, result.tree))
            span.set_attr("step", step)
            _RESTORES.labels(outcome="ok").inc()
            _RESTORE_SECONDS.observe(time.perf_counter() - t0)
            return result
    _RESTORES.labels(outcome="none").inc()
    return None


# ------------------------------------------------------------ async save
class Checkpointer:
    """Step-path-friendly saver: async D2H, background write, one save
    in flight. ``wait()`` (or close()) before exiting so the final save
    is durable; a failed background save re-raises on the next call."""

    def __init__(self, ckpt_dir: os.PathLike, keep: int = DEFAULT_KEEP,
                 async_save: bool = True):
        self.ckpt_dir = pathlib.Path(ckpt_dir)
        self.keep = keep
        self.async_save = async_save
        self.last_saved_step: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, step: int, tree: Any,
             meta: Optional[Dict[str, Any]] = None) -> None:
        self.wait()  # one in flight: on-disk order == step order
        if not self.async_save:
            save(self.ckpt_dir, step, tree, meta=meta, keep=self.keep)
            self.last_saved_step = step
            return
        _start_d2h(tree)
        # Materialize on THIS thread: the caller may donate/overwrite
        # device buffers on the very next step, so the host copy must
        # complete before save() returns. The transfers above already
        # overlapped; asarray mostly just wraps finished copies. The
        # ordered pairs keep the payload byte order identical to a
        # sync save of the same tree (the parity handle).
        host_flat = _FlatLeaves(
            (key, _to_host(leaf)) for key, leaf in flatten_tree(tree))

        def _write():
            try:
                save(self.ckpt_dir, step, host_flat, meta=meta,
                     keep=self.keep)
                self.last_saved_step = step
            except BaseException as e:  # noqa: BLE001 — re-raised on
                self._error = e         # the caller's next save/wait
        self._thread = threading.Thread(
            target=_write, name=f"ckpt-save-{step}", daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError(
                f"background checkpoint save failed: {err!r}") from err

    close = wait


# ------------------------------------------------------- SIGTERM grace
class GraceHandler:
    """Preemption-grace flag: the agent/gang layer forwards SIGTERM to
    the training process (agent/host_wrapper.py); installing this lets
    the loop finish the current step, save, and exit cleanly instead of
    dying mid-step. Exit with ``GRACE_EXIT_CODE`` so the gang records a
    non-success — the controller must still treat the task as
    interrupted (the slice is about to disappear), just with a fresh
    checkpoint to resume from.
    """

    GRACE_EXIT_CODE = 143  # 128 + SIGTERM, the conventional rc

    def __init__(self):
        self._event = threading.Event()
        self.signum: Optional[int] = None

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def _handle(self, signum, frame):
        del frame
        self.signum = signum
        self._event.set()

    @classmethod
    def install(cls, signals=(signal.SIGTERM,)) -> "GraceHandler":
        handler = cls()
        if threading.current_thread() is threading.main_thread():
            for sig in signals:
                signal.signal(sig, handler._handle)
        return handler
