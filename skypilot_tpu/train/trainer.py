"""Sharded training loop: pjit train step over a named mesh.

The reference delegates the training loop entirely to workloads (torch DDP /
torchtune invoked from task `run:` sections); here the trainer is a native
component recipes call into. One function, `make_train_step`, returns a
jit-compiled step with input/output shardings resolved from logical-axis
rules -- dp/fsdp/tp/sp all come from the rule table, XLA inserts the
collectives over ICI.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.parallel import mesh as mesh_lib

PyTree = Any


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    warmup_steps: int = 100
    total_steps: int = 10000
    max_grad_norm: float = 1.0
    b1: float = 0.9
    b2: float = 0.95
    # "adamw" (default) or "adafactor". Adafactor factors the second
    # moment into row/col statistics (O(rows+cols) instead of O(params))
    # — ~8 bytes/param of optimizer state become ~0, which is what lets
    # deep large-dim stacks (the 8B layer shape) fit a 16 GB chip.
    optimizer: str = "adamw"


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1))
    if cfg.optimizer == "adafactor":
        # optax.adafactor applies weight_decay_rate AFTER its
        # learning-rate scaling (unlike adamw, where decay is lr-scaled)
        # — passing cfg.weight_decay straight through would shrink every
        # weight by that fraction PER STEP. Rescale by the peak lr so
        # the effective decay matches adamw's lr*wd convention
        # (approximate: uses peak rather than the scheduled lr).
        wd = cfg.weight_decay * cfg.learning_rate
        return optax.chain(
            optax.clip_by_global_norm(cfg.max_grad_norm),
            optax.adafactor(schedule, weight_decay_rate=wd or None),
        )
    if cfg.optimizer != "adamw":
        raise ValueError(
            f"Unknown TrainConfig.optimizer {cfg.optimizer!r}; "
            "expected 'adamw' or 'adafactor'.")
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(schedule, b1=cfg.b1, b2=cfg.b2,
                    weight_decay=cfg.weight_decay),
    )


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross entropy in fp32. logits (B,S,V), targets (B,S)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None],
                               axis=-1).squeeze(-1)
    nll = logz - gold
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


# Sequence-chunk width for the fused head+CE loss. 1024 keeps the live
# fp32 chunk logits at batch*1024*vocab*4 bytes (~128MB for vocab 32k).
CE_CHUNK = 1024


def chunked_cross_entropy_loss(hidden: jax.Array, head: jax.Array,
                               targets: jax.Array,
                               mask: Optional[jax.Array] = None
                               ) -> jax.Array:
    """Next-token CE fused with the vocab projection, chunk-by-chunk.

    ``hidden`` (B,S,D) are FINAL-NORMED trunk states aligned with
    ``targets`` (B,S) (caller has already applied the next-token shift);
    ``head`` is (D,V). Each sequence chunk projects to fp32 logits,
    reduces to its NLL, and is rematerialized in the backward pass — the
    full (B,S,V) logits tensor never exists in HBM. At seq 8k x vocab
    32k that tensor is ~1GB fp32, and the write + multi-pass softmax
    reads + bwd round-trip through it cost more than the projection
    matmul itself (measured ~80ms of a 600ms step on v5e).
    """
    b, s, d = hidden.shape
    if mask is None:
        mask = jnp.ones((b, s), dtype=jnp.float32)
    mask = mask.astype(jnp.float32)
    chunk = min(CE_CHUNK, s)
    pad = (-s) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n = (s + pad) // chunk
    xs = (hidden.reshape(b, n, chunk, d).swapaxes(0, 1),
          targets.reshape(b, n, chunk).swapaxes(0, 1),
          mask.reshape(b, n, chunk).swapaxes(0, 1))

    @jax.checkpoint
    def _chunk(x_c, t_c, m_c):
        logits = jax.lax.dot_general(
            x_c, head.astype(x_c.dtype), (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, t_c[..., None],
                                   axis=-1).squeeze(-1)
        return (jnp.sum((logz - gold) * m_c), jnp.sum(m_c))

    def body(carry, inp):
        nll, cnt = _chunk(*inp)
        return (carry[0] + nll, carry[1] + cnt), None

    (nll, cnt), _ = jax.lax.scan(body, (jnp.float32(0.0),
                                        jnp.float32(0.0)), xs)
    return nll / jnp.maximum(cnt, 1.0)


class DelayedFetch:
    """One-step-delayed device→host fetch for loop telemetry.

    Fetching a step's loss with ``float(loss)`` / ``.item()`` syncs
    the host to the device INSIDE the hot loop — every step pays the
    full device latency just to log. The async alternative: hold the
    device handle for one iteration and fetch it only after the NEXT
    step has been dispatched, so the transfer overlaps device compute
    and the fetched value is already resident.

    Analyzer contract (``stpu-host-sync``): this class never touches
    the device itself — ``rotate`` just swaps handles. The CALLER
    performs the literal ``jax.device_get(prev)`` on the returned
    previous-step handle (the one blessed fetch form), keeping the
    sanctioned sync visible at the call site::

        prev = delayed.rotate(metrics["loss"])
        if prev is not None:
            host_loss = jax.device_get(prev)   # last step's, ready
            log(float(host_loss))

    ``drain()`` hands back the final outstanding handle after the
    loop so the last step's value is not lost.
    """

    def __init__(self) -> None:
        self._held: Any = None

    def rotate(self, new: Any) -> Any:
        """Store this step's device handle; return the previous one
        (None on the first call)."""
        prev = self._held
        self._held = new
        return prev

    def drain(self) -> Any:
        """Return the last outstanding handle (None if empty)."""
        prev = self._held
        self._held = None
        return prev


@dataclasses.dataclass
class TrainState:
    params: PyTree
    opt_state: PyTree
    step: jax.Array


jax.tree_util.register_dataclass(
    TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[])


def init_train_state(params: PyTree,
                     tx: optax.GradientTransformation) -> TrainState:
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), dtype=jnp.int32))


def state_shardings(mesh: Mesh, rules: mesh_lib.ShardingRules,
                    param_specs: PyTree, state_shape: TrainState
                    ) -> TrainState:
    """Shardings for a TrainState: params by their specs; opt_state leaves
    inherit the sharding of the param they track (matched by shape)."""
    p_shard = jax.tree.map(
        lambda spec: rules.sharding(spec, mesh), param_specs,
        is_leaf=lambda s: isinstance(s, tuple) and all(
            a is None or isinstance(a, str) for a in s))

    # Optimizer-state subtrees (adam mu/nu, ...) mirror the params treedef,
    # so an opt leaf's key path *ends with* the corresponding param's key
    # path. Match by longest path suffix — never by shape, which collides
    # for transposed weights of equal size (e.g. wq vs wo).
    def _path_key(path):
        return tuple(str(p) for p in path)

    param_paths = {}
    for path, sh in jax.tree_util.tree_flatten_with_path(p_shard)[0]:
        param_paths[_path_key(path)] = sh

    replicated = NamedSharding(mesh, P())

    def opt_leaf(path, leaf):
        key = _path_key(path)
        for start in range(len(key)):
            sh = param_paths.get(key[start:])
            if sh is not None and hasattr(leaf, "shape"):
                return sh
        return replicated

    o_shard = jax.tree_util.tree_map_with_path(opt_leaf,
                                               state_shape.opt_state)
    return TrainState(params=p_shard, opt_state=o_shard, step=replicated)


def make_train_step(
    forward_fn: Callable[..., jax.Array],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules: mesh_lib.ShardingRules,
    trunk_fn: Optional[Callable[..., jax.Array]] = None,
    head_fn: Optional[Callable[..., jax.Array]] = None,
    with_grad_norm: bool = True,
) -> Callable[[TrainState, Dict[str, jax.Array]],
              Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted step.

    forward_fn(params, tokens, constrain=...) -> logits. The constrain
    callback is bound to (mesh, rules) here so the model annotates
    activations without knowing the mesh.

    When ``trunk_fn`` (params, tokens, constrain=...) -> final hidden
    and ``head_fn`` (params) -> (dim, vocab) are given, the loss uses
    chunked_cross_entropy_loss — the vocab projection fuses into the CE
    chunk loop and full-sequence logits never materialize.
    """

    def constrain(x, logical_axes):
        return mesh_lib.constrain(x, mesh, rules, logical_axes)

    def loss_fn(params, batch):
        mask = batch.get("loss_mask")
        if trunk_fn is not None:
            with mesh_lib.use_mesh(mesh, rules):
                hidden = trunk_fn(params, batch["tokens"],
                                  constrain=constrain)
                ce = chunked_cross_entropy_loss(
                    hidden[:, :-1], head_fn(params),
                    batch["tokens"][:, 1:],
                    None if mask is None else mask[:, 1:])
            return ce, (ce, jnp.float32(0.0))
        with mesh_lib.use_mesh(mesh, rules):
            out = forward_fn(params, batch["tokens"], constrain=constrain)
        # forward_fn may return logits or (logits, aux_loss) — MoE models
        # surface their router load-balancing loss this way.
        logits, aux = out if isinstance(out, tuple) else (out, 0.0)
        ce = cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:],
                                None if mask is None else mask[:, 1:])
        return ce + aux, (ce, aux)

    batch_sharding = NamedSharding(mesh, rules.spec(("batch", None), mesh))

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        batch = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, batch_sharding),
            batch)
        (loss, (ce, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, batch)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": ce,
            "aux_loss": aux,
            "total_loss": loss,
            "step": state.step,
        }
        if with_grad_norm:
            # An EXTRA full sweep over every grad (clip_by_global_norm
            # already computes the same norm internally, inaccessibly);
            # benches that chase MFU turn it off.
            metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(params=new_params, opt_state=new_opt,
                          step=state.step + 1), metrics

    # Batch sharding is applied via the constraint above rather than
    # in_shardings so optional keys (loss_mask, ...) need no declaration.
    return jax.jit(step, donate_argnums=(0,))
