"""Local provider: fake multi-host clusters as directories + subprocesses.

The hermetic analog of a TPU pod slice: each "host" is a directory under
``$STPU_HOME/local_clusters/<cluster>/`` with its own $HOME, and commands
run as local subprocesses. This gives real end-to-end coverage of
provision → rsync → setup → gang exec → logs → autostop → teardown with
zero cloud credentials — the role Kind plays for the reference
(`sky local up`, sky/cli.py:5054) and the multi-host test harness
SURVEY.md §4 calls for.

Failure injection: config["fail_zones"] lists zones whose provisioning
raises (stockout simulation) so failover paths are testable.
"""
from __future__ import annotations

import json
import pathlib
import shutil
from typing import Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           ProvisionRecord)
from skypilot_tpu.utils import paths

PROVIDER_NAME = "local"


def _cluster_dir(cluster_name: str) -> pathlib.Path:
    return paths.home() / "local_clusters" / cluster_name


def _meta_path(cluster_name: str) -> pathlib.Path:
    return _cluster_dir(cluster_name) / "metadata.json"


def run_instances(region: Optional[str], zone: Optional[str],
                  cluster_name: str, config: dict) -> ProvisionRecord:
    if zone and zone in config.get("fail_zones", ()):
        raise exceptions.ProvisionError(
            f"local: simulated stockout in zone {zone}",
            blocklist_zone=zone)
    num_slices = int(config.get("num_slices", 1))
    hosts_per_slice = int(config.get("hosts_per_slice", 1))
    cdir = _cluster_dir(cluster_name)
    created = []
    instances = {}
    for s in range(num_slices):
        for h in range(hosts_per_slice):
            iid = f"{cluster_name}-s{s}-h{h}"
            host_dir = cdir / iid
            host_dir.mkdir(parents=True, exist_ok=True)
            created.append(iid)
            instances[iid] = {
                "instance_id": iid, "slice_id": f"slice-{s}",
                "host_index": h, "host_dir": str(host_dir),
                "status": "running",
            }
    meta = {
        "cluster_name": cluster_name, "region": region, "zone": zone,
        "num_slices": num_slices, "hosts_per_slice": hosts_per_slice,
        "instances": instances,
        "head_instance_id": f"{cluster_name}-s0-h0",
    }
    _meta_path(cluster_name).write_text(json.dumps(meta, indent=2))
    return ProvisionRecord(
        provider_name=PROVIDER_NAME, region=region, zone=zone,
        cluster_name=cluster_name,
        head_instance_id=meta["head_instance_id"],
        created_instance_ids=created)


def wait_instances(region, cluster_name: str, state: str,
                   provider_config: dict) -> None:
    del region, state  # local instances are synchronous


def query_instances(cluster_name: str,
                    provider_config: dict) -> Dict[str, str]:
    del provider_config
    meta_path = _meta_path(cluster_name)
    if not meta_path.exists():
        return {}
    meta = json.loads(meta_path.read_text())
    return {iid: info["status"]
            for iid, info in meta["instances"].items()}


def get_cluster_info(region, cluster_name: str,
                     provider_config: dict) -> ClusterInfo:
    meta = json.loads(_meta_path(cluster_name).read_text())
    instances = {}
    for iid, info in meta["instances"].items():
        instances[iid] = InstanceInfo(
            instance_id=iid, internal_ip="127.0.0.1", external_ip=None,
            slice_id=info["slice_id"], host_index=info["host_index"],
            tags={"host_dir": info["host_dir"]})
    return ClusterInfo(
        cluster_name=cluster_name, provider_name=PROVIDER_NAME,
        region=meta.get("region"), zone=meta.get("zone"),
        instances=instances,
        head_instance_id=meta["head_instance_id"],
        provider_config=provider_config or {})


def simulate_preemption(cluster_name: str) -> None:
    """Test hook: mark all instances preempted, the way a spot TPU slice
    dies — the provider's status flips but nothing on-host announces it
    (reference: spot preemption only visible via cloud API,
    sky/jobs/controller.py:236-262)."""
    meta_path = _meta_path(cluster_name)
    if not meta_path.exists():
        return
    meta = json.loads(meta_path.read_text())
    for info in meta["instances"].values():
        info["status"] = "preempted"
    meta_path.write_text(json.dumps(meta, indent=2))


def stop_instances(cluster_name: str, provider_config: dict) -> None:
    del provider_config
    meta_path = _meta_path(cluster_name)
    if not meta_path.exists():
        return
    meta = json.loads(meta_path.read_text())
    for info in meta["instances"].values():
        info["status"] = "stopped"
    meta_path.write_text(json.dumps(meta, indent=2))


def terminate_instances(cluster_name: str, provider_config: dict) -> None:
    del provider_config
    cdir = _cluster_dir(cluster_name)
    if cdir.exists():
        shutil.rmtree(cdir)


def open_ports(cluster_name: str, ports, provider_config: dict) -> None:
    """Local hosts are directories on this machine: every port a job
    binds is already reachable on localhost. Validate the spec (same
    grammar as the real providers) and do nothing."""
    del cluster_name, provider_config
    from skypilot_tpu.provision.common import parse_port_ranges
    parse_port_ranges(ports)


def cleanup_ports(cluster_name: str, ports, provider_config: dict) -> None:
    del cluster_name, ports, provider_config
