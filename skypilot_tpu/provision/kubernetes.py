"""Kubernetes provisioner: pod-per-slice-host behind the provision SPI.

Reference analog: sky/provision/kubernetes/instance.py (815) +
kubernetes_utils.py (1,654) — pod-based clusters with SSH-free exec.
TPU-native differences:

* An "instance" is a POD standing in for one slice host. A cluster of
  ``num_slices`` slices x ``hosts_per_slice`` hosts becomes that many
  pods, labeled ``stpu-cluster``/``stpu-slice``/``stpu-host-index`` —
  the same slice-atomic gang boundary the GCP provisioner gets from
  queuedResources. TPU chips are requested via the ``google.com/tpu``
  extended resource plus the GKE node selectors
  (``cloud.google.com/gke-tpu-accelerator``/``-topology``) so the
  scheduler lands each pod on a host of the right slice type.
* Exec is SSH-free from the CLIENT: commands reach pods through
  ``kubectl exec`` (utils/command_runner.KubernetesCommandRunner).
  INTRA-cluster (head pod -> worker pods, for the head-resident gang
  driver) is ALSO SSH-free since r4: worker pods run the
  token-authenticated exec agent (agent/exec_server.py), so any image
  with python3 gangs multi-host — unlike the reference's kubernetes
  pods, whose bootstrap installs openssh-server.
* Pods cannot be stopped, only deleted: `stop` raises NotSupportedError
  (clouds/kubernetes.py declares the capability), exactly like TPU pod
  slices.

All kubectl traffic goes through :func:`kubectl` so hermetic tests can
monkeypatch a fake API server (the provision/gcp.py `rest` discipline).
"""
from __future__ import annotations

import json
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           ProvisionRecord)

PROVIDER_NAME = "kubernetes"

_CLUSTER_LABEL = "stpu-cluster"
_SLICE_LABEL = "stpu-slice"
_HOST_INDEX_LABEL = "stpu-host-index"

_POLL_INTERVAL_SECONDS = 2
_CREATE_TIMEOUT_SECONDS = 600

# Pod phase -> SPI status strings (core._refresh_one contract).
_PHASE_MAP = {
    "Running": "running",
    "Pending": "pending",
    "Succeeded": "terminated",
    "Failed": "terminated",
    "Unknown": "terminated",
}

_DEFAULT_IMAGE = "python:3.11-slim"


def kubectl(args: List[str], input_obj: Optional[dict] = None,
            namespace: Optional[str] = None) -> Dict[str, Any]:
    """One kubectl invocation returning parsed JSON ({} when the command
    produces none). Tests monkeypatch this symbol with a fake cluster;
    everything above it is then hermetically testable."""
    cmd = ["kubectl"]
    if namespace:
        cmd += ["-n", namespace]
    cmd += args
    kwargs: Dict[str, Any] = {}
    if input_obj is not None:
        cmd += ["-f", "-"]
        kwargs["input"] = json.dumps(input_obj)
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=120, **kwargs)
    if proc.returncode != 0:
        raise exceptions.ProvisionError(
            f"kubectl {' '.join(args)} failed: "
            f"{proc.stderr.strip()[:500]}")
    out = proc.stdout.strip()
    if not out:
        return {}
    try:
        return json.loads(out)
    except ValueError:
        return {"raw": out}


def _namespace(config: dict) -> str:
    return config.get("namespace") or "default"


def _pod_name(cluster_name: str, slice_i: int, host_i: int) -> str:
    return f"{cluster_name}-s{slice_i}-h{host_i}"


def _pod_manifest(cluster_name: str, slice_i: int, host_i: int,
                  config: dict) -> dict:
    chips = int(config.get("chips_per_host") or 0)
    accelerator = config.get("accelerator")
    container: Dict[str, Any] = {
        "name": "stpu-host",
        "image": config.get("image") or _DEFAULT_IMAGE,
        # Long-running host process; work arrives via kubectl exec and
        # the head-resident gang driver.
        "command": ["/bin/sh", "-c", "sleep infinity"],
    }
    if chips:
        container["resources"] = {
            "limits": {"google.com/tpu": str(chips)},
            "requests": {"google.com/tpu": str(chips)},
        }
    spec: Dict[str, Any] = {
        "restartPolicy": "Never",
        "containers": [container],
    }
    if accelerator and config.get("gke_accelerator_type"):
        # GKE TPU scheduling contract: the node pool advertises the
        # slice type/topology; pods select it.
        spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator":
                config["gke_accelerator_type"],
            **({"cloud.google.com/gke-tpu-topology":
                config["gke_tpu_topology"]}
               if config.get("gke_tpu_topology") else {}),
        }
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": _pod_name(cluster_name, slice_i, host_i),
            "labels": {
                _CLUSTER_LABEL: cluster_name,
                _SLICE_LABEL: f"slice-{slice_i}",
                _HOST_INDEX_LABEL: str(host_i),
                **(config.get("labels") or {}),
            },
        },
        "spec": spec,
    }


def _list_pods(cluster_name: str, namespace: str) -> List[dict]:
    out = kubectl(["get", "pods", "-l",
                   f"{_CLUSTER_LABEL}={cluster_name}", "-o", "json"],
                  namespace=namespace)
    return out.get("items", [])


# ------------------------------------------------------------------- SPI
def run_instances(region, zone, cluster_name: str,
                  config: dict) -> ProvisionRecord:
    """Create (or adopt) the cluster's pods. Slice-atomic semantics: a
    creation failure deletes everything created this call before
    raising, so a half-scheduled slice never lingers."""
    del region, zone  # a kubernetes cluster is its own placement
    namespace = _namespace(config)
    num_slices = int(config.get("num_slices") or 1)
    hosts = int(config.get("hosts_per_slice") or 1)
    # Multi-host gangs need NO sshd image: worker pods run the
    # token-authenticated exec agent (agent/exec_server.py) and the
    # head's gang driver connects over the pod network. python3 is the
    # only requirement — and the wheel install needs it on every pod
    # anyway.

    existing = {}
    for p in _list_pods(cluster_name, namespace):
        existing[p["metadata"]["name"]] = \
            (p.get("status") or {}).get("phase", "")
    # A pod already in Failed/Succeeded will never become Ready again:
    # adopting it as "resumed" makes a provision retry stall the full
    # wait_instances timeout before failing AGAIN (ADVICE r3 #4).
    # Delete-and-recreate instead.
    dead = [n for n, phase in existing.items()
            if phase in ("Failed", "Succeeded")]
    for name in dead:
        kubectl(["delete", "pod", name, "--ignore-not-found"],
                namespace=namespace)
        existing.pop(name, None)
    created: List[str] = []
    try:
        for s in range(num_slices):
            for h in range(hosts):
                name = _pod_name(cluster_name, s, h)
                if name in existing:
                    continue
                kubectl(["create", "-o", "json"],
                        input_obj=_pod_manifest(cluster_name, s, h,
                                                config),
                        namespace=namespace)
                created.append(name)
    except exceptions.ProvisionError as e:
        for name in created:
            try:
                kubectl(["delete", "pod", name, "--ignore-not-found"],
                        namespace=namespace)
            except exceptions.ProvisionError:
                pass
        msg = str(e)
        # Namespace quota exhaustion is this cluster's stockout: a
        # retry in the same "zone" cannot help until quota frees.
        raise exceptions.ProvisionError(
            msg, retryable_in_zone="exceeded quota" not in msg.lower())
    return ProvisionRecord(
        provider_name=PROVIDER_NAME, region=None, zone=None,
        cluster_name=cluster_name,
        head_instance_id=_pod_name(cluster_name, 0, 0),
        created_instance_ids=created,
        resumed_instance_ids=sorted(existing))


def wait_instances(region, cluster_name: str, state: str,
                   provider_config: dict) -> None:
    del region
    namespace = _namespace(provider_config)
    deadline = time.time() + _CREATE_TIMEOUT_SECONDS
    while time.time() < deadline:
        pods = _list_pods(cluster_name, namespace)
        phases = [p.get("status", {}).get("phase", "Unknown")
                  for p in pods]
        if pods and all(
                _PHASE_MAP.get(ph, "terminated") == state
                for ph in phases):
            return
        if any(ph == "Failed" for ph in phases):
            failed = [p["metadata"]["name"] for p in pods
                      if p.get("status", {}).get("phase") == "Failed"]
            raise exceptions.ProvisionError(
                f"pod(s) failed during scheduling: {failed}",
                retryable_in_zone=True)
        time.sleep(_POLL_INTERVAL_SECONDS)
    raise exceptions.ProvisionError(
        f"pods of {cluster_name} not {state} after "
        f"{_CREATE_TIMEOUT_SECONDS}s", retryable_in_zone=True)


def query_instances(cluster_name: str,
                    provider_config: dict) -> Dict[str, str]:
    pods = _list_pods(cluster_name, _namespace(provider_config))
    return {
        p["metadata"]["name"]: _PHASE_MAP.get(
            p.get("status", {}).get("phase", "Unknown"), "terminated")
        for p in pods
    }


def get_cluster_info(region, cluster_name: str,
                     provider_config: dict) -> ClusterInfo:
    del region
    namespace = _namespace(provider_config)
    instances: Dict[str, InstanceInfo] = {}
    for pod in _list_pods(cluster_name, namespace):
        meta = pod["metadata"]
        labels = meta.get("labels", {})
        instances[meta["name"]] = InstanceInfo(
            instance_id=meta["name"],
            internal_ip=pod.get("status", {}).get("podIP", ""),
            external_ip=None,
            slice_id=labels.get(_SLICE_LABEL, "slice-0"),
            host_index=int(labels.get(_HOST_INDEX_LABEL, 0)),
            tags={"namespace": namespace},
        )
    head = _pod_name(cluster_name, 0, 0)
    return ClusterInfo(
        cluster_name=cluster_name, provider_name=PROVIDER_NAME,
        region=None, zone=None, instances=instances,
        head_instance_id=head if head in instances else None,
        ssh_user=provider_config.get("ssh_user", "root"),
        ssh_key_path=None,
        provider_config=dict(provider_config))


def stop_instances(cluster_name: str, provider_config: dict) -> None:
    raise exceptions.NotSupportedError(
        "kubernetes pods cannot be stopped, only terminated "
        "(`stpu down`); pod state does not survive deletion.")


def terminate_instances(cluster_name: str, provider_config: dict) -> None:
    kubectl(["delete", "pods", "-l", f"{_CLUSTER_LABEL}={cluster_name}",
             "--ignore-not-found", "--wait=false"],
            namespace=_namespace(provider_config))


# ------------------------------------------------------------------ ports
# Kubernetes analog of the GCP firewall ops (provision SPI
# open_ports/cleanup_ports; reference declares them in
# sky/provision/__init__.py:122,133 and implements the k8s side with a
# NodePort/LoadBalancer service in
# sky/provision/kubernetes/network.py). One NodePort Service per cluster
# exposes the requested ports on the HEAD pod (slice 0 / host 0 — where
# the serve LB and user servers run under the head-resident runtime).


def _ports_service_name(cluster_name: str) -> str:
    return f"{cluster_name}-ports"


def _expand_ports(ports: List[str]) -> List[int]:
    """"8080" / "30000-30010" specs → concrete port list (shared
    grammar: provision.common.parse_port_ranges). Services have no
    range syntax, so ranges expand; bounded so a careless "1-65535"
    cannot create a 65k-entry Service."""
    from skypilot_tpu.provision.common import parse_port_ranges
    out: List[int] = []
    for lo, hi in parse_port_ranges(ports):
        if hi - lo + 1 > 200:
            raise exceptions.ProvisionError(
                f"port range {lo}-{hi} too wide for a kubernetes "
                "Service (max 200 ports); open individual ports "
                "instead")
        out.extend(range(lo, hi + 1))
    return sorted(set(out))


# kube-apiserver's default --service-node-port-range: only ports inside
# it can be pinned as the Service's nodePort, making node_ip:port work
# directly (the serve LB range is chosen inside it for exactly this).
# Ports outside it get a cluster-assigned nodePort; in-cluster access is
# via ClusterIP:port either way.
_NODE_PORT_RANGE = (30000, 32767)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: dict) -> None:
    """Ensure a NodePort Service exposing ``ports`` on the head pod.
    Idempotent via `kubectl apply`; re-opening with new ports merges
    with the existing Service's (the serve LB range must survive a
    later launch-with-ports on the same cluster)."""
    if not ports:
        return
    namespace = _namespace(provider_config)
    name = _ports_service_name(cluster_name)
    want = set(_expand_ports(ports))
    try:
        existing = kubectl(["get", "service", name, "-o", "json"],
                           namespace=namespace)
        for entry in (existing.get("spec") or {}).get("ports", []):
            want.add(int(entry["port"]))
    except exceptions.ProvisionError as e:
        # Only a genuinely-absent Service may proceed to create: a
        # transient API error must NOT read as not-found, or the apply
        # below would clobber already-open ports (e.g. the serve LB
        # range) with just the new ones.
        if "not found" not in str(e).lower():
            raise
    manifest = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "labels": {_CLUSTER_LABEL: cluster_name},
        },
        "spec": {
            "type": "NodePort",
            "selector": {
                _CLUSTER_LABEL: cluster_name,
                _SLICE_LABEL: "slice-0",
                _HOST_INDEX_LABEL: "0",
            },
            "ports": [dict({"name": f"p{p}", "port": p,
                            "targetPort": p, "protocol": "TCP"},
                           # Pin nodePort=port when allowed so
                           # node_ip:port is reachable as requested;
                           # outside the apiserver's NodePort range the
                           # cluster assigns one (ClusterIP:port still
                           # serves in-cluster traffic).
                           **({"nodePort": p}
                              if _NODE_PORT_RANGE[0] <= p
                              <= _NODE_PORT_RANGE[1] else {}))
                      for p in sorted(want)],
        },
    }
    kubectl(["apply"], input_obj=manifest, namespace=namespace)


def cleanup_ports(cluster_name: str, ports: List[str],
                  provider_config: dict) -> None:
    del ports  # whole-service cleanup, matching the SPI contract
    kubectl(["delete", "service", _ports_service_name(cluster_name),
             "--ignore-not-found", "--wait=false"],
            namespace=_namespace(provider_config))


def query_ports(cluster_name: str, ports: List[str], head_ip,
                provider_config: dict) -> Dict[int, str]:
    """Resolve reachable endpoints for the cluster's ports Service
    (reference: sky/provision/kubernetes/network.py query_ports).

    A NodePort Service maps each requested port to a node port — the
    SAME number when the request was inside the apiserver's NodePort
    range (open_ports pins it), a cluster-assigned one otherwise. The
    node address comes from the first node's ExternalIP (InternalIP
    fallback); ``head_ip`` (the head pod IP) is the last resort and
    only reachable in-cluster.
    """
    namespace = _namespace(provider_config)
    want = set(_expand_ports(ports))
    try:
        svc = kubectl(["get", "service",
                       _ports_service_name(cluster_name), "-o", "json"],
                      namespace=namespace)
    except exceptions.ProvisionError as e:
        # Only a genuinely-absent Service reads as "no endpoints"; a
        # transient/auth apiserver error must surface, not print an
        # empty table (same discrimination as open_ports above).
        if "not found" in str(e).lower():
            return {}
        raise
    node_addr = None
    try:
        nodes = kubectl(["get", "nodes", "-o", "json"]).get("items", [])
        addrs = {a["type"]: a["address"]
                 for a in (nodes[0]["status"]["addresses"] if nodes
                           else [])}
        node_addr = addrs.get("ExternalIP") or addrs.get("InternalIP")
    except (exceptions.ProvisionError, KeyError, IndexError):
        pass
    out: Dict[int, str] = {}
    for entry in (svc.get("spec") or {}).get("ports", []):
        port = int(entry["port"])
        if port not in want:
            continue
        if node_addr is not None:
            out[port] = f"{node_addr}:{entry.get('nodePort', port)}"
        else:
            # No node address visible (nodes RBAC-forbidden): fall back
            # to the head POD, which listens on the TARGET port — the
            # nodePort is only bound on nodes. In-cluster reachability
            # only.
            out[port] = f"{head_ip}:{port}"
    return out
