"""GCP TPU provisioner: real slices via the Cloud TPU REST API.

Implements the provision SPI (skypilot_tpu/provision/__init__.py) against
``tpu.googleapis.com``. Reference analog:
sky/provision/gcp/instance_utils.py:1185-1620 (GCPTPUVMInstance — node API
create/stop/delete, state machine READY/CREATING/..., label filtering) and
the failover error taxonomy in sky/backends/cloud_vm_ray_backend.py:997-1051
(quota → region blocklist, stockout/code 8 → zone blocklist, preempted
during creation/code 3, insufficient reservation/code 9).

TPU-native differences from the reference:

* **Multi-host slices go through the v2 ``queuedResources`` API**, which is
  the only way GCP guarantees slice-atomic allocation of v5e/v5p/v6e pods —
  all hosts come up together or the request fails as a unit (the hardware
  analog of the reference's STRICT_SPREAD placement group). Single-host
  slices use the plain node API, like the reference.
* An "instance" in the SPI is a *slice host* (TPU VM worker). One node
  resource fans out to ``hosts_per_slice`` InstanceInfos via its
  ``networkEndpoints`` — rank order is the endpoint order, which libtpu
  also uses for the ICI topology.

All HTTP goes through :func:`rest` so hermetic tests can monkeypatch a fake
TPU service; nothing below this module imports a cloud SDK (the reference's
lazy-adaptor discipline, sky/adaptors/common.py:7).
"""
from __future__ import annotations

import functools
import hashlib
import json
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           ProvisionRecord)

PROVIDER_NAME = "gcp"
TPU_API_BASE = "https://tpu.googleapis.com/v2"
COMPUTE_API_BASE = "https://compute.googleapis.com/compute/v1"

# Node lifecycle states (Cloud TPU v2 API) → SPI status strings consumed by
# core._refresh_one / jobs.controller / serve.replica_managers.
_PENDING_STATES = ("CREATING", "STARTING", "RESTARTING", "REPAIRING")
_STATE_MAP = {
    "READY": "running",
    "CREATING": "pending",
    "STARTING": "pending",
    "RESTARTING": "pending",
    "REPAIRING": "pending",
    "STOPPING": "stopping",
    "STOPPED": "stopped",
    "SUSPENDING": "stopping",
    "SUSPENDED": "stopped",
    "PREEMPTED": "preempted",
    "TERMINATED": "terminated",
    "HIDING": "terminated",
    "HIDDEN": "terminated",
    "DELETING": "terminated",
}

_CLUSTER_LABEL = "stpu-cluster"
_SLICE_LABEL = "stpu-slice"

_POLL_INTERVAL_SECONDS = 5
_CREATE_TIMEOUT_SECONDS = 900


class GcpApiError(exceptions.SkyTpuError):
    """An HTTP error from the TPU API, with the parsed error body."""

    def __init__(self, status: int, body: Dict[str, Any], context: str = ""):
        self.status = status
        self.body = body or {}
        err = self.body.get("error", {})
        self.code = err.get("status") or err.get("code")
        self.message = err.get("message", "")
        super().__init__(
            f"TPU API error {status} ({self.code}) {context}: "
            f"{self.message}")


# ---------------------------------------------------------------- transport
@functools.lru_cache(maxsize=1)
def _gcloud_project() -> str:
    proc = subprocess.run(
        ["gcloud", "config", "get-value", "project"],
        capture_output=True, text=True, timeout=30, check=False)
    project = proc.stdout.strip()
    if proc.returncode != 0 or not project or project == "(unset)":
        raise exceptions.NoCloudAccessError(
            "No GCP project configured (gcloud config set project ...).")
    return project


_token_cache: List[Tuple[float, str]] = []


def _access_token() -> str:
    now = time.time()
    if _token_cache and _token_cache[0][0] > now:
        return _token_cache[0][1]
    proc = subprocess.run(
        ["gcloud", "auth", "print-access-token"],
        capture_output=True, text=True, timeout=30, check=False)
    token = proc.stdout.strip()
    if proc.returncode != 0 or not token:
        raise exceptions.NoCloudAccessError(
            "Could not obtain a GCP access token "
            "(run `gcloud auth login`).")
    _token_cache[:] = [(now + 240, token)]  # tokens live ~1h; refresh early
    return token


def rest(method: str, path: str, body: Optional[dict] = None,
         params: Optional[dict] = None) -> Dict[str, Any]:
    """One TPU-API call. ``path`` is relative to the API base
    (``projects/...``). Tests monkeypatch this symbol with a fake service;
    everything above it is then hermetically testable."""
    import requests  # lazy: only a real-cloud path needs it
    url = f"{TPU_API_BASE}/{path}"
    resp = requests.request(
        method, url, params=params or {}, json=body,
        headers={"Authorization": f"Bearer {_access_token()}"},
        timeout=60)
    try:
        payload = resp.json() if resp.content else {}
    except ValueError:
        payload = {"error": {"message": resp.text[:500]}}
    if resp.status_code >= 400:
        raise GcpApiError(resp.status_code, payload, f"{method} {path}")
    return payload


def compute_rest(method: str, path: str, body: Optional[dict] = None,
                 params: Optional[dict] = None) -> Dict[str, Any]:
    """One Compute-API call (firewall rules are a compute resource even
    for TPU VMs — reference: sky/provision/gcp/instance.py:594 routes
    TPU firewall ops through GCPComputeInstance). Same monkeypatchable
    shape as :func:`rest`; ``path`` is relative to the API base."""
    import requests  # lazy: only a real-cloud path needs it
    url = f"{COMPUTE_API_BASE}/{path}"
    resp = requests.request(
        method, url, params=params or {}, json=body,
        headers={"Authorization": f"Bearer {_access_token()}"},
        timeout=60)
    try:
        payload = resp.json() if resp.content else {}
    except ValueError:
        payload = {"error": {"message": resp.text[:500]}}
    if resp.status_code >= 400:
        raise GcpApiError(resp.status_code, payload, f"{method} {path}")
    return payload


def _project_of(config: dict) -> str:
    return config.get("project_id") or _gcloud_project()


def _parent(project: str, zone: str) -> str:
    return f"projects/{project}/locations/{zone}"


# ------------------------------------------------------------ error parsing
def _classify_provision_error(e: GcpApiError, zone: str,
                              region: Optional[str]) -> Exception:
    """Map a TPU-API failure onto failover scope, mirroring the reference's
    per-error blocklist parsing (cloud_vm_ray_backend.py:997-1051):
    stockout → skip zone; quota exhausted → skip region (or zone when the
    message says so); auth → not retryable anywhere."""
    msg = e.message or str(e)
    low = msg.lower()
    if e.status in (401, 403) or e.code in ("PERMISSION_DENIED",
                                            "UNAUTHENTICATED"):
        return exceptions.NoCloudAccessError(
            f"GCP TPU API access denied: {msg}")
    # gRPC code 8 (RESOURCE_EXHAUSTED) / "no more capacity": stockout.
    if e.code in ("RESOURCE_EXHAUSTED", 8) or "no more capacity" in low \
            or "out of capacity" in low or "stockout" in low:
        if "quota" in low and ("in region" in low or "per region" in low):
            return exceptions.ProvisionError(
                f"TPU quota exhausted in region: {msg}",
                blocklist_region=region or zone.rsplit("-", 1)[0])
        return exceptions.ProvisionError(
            f"TPU capacity unavailable in {zone}: {msg}",
            blocklist_zone=zone)
    # gRPC code 3: preempted during creation; code 9: insufficient
    # reserved capacity — both zone-scoped in the reference.
    if e.code in (3, 9, "FAILED_PRECONDITION") or \
            "while in state preempted" in low or \
            "insufficient reserved capacity" in low:
        return exceptions.ProvisionError(
            f"TPU creation failed in {zone}: {msg}", blocklist_zone=zone)
    if "quota" in low:
        return exceptions.ProvisionError(
            f"TPU quota exceeded: {msg}",
            blocklist_region=region or zone.rsplit("-", 1)[0])
    if e.status == 409 or e.code == "ALREADY_EXISTS":
        # Not a failure: creation raced a previous attempt.
        return exceptions.ProvisionError(
            f"TPU resource already exists: {msg}", retryable_in_zone=True)
    if e.status in (429, 500, 502, 503, 504) or e.code in (
            "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "INTERNAL"):
        return exceptions.ProvisionError(
            f"Transient TPU API failure: {msg}", retryable_in_zone=True)
    return exceptions.ProvisionError(
        f"TPU provisioning failed in {zone}: {msg}", blocklist_zone=zone)


# ------------------------------------------------------------------- naming
def _node_id(cluster_name: str, slice_index: int) -> str:
    return f"{cluster_name}-s{slice_index}"


def _node_body(cluster_name: str, slice_index: int, config: dict) -> dict:
    labels = dict(config.get("labels") or {})
    labels[_CLUSTER_LABEL] = cluster_name
    labels[_SLICE_LABEL] = str(slice_index)
    body: Dict[str, Any] = {
        "acceleratorType": _gcp_accelerator_type(config["accelerator"]),
        "runtimeVersion": config.get("runtime_version")
                          or "tpu-ubuntu2204-base",
        "labels": labels,
        "metadata": config.get("metadata") or {},
        "dataDisks": [],
        "networkConfig": {"enableExternalIps": True},
        # Network tags: the cluster tag lets open_ports target this
        # cluster's rule without per-instance mutation (the reference
        # tags instances lazily at open_ports time,
        # sky/provision/gcp/instance.py:600-608; tagging at creation
        # makes open/cleanup order-independent), and the shared "stpu"
        # tag scopes the bootstrap ssh/internal rules to our hosts
        # only on shared VPCs.
        "tags": [_network_tag(cluster_name), _COMMON_TAG],
    }
    if config.get("use_spot"):
        body["schedulingConfig"] = {"preemptible": True}
    return body


def _gcp_accelerator_type(accelerator: str) -> str:
    """``tpu-v5e-16`` → GCP acceleratorType ``v5litepod-16`` etc.

    Our catalog names slices by generation + chip count; GCP's API uses
    core counts for v2-v4 (a chip is 2 cores there) and chip counts with
    marketing names for v5e/v5p/v6e (sky/clouds/service_catalog/
    gcp_catalog.py:215-237 performs the same translation)."""
    name = accelerator[len("tpu-"):] if accelerator.startswith("tpu-") \
        else accelerator
    gen, _, count_s = name.partition("-")
    count = int(count_s)
    if gen in ("v2", "v3", "v4"):
        return f"{gen}-{count * 2}"          # chips → cores
    mapping = {"v5e": "v5litepod", "v5p": "v5p", "v6e": "v6e"}
    return f"{mapping[gen]}-{count}"


# ---------------------------------------------------------------------- SPI
def run_instances(region: Optional[str], zone: Optional[str],
                  cluster_name: str, config: dict) -> ProvisionRecord:
    """Create (or resume) every slice of the cluster.

    Multi-host slices are created as queued resources (slice-atomic);
    single-host as plain nodes. Existing STOPPED nodes are restarted,
    READY/CREATING ones left alone — rerunning is idempotent, like the
    reference's resume path."""
    if zone is None:
        raise exceptions.ProvisionError(
            "gcp: a concrete zone is required to create TPU slices")
    project = _project_of(config)
    num_slices = int(config.get("num_slices", 1))
    hosts_per_slice = int(config.get("hosts_per_slice", 1))
    existing = _list_cluster_nodes(project, zone, cluster_name)

    created, resumed = [], []
    try:
        for s in range(num_slices):
            node_id = _node_id(cluster_name, s)
            node = existing.get(node_id)
            if node is not None:
                state = node.get("state")
                if state == "STOPPED":
                    rest("POST", f"{_parent(project, zone)}/nodes/"
                                 f"{node_id}:start")
                    resumed.append(node_id)
                elif state in _PENDING_STATES + ("READY",):
                    resumed.append(node_id)
                else:
                    # PREEMPTED/TERMINATED husk: delete then recreate.
                    _delete_node(project, zone, node_id)
                    _create_slice(project, zone, cluster_name, s,
                                  hosts_per_slice, config)
                    created.append(node_id)
            else:
                _create_slice(project, zone, cluster_name, s,
                              hosts_per_slice, config)
                created.append(node_id)
    except GcpApiError as e:
        raise _classify_provision_error(e, zone, region) from e
    return ProvisionRecord(
        provider_name=PROVIDER_NAME, region=region, zone=zone,
        cluster_name=cluster_name,
        head_instance_id=f"{_node_id(cluster_name, 0)}-w0",
        created_instance_ids=created,
        resumed_instance_ids=resumed)


def _create_slice(project: str, zone: str, cluster_name: str,
                  slice_index: int, hosts_per_slice: int,
                  config: dict) -> None:
    node_id = _node_id(cluster_name, slice_index)
    body = _node_body(cluster_name, slice_index, config)
    if hosts_per_slice > 1:
        # Slice-atomic allocation through queuedResources: every host of
        # the pod is granted together, or the request fails as one unit.
        qr_body: Dict[str, Any] = {
            "tpu": {"nodeSpec": [{
                "parent": _parent(project, zone),
                "nodeId": node_id,
                "node": body,
            }]},
        }
        if config.get("use_spot"):
            body.pop("schedulingConfig", None)
            qr_body["spot"] = {}
        rest("POST", f"{_parent(project, zone)}/queuedResources",
             body=qr_body, params={"queuedResourceId": node_id})
    else:
        rest("POST", f"{_parent(project, zone)}/nodes", body=body,
             params={"nodeId": node_id})


def _list_cluster_nodes(project: str, zone: str, cluster_name: str,
                        lenient_auth: bool = True) -> Dict[str, dict]:
    """All TPU nodes of this cluster in the zone, keyed by short node id.

    Server-side filtering is not supported for labels on the nodes.list
    API, so filter client-side like the reference
    (instance_utils.py:1285-1303). ``lenient_auth`` maps 403/404 to "no
    nodes" (status queries must not crash on unauthorized regions,
    reference :1270-1276); destructive paths pass False so a credential
    failure cannot masquerade as a successful teardown."""
    try:
        resp = rest("GET", f"{_parent(project, zone)}/nodes")
    except GcpApiError as e:
        if e.status == 404 or (lenient_auth and e.status == 403):
            return {}
        if e.status == 403:
            raise exceptions.NoCloudAccessError(
                f"TPU API access denied listing nodes in {zone}: "
                f"{e.message}") from e
        raise
    out = {}
    for node in resp.get("nodes", []):
        if node.get("labels", {}).get(_CLUSTER_LABEL) != cluster_name:
            continue
        short = node["name"].rsplit("/", 1)[-1]
        out[short] = node
    return out


def _delete_node(project: str, zone: str, node_id: str) -> None:
    try:
        rest("DELETE", f"{_parent(project, zone)}/nodes/{node_id}")
    except GcpApiError as e:
        if e.status != 404:
            raise
    # Queued resources leave a record that blocks re-creating the same id.
    try:
        rest("DELETE",
             f"{_parent(project, zone)}/queuedResources/{node_id}",
             params={"force": "true"})
    except GcpApiError as e:
        if e.status != 404:
            raise


def wait_instances(region: Optional[str], cluster_name: str,
                   state: str, provider_config: dict) -> None:
    """Poll until every slice reaches ``state`` ("running" == READY).

    A queued resource that lands in FAILED is surfaced as a ProvisionError
    with failover scope so the backend's retry loop can move on."""
    zone, project = _zone_project(provider_config, cluster_name)
    want = {"running": "READY", "stopped": "STOPPED"}[state]
    deadline = time.time() + _CREATE_TIMEOUT_SECONDS
    while time.time() < deadline:
        nodes = _list_cluster_nodes(project, zone, cluster_name)
        states = {n.get("state") for n in nodes.values()}
        if nodes and states == {want}:
            return
        bad = states - set(_PENDING_STATES) - {want, "STOPPING"}
        if bad:
            _raise_for_failed_creation(project, zone, cluster_name, bad,
                                       region)
        _check_queued_resources(project, zone, cluster_name, region)
        time.sleep(_POLL_INTERVAL_SECONDS)
    raise exceptions.ProvisionError(
        f"Timed out waiting for {cluster_name} to reach {state}",
        blocklist_zone=zone)


def _raise_for_failed_creation(project: str, zone: str, cluster_name: str,
                               bad_states: set, region) -> None:
    raise exceptions.ProvisionError(
        f"TPU slice(s) of {cluster_name} entered {sorted(bad_states)} "
        f"during provisioning in {zone}", blocklist_zone=zone)


def _check_queued_resources(project: str, zone: str, cluster_name: str,
                            region) -> None:
    try:
        resp = rest("GET", f"{_parent(project, zone)}/queuedResources")
    except GcpApiError:
        return
    for qr in resp.get("queuedResources", []):
        short = qr["name"].rsplit("/", 1)[-1]
        if not short.startswith(f"{cluster_name}-s"):
            continue
        qstate = qr.get("state", {}).get("state")
        if qstate in ("FAILED", "SUSPENDED", "SUSPENDING"):
            detail = json.dumps(
                qr.get("state", {}).get("stateInitiator", ""))
            raise exceptions.ProvisionError(
                f"Queued resource {short} became {qstate} in {zone}: "
                f"{detail}", blocklist_zone=zone)


def _zone_project(provider_config: dict,
                  cluster_name: str) -> Tuple[str, str]:
    """Zone/project come from provider_config, ALWAYS: the backend
    records them at provision time and get_cluster_info echoes them into
    every handle, so provision code never reaches back into the client
    state DB (which does not exist where a controller cluster runs —
    the r2 layering inversion this replaces)."""
    zone = provider_config.get("zone")
    if zone is None:
        raise exceptions.ProvisionError(
            f"gcp: provider_config for {cluster_name} carries no zone; "
            "the caller must pass the provisioning-time config "
            "(handle.cluster_info.provider_config).")
    return zone, _project_of(provider_config)


def query_instances(cluster_name: str,
                    provider_config: dict) -> Dict[str, str]:
    """Per-host status map. A slice host inherits its node's state — on a
    pod slice there is no per-worker lifecycle (the gang lives and dies
    together), which is exactly the slice-atomic semantics the backend's
    status reconciler expects."""
    zone, project = _zone_project(provider_config, cluster_name)
    out: Dict[str, str] = {}
    for node_id, node in _list_cluster_nodes(project, zone,
                                             cluster_name).items():
        status = _STATE_MAP.get(node.get("state", ""), "pending")
        hosts = max(1, len(node.get("networkEndpoints", []) or [1]))
        for w in range(hosts):
            out[f"{node_id}-w{w}"] = status
    return out


def get_cluster_info(region: Optional[str], cluster_name: str,
                     provider_config: dict) -> ClusterInfo:
    zone, project = _zone_project(provider_config, cluster_name)
    instances: Dict[str, InstanceInfo] = {}
    head_id: Optional[str] = None
    nodes = _list_cluster_nodes(project, zone, cluster_name)
    for node_id in sorted(nodes):
        node = nodes[node_id]
        slice_id = node_id.rsplit("-", 1)[-1]       # "s0", "s1", ...
        endpoints = node.get("networkEndpoints") or []
        if not endpoints:
            endpoints = [{}]
        for w, ep in enumerate(endpoints):
            iid = f"{node_id}-w{w}"
            access = ep.get("accessConfig") or {}
            instances[iid] = InstanceInfo(
                instance_id=iid,
                internal_ip=ep.get("ipAddress", ""),
                external_ip=access.get("externalIp"),
                slice_id=slice_id,
                host_index=w,
                tags={"node_id": node_id, "zone": zone})
            if head_id is None:
                head_id = iid
    return ClusterInfo(
        cluster_name=cluster_name, provider_name=PROVIDER_NAME,
        region=region or zone.rsplit("-", 1)[0], zone=zone,
        instances=instances, head_instance_id=head_id,
        ssh_user=provider_config.get("ssh_user", "stpu"),
        ssh_key_path=provider_config.get("ssh_key_path"),
        provider_config=dict(provider_config, zone=zone,
                             project_id=project))


def stop_instances(cluster_name: str, provider_config: dict) -> None:
    """Stop the cluster's nodes. Multi-host pods cannot stop — the TPU API
    rejects it — so refuse up front (the capability layer routes user
    `stop` requests away from pods before this; reference:
    sky/clouds/gcp.py:558-610 unstoppable-pod handling)."""
    zone, project = _zone_project(provider_config, cluster_name)
    # Destructive-path listing: a 403 must raise, not return {} — an empty
    # loop here would report "stopped" while the nodes keep billing.
    for node_id, node in _list_cluster_nodes(project, zone, cluster_name,
                                             lenient_auth=False).items():
        if len(node.get("networkEndpoints") or []) > 1:
            raise exceptions.NotSupportedError(
                f"TPU pod slice {node_id} cannot be stopped; only "
                "single-host slices support stop. Use `down` instead.")
        if node.get("state") in ("READY",) + _PENDING_STATES:
            rest("POST", f"{_parent(project, zone)}/nodes/{node_id}:stop")


def terminate_instances(cluster_name: str, provider_config: dict) -> None:
    try:
        zone, project = _zone_project(provider_config, cluster_name)
    except exceptions.ProvisionError:
        return  # nothing recorded → nothing to clean
    for node_id in _list_cluster_nodes(project, zone, cluster_name,
                                       lenient_auth=False):
        _delete_node(project, zone, node_id)


# ------------------------------------------------------------------ ports
# Firewall management (provision SPI open_ports/cleanup_ports). Reference:
# sky/provision/__init__.py:122,133 declare the ops;
# sky/provision/gcp/instance.py:571,626 implement them with one
# per-cluster ingress rule targeting a cluster network tag. Differences
# here: SDK-free Compute REST (the repo's `rest` discipline), and hosts
# are tagged at node CREATION (_node_body) instead of lazily, so the rule
# applies to later-added slices automatically. The VPC itself is assumed
# to exist (default network unless provider_config["network"] says
# otherwise) — the reference's VPC/subnet bootstrap
# (sky/provision/gcp/config.py:392-540) is out of scope for TPU VMs,
# which GCP only places in pre-existing networks.

_OP_WAIT_TIMEOUT_SECONDS = 120


def _network_tag(cluster_name: str) -> str:
    """RFC1035-safe network tag for the cluster (lowercase, [a-z0-9-],
    63 chars), suffixed with a short hash of the RAW name: the
    sanitize/truncate steps are lossy (``a.b`` and ``a-b`` sanitize
    identically; two long names sharing a 57-char prefix truncate
    identically), and colliding tags would alias two clusters onto ONE
    firewall rule — tearing down either cluster then deletes the
    other's ingress (ADVICE round 5). The hash restores injectivity."""
    digest = hashlib.sha1(cluster_name.encode()).hexdigest()[:6]
    tag = "".join(c if c.isalnum() or c == "-" else "-"
                  for c in cluster_name.lower())
    # Room for "-<digest>" plus the "-ports" suffix rule names append.
    stem = ("stpu-" + tag.strip("-"))[:63 - 7 - 6].rstrip("-")
    return f"{stem}-{digest}"


def _legacy_network_tag(cluster_name: str) -> str:
    """Tag format before the hash suffix — kept so cleanup can delete
    rules created by earlier versions instead of leaking them."""
    tag = "".join(c if c.isalnum() or c == "-" else "-"
                  for c in cluster_name.lower())
    return ("stpu-" + tag.strip("-"))[:63].rstrip("-")


def _firewall_rule_name(cluster_name: str) -> str:
    return (_network_tag(cluster_name) + "-ports")[:63]


def _normalize_ports(ports) -> List[str]:
    """Resources.ports entries ("80", 8080, "30000-30100") → the compute
    API's allowed.ports strings (shared grammar:
    provision.common.parse_port_ranges)."""
    from skypilot_tpu.provision.common import parse_port_ranges
    out = [f"{lo}-{hi}" if hi != lo else str(lo)
           for lo, hi in parse_port_ranges(ports)]
    return sorted(set(out))


def _wait_compute_op(project: str, op: Dict[str, Any]) -> None:
    """Block until a global compute operation is DONE; raise on error."""
    name = op.get("name")
    if not name:
        return
    deadline = time.time() + _OP_WAIT_TIMEOUT_SECONDS
    while True:
        if op.get("status") == "DONE":
            errors = (op.get("error") or {}).get("errors")
            if errors:
                raise exceptions.ProvisionError(
                    f"firewall operation {name} failed: {errors}")
            return
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f"firewall operation {name} timed out")
        time.sleep(_POLL_INTERVAL_SECONDS)
        op = compute_rest(
            "GET", f"projects/{project}/global/operations/{name}")


def open_ports(cluster_name: str, ports: List[str],
               provider_config: dict) -> None:
    """Ensure one ingress rule allowing ``ports`` (tcp) to this
    cluster's tagged hosts. Idempotent: re-opening merges with whatever
    the rule already allows (a serve controller opens its LB range once;
    a later `launch` against the same cluster with task ports must not
    clobber it)."""
    if not ports:
        return
    project = _project_of(provider_config)
    network = provider_config.get("network") or "default"
    name = _firewall_rule_name(cluster_name)
    want = _normalize_ports(ports)
    try:
        existing = compute_rest(
            "GET", f"projects/{project}/global/firewalls/{name}")
    except GcpApiError as e:
        if e.status != 404:
            raise
        existing = None
    if existing is not None:
        have = []
        for allowed in existing.get("allowed", []):
            if allowed.get("IPProtocol") == "tcp":
                have.extend(allowed.get("ports", []))
        merged = sorted(set(have) | set(want))
        if merged == sorted(set(have)):
            return  # already open
        op = compute_rest(
            "PATCH", f"projects/{project}/global/firewalls/{name}",
            body={"allowed": [{"IPProtocol": "tcp", "ports": merged}]})
    else:
        op = compute_rest(
            "POST", f"projects/{project}/global/firewalls",
            body={
                "name": name,
                "network": f"projects/{project}/global/networks/"
                           f"{network}",
                "direction": "INGRESS",
                "sourceRanges": ["0.0.0.0/0"],
                "allowed": [{"IPProtocol": "tcp", "ports": want}],
                "targetTags": [_network_tag(cluster_name)],
                "description": f"stpu-managed ingress for cluster "
                               f"{cluster_name}",
            })
    _wait_compute_op(project, op)


def cleanup_ports(cluster_name: str, ports: List[str],
                  provider_config: dict) -> None:
    """Delete the cluster's ingress rule (the whole rule — ports is
    advisory, matching the reference's cleanup_ports contract which
    ignores it, sky/provision/gcp/instance.py:626). Both the current
    (hash-suffixed) and the legacy rule name are tried: a cluster
    provisioned before the tag format changed still tears its rule
    down instead of leaking ingress."""
    del ports
    project = _project_of(provider_config)
    legacy = _legacy_network_tag(cluster_name) + "-ports"
    for name in dict.fromkeys([_firewall_rule_name(cluster_name),
                               legacy[:63]]):
        try:
            op = compute_rest(
                "DELETE", f"projects/{project}/global/firewalls/{name}")
        except GcpApiError as e:
            if e.status == 404:
                continue  # never created / already gone
            raise
        _wait_compute_op(project, op)


# -------------------------------------------------------------- bootstrap
# Reference analog: bootstrap_instances in the provision SPI
# (sky/provision/__init__.py) backed by sky/provision/gcp/config.py:392-540
# + constants.py:57-194 — ensure the VPC is usable BEFORE any instance
# waits on it. Trimmed to what TPU VMs actually need: the network must
# exist (TPU VMs only join pre-existing networks — no VPC creation), SSH
# ingress must be open (or provisioner.wait_for_ssh hangs its full
# timeout on a locked-down project), and intra-VPC traffic must flow
# (gang drivers reach workers over internal IPs).

# Shared network tag carried by every stpu-provisioned host: bootstrap
# rules target it so a shared/pre-existing VPC's unrelated VMs are
# never exposed by our ingress (open_ports applies the same
# tag-scoping discipline per cluster).
_COMMON_TAG = "stpu"

_BOOTSTRAP_RULES = (
    # (suffix, body) — idempotent per network, targeted at stpu nodes.
    ("allow-ssh", {
        "direction": "INGRESS",
        "sourceRanges": ["0.0.0.0/0"],
        "targetTags": [_COMMON_TAG],
        "allowed": [{"IPProtocol": "tcp", "ports": ["22"]}],
        "description": "stpu bootstrap: ssh ingress for provisioning "
                       "(stpu-tagged hosts only)",
    }),
    ("allow-internal", {
        "direction": "INGRESS",
        # GCP auto-mode subnets live in 10.128.0.0/9 (the reference's
        # range, constants.py:71); custom-mode users with other ranges
        # manage internal rules themselves.
        "sourceRanges": ["10.128.0.0/9"],
        "targetTags": [_COMMON_TAG],
        "allowed": [{"IPProtocol": "tcp", "ports": ["0-65535"]},
                    {"IPProtocol": "udp", "ports": ["0-65535"]},
                    {"IPProtocol": "icmp"}],
        "description": "stpu bootstrap: intra-VPC traffic (gang "
                       "drivers, host agents, jax coordinator; "
                       "stpu-tagged hosts only)",
    }),
)


def bootstrap_instances(region, cluster_name: str,
                        provider_config: dict) -> None:
    """Pre-provision VPC sanity: verify the network exists and ensure
    the ssh/internal ingress rules a cluster needs are present.
    Idempotent; rules are per-network (shared by every cluster on it),
    not per-cluster — cleanup_ports never touches them, matching the
    reference's persistent bootstrap rules."""
    del cluster_name
    project = _project_of(provider_config)
    network = provider_config.get("network") or "default"
    try:
        compute_rest(
            "GET", f"projects/{project}/global/networks/{network}")
    except GcpApiError as e:
        if e.status == 404:
            # Project-global, permanent: failing over to another zone
            # cannot fix a missing VPC, so this must NOT be a
            # (retryable) ProvisionError.
            raise exceptions.NoCloudAccessError(
                f"VPC network {network!r} does not exist in project "
                f"{project!r}. TPU VMs only join pre-existing "
                "networks: create it (or set provider network config) "
                "first.") from e
        raise _classify_provision_error(e, zone=str(region),
                                        region=region) from e
    safe_net = "".join(c if c.isalnum() or c == "-" else "-"
                       for c in network.lower()).strip("-")[:40]
    for suffix, body in _BOOTSTRAP_RULES:
        name = f"stpu-{safe_net}-{suffix}"[:63]
        try:
            compute_rest(
                "GET", f"projects/{project}/global/firewalls/{name}")
            continue  # already bootstrapped
        except GcpApiError as e:
            if e.status != 404:
                raise _classify_provision_error(
                    e, zone=str(region), region=region) from e
        try:
            op = compute_rest(
                "POST", f"projects/{project}/global/firewalls",
                body={
                    "name": name,
                    "network": f"projects/{project}/global/networks/"
                               f"{network}",
                    **body,
                })
        except GcpApiError as e:
            if e.status == 409:
                continue  # concurrent launch won the create race
            raise _classify_provision_error(
                e, zone=str(region), region=region) from e
        _wait_compute_op(project, op)
