"""Stateless per-cloud provisioning SPI, dispatched by module name.

Reference analog: sky/provision/__init__.py (_route_to_cloud_impl:30; ops
query/run/wait/stop/terminate/get_cluster_info). Providers implement plain
functions in ``skypilot_tpu.provision.<provider>``:

    run_instances(region, zone, cluster_name, config) -> ProvisionRecord
    wait_instances(region, cluster_name, state, provider_config) -> None
    query_instances(cluster_name, provider_config) -> Dict[id, status_str]
    get_cluster_info(region, cluster_name, provider_config) -> ClusterInfo
    stop_instances(cluster_name, provider_config) -> None
    terminate_instances(cluster_name, provider_config) -> None

Providers: ``gcp`` (TPU VMs via the TPU REST API), ``local`` (subprocess
hosts for hermetic multi-host testing — the analog of the reference's
Kind-backed `sky local up` path, sky/cli.py:5054).
"""
from __future__ import annotations

import functools
import importlib
from typing import Any

from skypilot_tpu.provision.common import (  # noqa: F401
    ClusterInfo, InstanceInfo, ProvisionRecord)


@functools.lru_cache(maxsize=None)
def _provider_module(provider_name: str):
    try:
        return importlib.import_module(
            f"skypilot_tpu.provision.{provider_name}")
    except ModuleNotFoundError as e:
        from skypilot_tpu import exceptions
        raise exceptions.NoCloudAccessError(
            f"No provisioner for provider {provider_name!r} "
            f"(module skypilot_tpu.provision.{provider_name} not "
            f"found).") from e


def _route(provider_name: str, func_name: str, *args, **kwargs) -> Any:
    module = _provider_module(provider_name)
    fn = getattr(module, func_name, None)
    if fn is None:
        raise NotImplementedError(
            f"Provider {provider_name!r} does not implement {func_name}")
    return fn(*args, **kwargs)


def run_instances(provider_name: str, region, zone, cluster_name: str,
                  config: dict) -> ProvisionRecord:
    return _route(provider_name, "run_instances", region, zone,
                  cluster_name, config)


def wait_instances(provider_name: str, region, cluster_name: str,
                   state: str, provider_config: dict) -> None:
    return _route(provider_name, "wait_instances", region, cluster_name,
                  state, provider_config)


def query_instances(provider_name: str, cluster_name: str,
                    provider_config: dict) -> dict:
    return _route(provider_name, "query_instances", cluster_name,
                  provider_config)


def get_cluster_info(provider_name: str, region, cluster_name: str,
                     provider_config: dict) -> ClusterInfo:
    return _route(provider_name, "get_cluster_info", region, cluster_name,
                  provider_config)


def bootstrap_instances(provider_name: str, region, cluster_name: str,
                        provider_config: dict) -> None:
    """Pre-provision environment sanity (reference:
    sky/provision/__init__.py bootstrap_instances backed by
    sky/provision/gcp/config.py). GCP verifies the VPC exists and
    ensures ssh/internal ingress so wait-for-SSH cannot hang on a
    locked-down project. Providers without environment bootstrap
    (local, docker, kubernetes) simply don't implement it — no-op."""
    module = _provider_module(provider_name)
    fn = getattr(module, "bootstrap_instances", None)
    if fn is not None:
        fn(region, cluster_name, provider_config)


def open_ports(provider_name: str, cluster_name: str, ports: list,
               provider_config: dict) -> None:
    """Open ``ports`` for inbound traffic to the cluster (reference:
    sky/provision/__init__.py:122). GCP: one tagged VPC ingress rule;
    kubernetes: a NodePort Service on the head pod; local: no-op
    (localhost). Idempotent; re-opening merges."""
    return _route(provider_name, "open_ports", cluster_name, ports,
                  provider_config)


def cleanup_ports(provider_name: str, cluster_name: str, ports: list,
                  provider_config: dict) -> None:
    """Delete whatever open_ports created for the cluster (reference:
    sky/provision/__init__.py:133; like there, ``ports`` is advisory —
    cleanup is whole-cluster)."""
    return _route(provider_name, "cleanup_ports", cluster_name, ports,
                  provider_config)


def query_ports(provider_name: str, cluster_name: str, ports: list,
                head_ip, provider_config: dict) -> dict:
    """Reachable endpoints for the cluster's opened ports (reference:
    sky/provision/__init__.py:145). Returns {port: "host:port"} for
    every CONCRETE port in ``ports`` (ranges expand). Providers where
    the requested port passes straight through (GCP firewall, local)
    build endpoints from ``head_ip``; kubernetes resolves the
    cluster-assigned nodePorts from the Service."""
    module = _provider_module(provider_name)
    fn = getattr(module, "query_ports", None)
    if fn is not None:
        return fn(cluster_name, ports, head_ip, provider_config)
    # Passthrough default: the opened port IS the reachable port.
    from skypilot_tpu.provision.common import parse_port_ranges
    out = {}
    for lo, hi in parse_port_ranges(ports):
        for p in range(lo, hi + 1):
            out[p] = f"{head_ip}:{p}"
    return out


def stop_instances(provider_name: str, cluster_name: str,
                   provider_config: dict) -> None:
    return _route(provider_name, "stop_instances", cluster_name,
                  provider_config)


def terminate_instances(provider_name: str, cluster_name: str,
                        provider_config: dict) -> None:
    return _route(provider_name, "terminate_instances", cluster_name,
                  provider_config)
