"""Docker provisioner: containers as cluster hosts (dev/debug path).

Reference analog: sky/backends/local_docker_backend.py + docker_utils —
the reference's "run this task in a local container" development path.
Here it is a provider behind the same provision SPI instead of a
separate backend: a cluster of N hosts is N long-running containers on
the local docker daemon, labeled like the kubernetes provider's pods,
exec'd via ``docker exec``. No TPU passthrough — this is the path for
orchestration development and CPU tasks with containerized deps; real
accelerator work goes to gcp/kubernetes.

All docker traffic goes through one :func:`docker` seam so hermetic
tests can fake the daemon (the provision/gcp.py `rest` discipline).
"""
from __future__ import annotations

import json
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           ProvisionRecord)

PROVIDER_NAME = "docker"

_CLUSTER_LABEL = "stpu-cluster"
_SLICE_LABEL = "stpu-slice"
_HOST_INDEX_LABEL = "stpu-host-index"

_DEFAULT_IMAGE = "python:3.11-slim"

# docker container states -> SPI status strings.
_STATE_MAP = {
    "running": "running",
    "created": "pending",
    "restarting": "pending",
    "paused": "stopped",
    "exited": "stopped",
    "dead": "terminated",
    "removing": "terminated",
}


def docker(args: List[str]) -> Any:
    """One docker-CLI invocation returning parsed JSON when the command
    produces it (``--format {{json .}}`` lines become a list). Tests
    monkeypatch this symbol with a fake daemon."""
    proc = subprocess.run(["docker"] + args, capture_output=True,
                          text=True, timeout=120)
    if proc.returncode != 0:
        raise exceptions.ProvisionError(
            f"docker {' '.join(args[:3])}... failed: "
            f"{proc.stderr.strip()[:500]}")
    out = proc.stdout.strip()
    if not out:
        return []
    try:
        return [json.loads(line) for line in out.splitlines()]
    except ValueError:
        return out


def _container_name(cluster_name: str, slice_i: int, host_i: int) -> str:
    return f"stpu-{cluster_name}-s{slice_i}-h{host_i}"


def _list_containers(cluster_name: str) -> List[dict]:
    return docker(["ps", "-a", "--filter",
                   f"label={_CLUSTER_LABEL}={cluster_name}",
                   "--format", "{{json .}}"])


# ------------------------------------------------------------------- SPI
def run_instances(region, zone, cluster_name: str,
                  config: dict) -> ProvisionRecord:
    del region, zone  # the docker daemon is its own placement
    num_slices = int(config.get("num_slices") or 1)
    hosts = int(config.get("hosts_per_slice") or 1)
    if num_slices * hosts > 1:
        # Single-container dev path (reference LocalDockerBackend
        # semantics): containers report loopback IPs, so a rank>0 host
        # would be unreachable by the gang driver's SSH transport.
        raise exceptions.ProvisionError(
            f"docker provider runs ONE container per cluster; "
            f"{cluster_name} asked for {num_slices * hosts} hosts. Use "
            "local/kubernetes/gcp for multi-host gangs.")
    image = config.get("image") or _DEFAULT_IMAGE

    existing = {c["Names"] for c in _list_containers(cluster_name)}
    created: List[str] = []
    try:
        for s in range(num_slices):
            for h in range(hosts):
                name = _container_name(cluster_name, s, h)
                if name in existing:
                    # Stopped containers restart in place (the provider's
                    # `start` semantics).
                    docker(["start", name])
                    continue
                docker(["run", "-d", "--name", name,
                        "--label", f"{_CLUSTER_LABEL}={cluster_name}",
                        "--label", f"{_SLICE_LABEL}=slice-{s}",
                        "--label", f"{_HOST_INDEX_LABEL}={h}",
                        image, "sleep", "infinity"])
                created.append(name)
    except exceptions.ProvisionError:
        for name in created:
            try:
                docker(["rm", "-f", name])
            except exceptions.ProvisionError:
                pass
        raise
    return ProvisionRecord(
        provider_name=PROVIDER_NAME, region=None, zone=None,
        cluster_name=cluster_name,
        head_instance_id=_container_name(cluster_name, 0, 0),
        created_instance_ids=created,
        resumed_instance_ids=sorted(existing))


def wait_instances(region, cluster_name: str, state: str,
                   provider_config: dict) -> None:
    del region, provider_config
    deadline = time.time() + 120
    while time.time() < deadline:
        containers = _list_containers(cluster_name)
        if containers and all(
                _STATE_MAP.get(c.get("State", ""), "pending") == state
                for c in containers):
            return
        time.sleep(1)
    raise exceptions.ProvisionError(
        f"containers of {cluster_name} not {state} after 120s")


def query_instances(cluster_name: str,
                    provider_config: dict) -> Dict[str, str]:
    del provider_config
    return {
        c["Names"]: _STATE_MAP.get(c.get("State", ""), "pending")
        for c in _list_containers(cluster_name)
    }


def get_cluster_info(region, cluster_name: str,
                     provider_config: dict) -> ClusterInfo:
    del region
    instances: Dict[str, InstanceInfo] = {}
    for c in _list_containers(cluster_name):
        name = c["Names"]
        labels = dict(
            part.split("=", 1)
            for part in (c.get("Labels") or "").split(",") if "=" in part)
        instances[name] = InstanceInfo(
            instance_id=name,
            internal_ip="127.0.0.1",
            external_ip=None,
            slice_id=labels.get(_SLICE_LABEL, "slice-0"),
            host_index=int(labels.get(_HOST_INDEX_LABEL, 0)),
            tags={"container": name},
        )
    head = _container_name(cluster_name, 0, 0)
    return ClusterInfo(
        cluster_name=cluster_name, provider_name=PROVIDER_NAME,
        region=None, zone=None, instances=instances,
        head_instance_id=head if head in instances else None,
        ssh_user="root", ssh_key_path=None,
        provider_config=dict(provider_config))


def stop_instances(cluster_name: str, provider_config: dict) -> None:
    del provider_config
    for c in _list_containers(cluster_name):
        docker(["stop", c["Names"]])


def terminate_instances(cluster_name: str, provider_config: dict) -> None:
    del provider_config
    for c in _list_containers(cluster_name):
        docker(["rm", "-f", c["Names"]])
