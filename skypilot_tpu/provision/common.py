"""Shared provisioner dataclasses.

Reference analog: sky/provision/common.py (ProvisionRecord, ClusterInfo,
InstanceInfo). One TPU-native addition: an *instance* here is a slice host
(TPU VM worker), and a cluster groups hosts by slice — slice_id is the
gang boundary for atomic failure handling.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class InstanceInfo:
    instance_id: str
    internal_ip: str
    external_ip: Optional[str]
    slice_id: str                  # which slice this host belongs to
    host_index: int                # index within the slice (rank source)
    ssh_port: int = 22
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClusterInfo:
    """Everything the backend needs to reach a provisioned cluster."""
    cluster_name: str
    provider_name: str             # provision module name ("gcp", "local")
    region: Optional[str]
    zone: Optional[str]
    instances: Dict[str, InstanceInfo] = dataclasses.field(
        default_factory=dict)
    head_instance_id: Optional[str] = None
    ssh_user: str = "root"
    ssh_key_path: Optional[str] = None
    provider_config: Dict[str, Any] = dataclasses.field(
        default_factory=dict)

    def get_head_instance(self) -> Optional[InstanceInfo]:
        if self.head_instance_id is None:
            return None
        return self.instances.get(self.head_instance_id)

    def ordered_instances(self) -> List[InstanceInfo]:
        """Deterministic rank order: sort by (slice_id, host_index) with
        the head's slice first — the analog of the reference's
        sorted-internal-IP rank assignment
        (sky/backends/cloud_vm_ray_backend.py:497-505)."""
        head = self.get_head_instance()
        head_slice = head.slice_id if head else ""

        def key(inst: InstanceInfo):
            return (inst.slice_id != head_slice, inst.slice_id,
                    inst.host_index)
        return sorted(self.instances.values(), key=key)

    def internal_ips(self) -> List[str]:
        return [i.internal_ip for i in self.ordered_instances()]


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances: what got created/resumed."""
    provider_name: str
    region: Optional[str]
    zone: Optional[str]
    cluster_name: str
    head_instance_id: Optional[str]
    created_instance_ids: List[str] = dataclasses.field(
        default_factory=list)
    resumed_instance_ids: List[str] = dataclasses.field(
        default_factory=list)


def parse_port_ranges(ports) -> List[tuple]:
    """Validate Resources.ports entries and return (lo, hi) int pairs.

    One grammar for every provider ('80' or '30000-30100' — the
    reference's resources_utils.port_ranges_to_set grammar), so a task
    YAML that validates on GCP can't error on Kubernetes. Providers
    render the pairs into their own API shapes (compute allowed.ports
    strings, Service port lists).
    """
    from skypilot_tpu import exceptions
    out: List[tuple] = []
    for p in ports:
        s = str(p).strip()
        lo, dash, hi = s.partition("-")
        if not lo.isdigit() or (dash and not hi.isdigit()):
            raise exceptions.ProvisionError(
                f"invalid port spec {p!r} (want '80' or '30000-30100')")
        lo_i = int(lo)
        hi_i = int(hi) if dash else lo_i
        if not (0 < lo_i <= hi_i <= 65535):
            raise exceptions.ProvisionError(
                f"port spec {p!r} out of range 1-65535")
        out.append((lo_i, hi_i))
    return out
