"""Post-provision orchestration: SSH wait + agent runtime bring-up.

Reference analog: sky/provision/provisioner.py (_post_provision_setup:402:
wait SSH → file mounts → runtime setup → ray start → skylet) and
sky/provision/instance_setup.py. The TPU replacement for "ray start" is
installing + starting the host agent on every host of the slice; TPU VMs
of a slice boot together, so there is no autoscaler-style staggered join.
"""
from __future__ import annotations

import concurrent.futures as cf
import json
import os
import subprocess
import time
from typing import List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu.utils import command_runner as runner_lib

SSH_WAIT_TIMEOUT_SECONDS = 300

# Commands that bring up the on-host runtime. The wheel is rsynced by
# setup_agent_runtime; the agent daemon (skypilot_tpu/agent/daemon.py,
# the skylet analog) is started under nohup on the head host, which also
# runs the job DB and enforces autostop.
_AGENT_START_CMD = (
    "mkdir -p ~/.stpu_agent && "
    # Replace, never duplicate: a re-ship (version-drift repair on a
    # reused cluster) must not leave two daemons racing over the job DB.
    "{ [ -f ~/.stpu_agent/daemon.pid ] && "
    "kill $(cat ~/.stpu_agent/daemon.pid) 2>/dev/null; "
    "rm -f ~/.stpu_agent/daemon.pid; } ; "
    "nohup python3 -m skypilot_tpu.agent.daemon "
    "  > ~/.stpu_agent/daemon.out 2>&1 & "
    "echo started")
# Separate so hermetic tests can defang the package install while still
# executing the real bring-up orchestration.
_RUNTIME_INSTALL_CMD = "pip install -q --user ~/.stpu_wheels/*.whl"
# Worker-pod exec agent (kubernetes): the sshd replacement. Replace,
# never duplicate, mirroring the daemon start above.
_EXEC_AGENT_START_CMD = (
    "mkdir -p ~/.stpu_agent && "
    "{ [ -f ~/.stpu_agent/exec_server.pid ] && "
    "kill $(cat ~/.stpu_agent/exec_server.pid) 2>/dev/null; "
    "rm -f ~/.stpu_agent/exec_server.pid; } ; "
    "nohup python3 -m skypilot_tpu.agent.exec_server "
    "  > ~/.stpu_agent/exec_server.out 2>&1 & "
    "echo $! > ~/.stpu_agent/exec_server.pid && echo exec-agent-started")


def _ssh_runner(info: ClusterInfo, inst) -> runner_lib.CommandRunner:
    """Bring-up transport to one host: SSH for VM hosts, kubectl exec
    for pods (the readiness wait and runtime setup below are transport-
    agnostic — they only need run()/rsync())."""
    if info.provider_name == "kubernetes":
        return runner_lib.KubernetesCommandRunner(
            inst.instance_id, pod_name=inst.instance_id,
            namespace=inst.tags.get("namespace", "default"),
            internal_ip=inst.internal_ip)
    if info.provider_name == "docker":
        return runner_lib.DockerCommandRunner(
            inst.instance_id,
            container=inst.tags.get("container", inst.instance_id))
    return runner_lib.SSHCommandRunner(
        inst.instance_id, inst.external_ip or inst.internal_ip,
        ssh_user=info.ssh_user,
        ssh_key_path=info.ssh_key_path or "~/.ssh/id_rsa",
        port=inst.ssh_port,
        proxy_command=info.provider_config.get("ssh_proxy_command"))


def wait_for_ssh(info: ClusterInfo,
                 timeout: int = SSH_WAIT_TIMEOUT_SECONDS) -> None:
    """Block until every host of every slice accepts SSH (reference:
    provisioner.wait_for_ssh:363)."""
    deadline = time.time() + timeout
    # One runner per host for the whole wait: reuses the multiplexed
    # ControlMaster connection and its temp dir across polls.
    pending = [(inst, _ssh_runner(info, inst))
               for inst in info.ordered_instances()]
    while pending and time.time() < deadline:
        still: List = []
        for inst, runner in pending:
            try:
                rc = runner.run("true")
            except (OSError, subprocess.SubprocessError):
                rc = 255
            if rc != 0:
                still.append((inst, runner))
        pending = still
        if pending:
            time.sleep(5)
    if pending:
        raise exceptions.ProvisionError(
            f"SSH not reachable on {len(pending)} host(s) of "
            f"{info.cluster_name} after {timeout}s",
            retryable_in_zone=True)


def _exec_token(cluster_name: str) -> str:
    """Per-cluster random exec/coordinator auth token — an INDEPENDENT
    secret (presenting it grants exec on worker pods), never derived
    from key material that also appears in public places like
    authorized_keys. Generated once, persisted next to the keypair."""
    import secrets
    from skypilot_tpu.agent import constants as agent_constants
    from skypilot_tpu.utils import paths
    key_dir = paths.generated_dir() / cluster_name
    key_dir.mkdir(parents=True, exist_ok=True)
    tok = key_dir / "exec_token"
    if not tok.exists():
        tmp = tok.with_suffix(".tmp")
        tmp.write_text(
            secrets.token_hex(agent_constants.TOKEN_LEN // 2))
        tmp.chmod(0o600)
        tmp.rename(tok)
    return tok.read_text().strip()


def _internal_keypair(cluster_name: str):
    """Cluster-internal SSH keypair (generated once per cluster,
    client-side): the private half goes to the head, the public half
    into every host's authorized_keys — so the head-resident gang
    driver reaches workers over the slice's internal network with the
    client long gone. Returns (private_key_path, pubkey_line)."""
    from skypilot_tpu.utils import paths
    key_dir = paths.generated_dir() / cluster_name
    key_dir.mkdir(parents=True, exist_ok=True)
    priv = key_dir / "internal_key"
    if not priv.exists():
        # Pure-python keygen (the client image need not ship ssh-keygen).
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import ed25519
        key = ed25519.Ed25519PrivateKey.generate()
        priv_bytes = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.OpenSSH,
            serialization.NoEncryption())
        pub_bytes = key.public_key().public_bytes(
            serialization.Encoding.OpenSSH,
            serialization.PublicFormat.OpenSSH)
        # .pub first, then the private key ATOMICALLY (tmp + rename):
        # the gate above is priv.exists(), so no crash point may leave
        # an existing-but-incomplete private key it would trust forever.
        priv.with_suffix(".pub").write_text(
            f"{pub_bytes.decode()} stpu-internal-{cluster_name}\n")
        tmp = priv.with_suffix(".tmp")
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.write(fd, priv_bytes)
        finally:
            os.close(fd)
        os.replace(tmp, priv)
    pub = priv.with_suffix(".pub").read_text().strip()
    return priv, pub


def setup_agent_runtime(info: ClusterInfo,
                        cluster_identity: Optional[dict] = None) -> None:
    """Ship the framework wheel, record the cluster identity, install
    the cluster-internal keypair, and start the head daemon — all hosts
    in parallel (reference: instance_setup.setup_runtime_on_cluster:173
    + start_skylet_on_head_node:407). ``cluster_identity`` is the
    daemon's cluster.json (who am I + provider config for self-stop)."""
    import shlex

    from skypilot_tpu.agent import constants as agent_constants
    from skypilot_tpu.utils import wheel_utils
    wheel_path = wheel_utils.build_wheel()
    priv_key, pub_key = _internal_keypair(info.cluster_name)
    instances = info.ordered_instances()
    identity_json = json.dumps(cluster_identity or {
        "cluster_name": info.cluster_name,
        "provider_name": info.provider_name,
        "provider_config": info.provider_config,
    })

    version = wheel_utils.runtime_version()
    # Exec-agent token: per-cluster random secret authenticating the
    # sshd-free worker transport (agent/exec_server.py) and the
    # direct-connect gang coordinator.
    exec_token = _exec_token(info.cluster_name)

    def bring_up(inst):
        runner = _ssh_runner(info, inst)
        runner.rsync(str(wheel_path), "~/.stpu_wheels/", up=True)
        is_head = inst.instance_id == info.head_instance_id
        cmd = (f"{_RUNTIME_INSTALL_CMD} && "
               "mkdir -p ~/.stpu_agent ~/.ssh && chmod 700 ~/.ssh && "
               f"{{ grep -qxF {shlex.quote(pub_key)} "
               "~/.ssh/authorized_keys 2>/dev/null || "
               f"printf '%s\\n' {shlex.quote(pub_key)} "
               ">> ~/.ssh/authorized_keys; } && "
               "chmod 600 ~/.ssh/authorized_keys && "
               f"printf '%s' {shlex.quote(identity_json)} "
               "> ~/.stpu_agent/cluster.json && "
               f"printf '%s' {shlex.quote(exec_token)} "
               f"> {agent_constants.EXEC_TOKEN_PATH} && "
               f"chmod 600 {agent_constants.EXEC_TOKEN_PATH}")
        if is_head:
            runner.run("mkdir -p ~/.ssh && chmod 700 ~/.ssh")
            runner.rsync(str(priv_key),
                         agent_constants.INTERNAL_KEY_PATH, up=True)
            cmd += (f" && chmod 600 {agent_constants.INTERNAL_KEY_PATH}"
                    " && " + _AGENT_START_CMD)
        elif info.provider_name == "kubernetes":
            # Worker pods run the exec agent instead of sshd: the gang
            # driver reaches them over the pod network with the token.
            cmd += " && " + _EXEC_AGENT_START_CMD
        # Version stamp LAST (after the daemon [re]start on the head):
        # a partial bring-up must read as stale so the next reuse
        # repairs it.
        cmd += (f" && printf '%s' {shlex.quote(version)} "
                f"> {agent_constants.RUNTIME_VERSION_PATH}")
        rc = runner.run(cmd)
        runner.check_returncode(rc, "agent bring-up",
                                f"host {inst.instance_id}")
    with cf.ThreadPoolExecutor(max_workers=min(32,
                                               len(instances))) as pool:
        list(pool.map(bring_up, instances))
