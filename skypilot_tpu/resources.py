"""Resource requirements: a TPU slice (or CPU VM) in a zone, at a price.

Reference analog: sky/resources.py (Resources:30, _set_accelerators:527,
_validate_and_set_region_zone:600, get_cost:982, less_demanding_than:1078,
from_yaml_config:1277). The TPU-native difference: the schedulable unit is a
*slice* — ``accelerator='tpu-v5p-64'`` implies the host VMs (8 hosts × 4
chips), their gang membership, and the ICI domain. There is no separate
"instance_type + accelerator count" pair for TPU resources; for CPU-only
tasks (controllers, data prep) ``instance_type`` picks a plain VM.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import catalog
from skypilot_tpu import exceptions

# Default TPU VM software version per generation (public runtime names).
_DEFAULT_RUNTIME = {
    "v2": "tpu-ubuntu2204-base",
    "v3": "tpu-ubuntu2204-base",
    "v4": "tpu-ubuntu2204-base",
    "v5e": "v2-alpha-tpuv5-lite",
    "v5p": "v2-alpha-tpuv5",
    "v6e": "v2-alpha-tpuv6e",
}

_DEFAULT_DISK_SIZE_GB = 256


@dataclasses.dataclass(frozen=True)
class Resources:
    """Immutable resource spec. ``copy()`` derives variants.

    Exactly one of (accelerator, instance_type, cpus/memory floors) drives
    sizing:
      * ``accelerator``: a TPU slice name (``tpu-v5e-16``).
      * ``instance_type``: an explicit CPU VM type.
      * ``cpus``/``memory``: floors; the cheapest VM meeting them is chosen
        at optimization time (reference: Resources(cpus='4+')).

    ``cloud``: provisioning provider. None means the default real cloud
    ("gcp"); "local" targets the hermetic subprocess provider (no catalog,
    price 0) used by tests and `stpu local` workflows.
    """
    accelerator: Optional[str] = None
    cloud: Optional[str] = None
    instance_type: Optional[str] = None
    cpus: Optional[Union[int, str]] = None      # 4 or "4+"
    memory: Optional[Union[float, str]] = None  # GB, 16 or "16+"
    region: Optional[str] = None
    zone: Optional[str] = None
    use_spot: bool = False
    spot_recovery: Optional[str] = None         # e.g. "EAGER_NEXT_REGION"
    disk_size: int = _DEFAULT_DISK_SIZE_GB
    image_id: Optional[str] = None
    runtime_version: Optional[str] = None       # TPU software version
    ports: tuple = ()
    labels: Optional[Dict[str, str]] = None
    autostop: Optional[int] = None              # idle minutes; -1 = down
    job_recovery: Optional[str] = None

    # ------------------------------------------------------------------
    def __post_init__(self):
        if self.cloud is not None:
            from skypilot_tpu import clouds as clouds_lib
            if self.cloud not in clouds_lib.CLOUD_REGISTRY:
                raise exceptions.InvalidTaskError(
                    f"Unknown cloud {self.cloud!r}; supported: "
                    f"{', '.join(clouds_lib.registered_names())}")
        if self.cloud in ("local", "docker"):
            return  # no catalog validation for these providers
        if self.cloud == "kubernetes":
            # Placement is the cluster itself: no zones to validate.
            # Accelerator names still canonicalize so slice_info()
            # (hosts/chips topology math) works for named TPU slices.
            if self.accelerator is not None:
                from skypilot_tpu.utils import accelerator_registry
                object.__setattr__(
                    self, "accelerator",
                    accelerator_registry.canonicalize_accelerator_name(
                        self.accelerator))
            return
        if self.accelerator is not None:
            # Normalize user spellings (V5E-8, tpu_v5e_8, v5litepod-8)
            # to the canonical catalog name, validating against it.
            from skypilot_tpu.utils import accelerator_registry
            object.__setattr__(
                self, "accelerator",
                accelerator_registry.canonicalize_accelerator_name(
                    self.accelerator))
            if self.instance_type is not None:
                raise exceptions.InvalidTaskError(
                    "accelerator and instance_type are mutually exclusive "
                    "for TPU resources: the slice implies its host VMs.")
        if self.zone is not None and self.region is not None:
            if not self.zone.startswith(self.region):
                raise exceptions.InvalidTaskError(
                    f"zone {self.zone!r} is not in region {self.region!r}")
        if self.zone is not None and self.region is None:
            object.__setattr__(self, "region", self.zone.rsplit("-", 1)[0])
        self._validate_catalog_placement()

    def _validate_catalog_placement(self):
        if self.accelerator is not None:
            zones = catalog.tpu_zones(self.accelerator, region=self.region)
            if self.region is not None and not zones:
                raise exceptions.InvalidTaskError(
                    f"{self.accelerator} is not offered in region "
                    f"{self.region}; offered in "
                    f"{catalog.tpu_regions(self.accelerator)}")
            if self.zone is not None and self.zone not in \
                    catalog.tpu_zones(self.accelerator):
                raise exceptions.InvalidTaskError(
                    f"{self.accelerator} is not offered in zone "
                    f"{self.zone}; offered in "
                    f"{catalog.tpu_zones(self.accelerator)}")
        elif self.instance_type is not None:
            catalog.vm_info(self.instance_type)
            if self.zone is not None and self.zone not in \
                    catalog.vm_zones(self.instance_type):
                raise exceptions.InvalidTaskError(
                    f"{self.instance_type} not offered in zone {self.zone}")

    # ------------------------------------------------------------------
    @property
    def is_tpu(self) -> bool:
        return self.accelerator is not None

    def slice_info(self) -> Optional[catalog.SliceInfo]:
        if self.accelerator is None:
            return None
        return catalog.slice_info(self.accelerator)

    @property
    def num_hosts(self) -> int:
        """Hosts per node-unit: slice hosts for TPU, 1 for a VM."""
        info = self.slice_info()
        return info.hosts if info else 1

    @property
    def tpu_runtime_version(self) -> Optional[str]:
        if not self.is_tpu:
            return None
        if self.runtime_version:
            return self.runtime_version
        return _DEFAULT_RUNTIME[self.slice_info().generation]

    @property
    def provider_name(self) -> str:
        return self.cloud or "gcp"

    @property
    def is_launchable(self) -> bool:
        """Concrete enough to hand to the provisioner: needs a zone and a
        concrete device/VM (local provider needs neither)."""
        if self.cloud in ("local", "kubernetes", "docker"):
            return True
        return (self.zone is not None and
                (self.accelerator is not None or
                 self.instance_type is not None))

    def need_cleanup_after_preemption(self) -> bool:
        """Spot TPU slices are not auto-deleted on preemption — the managed
        jobs controller must terminate the husk (reference:
        sky/resources.py:595, sky/clouds/gcp.py:881)."""
        return self.is_tpu and self.use_spot

    # ------------------------------------------------------------------
    def hourly_price(self) -> float:
        """Price of this (concrete) resource per hour."""
        if self.cloud in ("local", "kubernetes", "docker"):
            # On-prem / pre-paid hardware: $0 marginal cost (reference
            # prices kubernetes the same way), so the optimizer prefers
            # an enabled kubernetes cluster over metered cloud TPUs.
            return 0.0
        if self.accelerator is not None:
            return catalog.tpu_price(self.accelerator, zone=self.zone,
                                     region=self.region,
                                     use_spot=self.use_spot)
        itype = self.instance_type
        if itype is None:
            itype = catalog.default_vm_for(*self._cpu_mem_floor())
        return catalog.vm_price(itype, zone=self.zone, region=self.region,
                                use_spot=self.use_spot)

    def get_cost(self, seconds: float) -> float:
        return self.hourly_price() * seconds / 3600.0

    def _cpu_mem_floor(self):
        def floor(v, default):
            if v is None:
                return default
            if isinstance(v, str):
                return float(v.rstrip("+"))
            return float(v)
        return floor(self.cpus, 0), floor(self.memory, 0)

    # ------------------------------------------------------------------
    def copy(self, **override) -> "Resources":
        return dataclasses.replace(self, **override)

    def less_demanding_than(self, other: "Resources") -> bool:
        """True if an ``other``-shaped cluster can serve this request
        (reference: sky/resources.py:1078; used by `exec` reuse checks)."""
        if self.accelerator is not None:
            if other.accelerator != self.accelerator:
                return False
        if self.instance_type is not None and \
                other.instance_type != self.instance_type:
            return False
        cpus, mem = self._cpu_mem_floor()
        if other.instance_type is not None and (cpus or mem):
            info = catalog.vm_info(other.instance_type)
            if info["vcpus"] < cpus or info["memory_gb"] < mem:
                return False
        if self.use_spot != other.use_spot:
            return False
        for field in ("region", "zone"):
            mine = getattr(self, field)
            if mine is not None and getattr(other, field) != mine:
                return False
        return True

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Optional[Dict[str, Any]]
                         ) -> "Resources":
        config = dict(config or {})
        known = {
            "accelerator", "accelerators", "instance_type", "cpus",
            "memory", "region", "zone", "use_spot", "spot_recovery",
            "disk_size", "image_id", "runtime_version", "ports", "labels",
            "autostop", "job_recovery", "any_of", "cloud",
        }
        unknown = set(config) - known
        if unknown:
            raise exceptions.InvalidTaskError(
                f"Unknown resources fields: {sorted(unknown)}")
        acc_plural = config.pop("accelerators", None)
        acc_singular = config.pop("accelerator", None)
        if acc_plural is not None and acc_singular is not None:
            raise exceptions.InvalidTaskError(
                "Specify either 'accelerators' or 'accelerator', not both.")
        acc = acc_plural if acc_plural is not None else acc_singular
        if isinstance(acc, dict):
            if len(acc) != 1:
                raise exceptions.InvalidTaskError(
                    f"Exactly one accelerator entry expected, got {acc}")
            (acc, count), = acc.items()
            if count != 1:
                raise exceptions.InvalidTaskError(
                    f"TPU slices have count 1 (the size is in the name); "
                    f"got {acc}: {count}. Want more chips? Pick a bigger "
                    f"slice (e.g. tpu-v5e-32) or more num_nodes (slices).")
        ports = config.pop("ports", ()) or ()
        if isinstance(ports, (int, str)):
            ports = (ports,)
        config.pop("any_of", None)  # handled by Task.set_resources
        return cls(accelerator=acc, ports=tuple(str(p) for p in ports),
                   **config)

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.accelerator is not None:
            out["accelerators"] = self.accelerator
        for field in ("cloud", "instance_type", "cpus", "memory", "region",
                      "zone", "spot_recovery", "image_id",
                      "runtime_version", "labels", "autostop",
                      "job_recovery"):
            val = getattr(self, field)
            if val is not None:
                out[field] = val
        if self.use_spot:
            out["use_spot"] = True
        if self.disk_size != _DEFAULT_DISK_SIZE_GB:
            out["disk_size"] = self.disk_size
        if self.ports:
            out["ports"] = list(self.ports)
        return out

    def __repr__(self) -> str:
        parts: List[str] = []
        if self.accelerator:
            info = self.slice_info()
            parts.append(f"{self.accelerator}"
                         f"[{info.chips}chips/{info.hosts}hosts]")
        if self.instance_type:
            parts.append(self.instance_type)
        if self.cpus:
            parts.append(f"cpus={self.cpus}")
        if self.use_spot:
            parts.append("[spot]")
        if self.zone:
            parts.append(self.zone)
        elif self.region:
            parts.append(self.region)
        return f"Resources({', '.join(parts) or 'cheapest'})"
