"""Blockwise (flash) attention as Pallas TPU kernels, fwd and bwd.

Forward: online-softmax over KV blocks, working set held in VMEM, logits
never materialized in HBM (O(S*D) traffic instead of O(S^2)).

Backward: two Pallas kernels from the saved (q, k, v, o, lse) — a dq
kernel gridded over Q blocks (inner loop over KV blocks) and a dk/dv
kernel gridded over KV blocks (inner loop over Q blocks), the standard
flash-attention-2 split so each output block has a single writer and no
cross-block reduction. delta = rowsum(do*o) is recomputed in-kernel from
the o/do blocks. Causal runs skip fully-masked block pairs on both sides.

Supports causal masking and GQA (n_heads % n_kv_heads == 0): the backward
computes per-query-head dk/dv and the group-sum back to KV heads happens
in XLA outside the kernel.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


LSE_PAD = 8  # trailing tile dim for the lse output (tiling constraint)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale: float, block_k: int, causal: bool, seq_len: int):
    # Refs are rank-reduced by the None dims in the BlockSpecs:
    # q_ref/o_ref: (block_q, d); k_ref/v_ref: (seq_len, d);
    # lse_ref: (block_q, LSE_PAD)
    qi = pl.program_id(2)
    q = q_ref[...].astype(jnp.float32) * scale  # (bq, D)
    bq, d = q.shape
    q_start = qi * bq

    if causal:
        # Only KV blocks at or before the end of this Q block contribute.
        n_blocks = lax.div(q_start + bq + block_k - 1, block_k)
    else:
        n_blocks = seq_len // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = q_start + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                      (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((bq, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((bq, d), dtype=jnp.float32)
    m, l, acc = lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    # lse block is (block_q, LSE_PAD): broadcast across the pad dim, which
    # exists only to satisfy the (8,128)-ish tiling constraint on outputs.
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l),
                                    (bq, lse_ref.shape[-1]))


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
               causal: bool, scale: float,
               block_q: int, block_k: int,
               keep_lse_pad: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b, h, s // block_q)

    # Kernel operates in (B, H, S, D) layout so the last two dims of every
    # block are MXU/VPU-tileable (S and D); XLA fuses the transposes into
    # the surrounding projections.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                          causal=causal, seq_len=s),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LSE_PAD), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() == "cpu",
    )(qt, kt, vt)
    # keep_lse_pad: the (B,H,S,LSE_PAD) layout feeds the bwd kernels
    # directly (already lane-tileable); [..., 0] is the logical value.
    return out.transpose(0, 2, 1, 3), (lse if keep_lse_pad
                                       else lse[..., 0])


def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *,
               scale: float, block_k: int, causal: bool, seq_len: int):
    # q/o/do/dq_ref: (block_q, d); k/v_ref: (seq_len, d);
    # lse_ref: (block_q, LSE_PAD)
    qi = pl.program_id(2)
    q = q_ref[...].astype(jnp.float32) * scale
    do = do_ref[...].astype(jnp.float32)
    o = o_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, 0:1]                       # (bq, 1)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # (bq, 1)
    bq, d = q.shape
    q_start = qi * bq
    if causal:
        n_blocks = lax.div(q_start + bq + block_k - 1, block_k)
    else:
        n_blocks = seq_len // block_k

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = q_start + lax.broadcasted_iota(jnp.int32, (bq, block_k),
                                                  0)
            kpos = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                      (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((bq, d), dtype=jnp.float32)
    dq = lax.fori_loop(0, n_blocks, body, dq0)
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                dk_ref, dv_ref, *, scale: float, block_q: int,
                causal: bool, seq_len: int, groups: int):
    # k/v/dk/dv_ref: (block_k, d); q/o/do_ref: (seq_len, d);
    # lse_ref: (seq_len, LSE_PAD). Grid is (batch, kv_block, head) with
    # head fastest, so the `groups` query heads of one KV head hit the
    # same (bi, hi // groups, ki) output block on consecutive steps and
    # the GQA group-sum happens by accumulating into the resident block
    # — no per-query-head (B,H,S,D) gradient ever reaches HBM.
    ki = pl.program_id(1)
    hi = pl.program_id(2)
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    bk, d = k.shape
    k_start = ki * bk
    nq = seq_len // block_q
    i0 = lax.div(k_start, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :].astype(
            jnp.float32) * scale
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        o = o_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :][:, 0:1]
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            kpos = k_start + lax.broadcasted_iota(jnp.int32,
                                                  (block_q, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        # dv += p^T @ do ; dk += ds^T @ (q*scale)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), dtype=jnp.float32)
    dk, dv = lax.fori_loop(i0, nq, body, (z, z))

    first_in_group = hi % groups == 0

    @pl.when(first_in_group)
    def _():
        dk_ref[...] = dk
        dv_ref[...] = dv

    @pl.when(jnp.logical_not(first_in_group))
    def _():
        dk_ref[...] += dk
        dv_ref[...] += dv


def _flash_bwd(res, do, *, causal: bool, scale: float,
               block_q: int, block_k: int):
    q, k, v, o, lse_pad = res
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    interpret = jax.default_backend() == "cpu"

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot = o.transpose(0, 2, 1, 3)
    dot_ = do.transpose(0, 2, 1, 3)

    qspec = pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, i: (bi, hi, i, 0))
    kv_full = pl.BlockSpec((None, None, s, d),
                           lambda bi, hi, i: (bi, hi // groups, 0, 0))
    lse_q = pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, i: (bi, hi, i, 0))

    dqt = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_k=block_k,
                          causal=causal, seq_len=s),
        grid=(b, h, s // block_q),
        in_specs=[qspec, kv_full, kv_full, qspec, qspec, lse_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse_pad)

    # Grid (batch, kv_block, head), head fastest: the group's heads
    # accumulate into the same resident (B,KVH,S,D) output block.
    kvspec = pl.BlockSpec((None, None, block_k, d),
                          lambda bi, i, hi: (bi, hi // groups, i, 0))
    fullq_h = pl.BlockSpec((None, None, s, d),
                           lambda bi, i, hi: (bi, hi, 0, 0))
    lse_h = pl.BlockSpec((None, None, s, LSE_PAD),
                         lambda bi, i, hi: (bi, hi, 0, 0))
    dkt, dvt = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                          causal=causal, seq_len=s, groups=groups),
        grid=(b, s // block_k, h),
        in_specs=[fullq_h, kvspec, kvspec, fullq_h, fullq_h, lse_h],
        out_specs=[kvspec, kvspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse_pad)

    dq = dqt.transpose(0, 2, 1, 3)
    dk = dkt.transpose(0, 2, 1, 3)
    dv = dvt.transpose(0, 2, 1, 3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal=causal, scale=scale,
                        block_q=block_q, block_k=block_k)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse_pad = _flash_fwd(q, k, v, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              keep_lse_pad=True)
    return out, (q, k, v, out, lse_pad)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, do):
    return _flash_bwd(res, do, causal=causal, scale=scale,
                      block_q=block_q, block_k=block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Flash attention. q: (B,S,H,D); k,v: (B,S,KVH,D)."""
    b, s, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if (k.shape[1] != s or s % block_q or s % block_k or h % k.shape[2] or
            block_q % 8 or block_k % 8 or d % 8):
        # Irregular/misaligned shapes: fall back to the XLA reference path
        # (Mosaic requires 8-sublane-aligned blocks).
        from skypilot_tpu.ops import attention as attention_ops
        return attention_ops._reference_attention(q, k, v, causal=causal,
                                                  scale=scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)
