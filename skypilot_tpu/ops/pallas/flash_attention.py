"""Blockwise (flash) attention as a Pallas TPU kernel.

Forward pass is a Pallas kernel: online-softmax over KV blocks, working set
held in VMEM, logits never materialized in HBM (O(S*D) traffic instead of
O(S^2)). Backward pass is a custom VJP computed blockwise with `lax.scan`
in plain XLA from the saved (q, k, v, o, lse): memory stays O(S*block_k)
and every contraction is an MXU-shaped matmul. (A fully-Pallas backward is
a later optimization; the fwd kernel is where the S^2 HBM win is.)

Supports causal masking and GQA (n_heads % n_kv_heads == 0).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


LSE_PAD = 8  # trailing tile dim for the lse output (tiling constraint)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale: float, block_k: int, causal: bool, seq_len: int):
    # Refs are rank-reduced by the None dims in the BlockSpecs:
    # q_ref/o_ref: (block_q, d); k_ref/v_ref: (seq_len, d);
    # lse_ref: (block_q, LSE_PAD)
    qi = pl.program_id(2)
    q = q_ref[...].astype(jnp.float32) * scale  # (bq, D)
    bq, d = q.shape
    q_start = qi * bq

    if causal:
        # Only KV blocks at or before the end of this Q block contribute.
        n_blocks = lax.div(q_start + bq + block_k - 1, block_k)
    else:
        n_blocks = seq_len // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = q_start + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                      (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((bq, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((bq, d), dtype=jnp.float32)
    m, l, acc = lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    # lse block is (block_q, LSE_PAD): broadcast across the pad dim, which
    # exists only to satisfy the (8,128)-ish tiling constraint on outputs.
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l),
                                    (bq, lse_ref.shape[-1]))


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
               causal: bool, scale: float,
               block_q: int, block_k: int
               ) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b, h, s // block_q)

    # Kernel operates in (B, H, S, D) layout so the last two dims of every
    # block are MXU/VPU-tileable (S and D); XLA fuses the transposes into
    # the surrounding projections.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                          causal=causal, seq_len=s),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LSE_PAD), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() == "cpu",
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def _bwd_blockwise(res, do, *, causal: bool, scale: float, block_k: int):
    """Flash-style backward in XLA: scan over KV blocks, O(S*block_k) mem."""
    q, k, v, o, lse = res
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_k = min(block_k, s)
    nk = s // block_k

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    # delta = rowsum(do * o): (B, S, H)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)
    # expand kv heads to full heads for per-head math
    kf = jnp.repeat(k.astype(jnp.float32), groups, axis=2)  # (B,S,H,D)
    vf = jnp.repeat(v.astype(jnp.float32), groups, axis=2)

    qpos = jnp.arange(s)

    def block(j):
        ks = jax.lax.dynamic_slice_in_dim(kf, j * block_k, block_k, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(vf, j * block_k, block_k, axis=1)
        s_blk = jnp.einsum("bqhd,bkhd->bhqk", qf, ks) * scale
        if causal:
            kpos = j * block_k + jnp.arange(block_k)
            mask = qpos[:, None] >= kpos[None, :]
            s_blk = jnp.where(mask[None, None], s_blk, _NEG_INF)
        p = jnp.exp(s_blk - lse[:, :, :, None])  # (B,H,Q,K)
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vs)
        ds = p * (dp - delta.transpose(0, 2, 1)[:, :, :, None]) * scale
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds, ks)
        return dq_blk, dk_blk, dv_blk

    def body(carry, j):
        dq = carry
        dq_blk, dk_blk, dv_blk = block(j)
        return dq + dq_blk, (dk_blk, dv_blk)

    dq0 = jnp.zeros((b, s, h, d), dtype=jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(body, dq0, jnp.arange(nk))
    # (nk, B, bk, H, D) -> (B, S, H, D)
    dk = dk_blocks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    dv = dv_blocks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    # reduce grouped heads back to kv heads
    dk = dk.reshape(b, s, kvh, groups, d).sum(axis=3)
    dv = dv.reshape(b, s, kvh, groups, d).sum(axis=3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal=causal, scale=scale,
                        block_q=block_q, block_k=block_k)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, do):
    return _bwd_blockwise(res, do, causal=causal, scale=scale,
                          block_k=block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Flash attention. q: (B,S,H,D); k,v: (B,S,KVH,D)."""
    b, s, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if (k.shape[1] != s or s % block_q or s % block_k or h % k.shape[2] or
            block_q % 8 or block_k % 8 or d % 8):
        # Irregular/misaligned shapes: fall back to the XLA reference path
        # (Mosaic requires 8-sublane-aligned blocks).
        from skypilot_tpu.ops import attention as attention_ops
        return attention_ops._reference_attention(q, k, v, causal=causal,
                                                  scale=scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)
