"""Blockwise (flash) attention as Pallas TPU kernels, fwd and bwd.

Forward: online-softmax with the KV loop as a *grid dimension* — each
step stages one (block_k, d) tile into VMEM and carries (m, l, acc) in
VMEM scratch, so the working set is O(block) regardless of sequence
length (64k+ sequences compile; an in-kernel full-K load would blow VMEM
past ~8k). Logits never touch HBM.

Backward: two kernels from the saved (q, k, v, o, lse) — a dq kernel
gridded (batch, head, q_block, kv_block) and a dk/dv kernel gridded
(batch, kv_block, head, q_block), the flash-attention-2 split so each
output block has a single writer. delta = rowsum(do*o) is recomputed
in-kernel. Causal runs skip fully-masked block pairs via predicated
compute on the grid.

GQA (n_heads % n_kv_heads == 0): the dk/dv kernel orders the grid so one
KV head's query-head group and all q blocks are consecutive steps; the
group-sum accumulates in VMEM scratch and writes (B, KVH, S, D) once —
no per-query-head gradient reaches HBM.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
# Softmax runs in the exp2 domain: log2(e) is folded into the logit
# scale once, so every per-element transcendental is exp2 (cheaper on
# the VPU than exp) and the lse carries base-2 values end-to-end
# (fwd and bwd agree; nothing outside the kernel pair reads lse).
_LOG2E = 1.4426950408889634
# Measured on v5e (16L, GQA 16/8, d=128, seq 8k): 1024x1024 blocks run
# fwd+bwd 2.7x faster than 256x256 — the streamed grid's per-step cost
# dominates at small blocks. 2048-wide q blocks blow VMEM (scores are
# block_q x block_k f32).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024

LSE_PAD = 8    # trailing tile dim for the lse output (tiling constraint)
_STAT = 128    # lane width for the (m, l) scratch carries


def _causal_mask(s, q_start, k_start):
    bq, bk = s.shape
    qpos = q_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, _NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool):
    # Blocks: q/o (bq, d); k/v (bk, d); lse (bq, LSE_PAD).
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    q_start = qi * bq
    k_start = ki * bk

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: a KV block right of the Q block's last row contributes
    # nothing — skip its compute (the fetch already happened).
    run = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(run)
    def _step():
        # MXU dots take bf16 INPUTS (f32 accumulate via
        # preferred_element_type): casting inputs to f32 first would run
        # the matmuls at the fp32 rate, ~4x below bf16 peak on v5e.
        # Scale applies after the dot, in f32.
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            s = _causal_mask(s, q_start, k_start)
        m_prev = m_scr[...][:, 0:1]
        l_prev = l_scr[...][:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[...][:, 0:1], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[...] = jnp.broadcast_to(
            m_scr[...][:, 0:1] + jnp.log(l), lse_ref.shape)


def _flash_fwd_streamed(q: jax.Array, k: jax.Array, v: jax.Array, *,
               causal: bool, scale: float,
               block_q: int, block_k: int,
               keep_lse_pad: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b, h, s // block_q, s // block_k)

    # Kernel operates in (B, H, S, D) layout so the last two dims of every
    # block are MXU/VPU-tileable (S and D); XLA fuses the transposes into
    # the surrounding projections.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    # Causal DMA elision: a KV block fully right of the Q block is
    # skipped by the kernel's pl.when — clamping its index to the causal
    # bound makes the "fetch" re-reference the previous block, which
    # Pallas elides (same index => no copy), so masked grid steps cost
    # neither compute nor HBM traffic.
    if causal:
        def _kv_idx(bi, hi, qi, ki):
            bound = (qi * block_q + block_q - 1) // block_k
            return (bi, hi // groups, jnp.minimum(ki, bound), 0)
    else:
        def _kv_idx(bi, hi, qi, ki):
            return (bi, hi // groups, ki, 0)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_k, d), _kv_idx),
            pl.BlockSpec((None, None, block_k, d), _kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LSE_PAD), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _STAT), jnp.float32),
            pltpu.VMEM((block_q, _STAT), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=jax.default_backend() == "cpu",
    )(qt, kt, vt)
    # keep_lse_pad: the (B,H,S,LSE_PAD) layout feeds the bwd kernels
    # directly (already lane-tileable); [..., 0] is the logical value.
    return out.transpose(0, 2, 1, 3), (lse if keep_lse_pad
                                       else lse[..., 0])


def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
               dq_scr, delta_scr, *, scale: float, causal: bool):
    # Blocks: q/o/do/dq (bq, d); k/v (bk, d); lse (bq, LSE_PAD).
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    q_start = qi * bq
    k_start = ki * bk

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)
        # delta depends only on the q block — compute once, not per
        # KV step (nk can be 256+ on the long-context path).
        do = do_ref[...].astype(jnp.float32)
        o = o_ref[...].astype(jnp.float32)
        delta_scr[...] = jnp.broadcast_to(
            jnp.sum(do * o, axis=-1, keepdims=True), delta_scr.shape)

    run = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(run)
    def _step():
        # bf16 MXU inputs, f32 accumulate (see _fwd_kernel).
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][:, 0:1]
        delta = delta_scr[...][:, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[...] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool, groups: int):
    # Grid (batch, kv_block, head, q_block): for one KV-head group the
    # `groups * nq` innermost steps hit the same (bi, hi//groups, ki)
    # output block; dk/dv accumulate in scratch (the GQA group-sum) and
    # write once at the group's final step.
    ki, hi, qi = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    q_start = qi * bq
    k_start = ki * bk

    first = jnp.logical_and(hi % groups == 0, qi == 0)

    @pl.when(first)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (q_start + bq - 1 >= k_start) if causal else True

    @pl.when(run)
    def _step():
        # bf16 MXU inputs, f32 accumulate (see _fwd_kernel).
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        o = o_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, 0:1]
        delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1,
                        keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp(s - lse)
        # dv += p^T @ do ; dk += ds^T @ q (scale folded in at _finish)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    last = jnp.logical_and(hi % groups == groups - 1, qi == nq - 1)

    @pl.when(last)
    def _finish():
        dk_ref[...] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_streamed(res, do, *, causal: bool, scale: float,
               block_q: int, block_k: int):
    q, k, v, o, lse_pad = res
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    nq, nk = s // block_q, s // block_k
    interpret = jax.default_backend() == "cpu"

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot = o.transpose(0, 2, 1, 3)
    dot_ = do.transpose(0, 2, 1, 3)

    qspec = pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    if causal:
        # Same DMA elision as the forward: skipped KV blocks re-fetch
        # the previous index (no copy) instead of staging dead data.
        def _kv_idx(bi, hi, qi, ki):
            bound = (qi * block_q + block_q - 1) // block_k
            return (bi, hi // groups, jnp.minimum(ki, bound), 0)
        kvspec = pl.BlockSpec((None, None, block_k, d), _kv_idx)
    else:
        kvspec = pl.BlockSpec(
            (None, None, block_k, d),
            lambda bi, hi, qi, ki: (bi, hi // groups, ki, 0))
    lse_q = pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0))

    dqt = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal),
        grid=(b, h, nq, nk),
        in_specs=[qspec, kvspec, kvspec, qspec, qspec, lse_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, _STAT), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse_pad)

    # Grid (batch, kv_block, head, q_block): head × q_block innermost so
    # one KV head's whole group accumulates into the resident output.
    if causal:
        # Mirror-image elision: Q blocks BEFORE the KV block are masked;
        # clamp from below so they re-fetch instead of staging dead
        # data. One index fn serves q/o/do AND lse so their blocks can
        # never desynchronize.
        def _q_idx(bi, ki, hi, qi):
            lo = (ki * block_k) // block_q
            return (bi, hi, jnp.maximum(qi, lo), 0)
    else:
        def _q_idx(bi, ki, hi, qi):
            return (bi, hi, qi, 0)
    q_h = pl.BlockSpec((None, None, block_q, d), _q_idx)
    kv_h = pl.BlockSpec((None, None, block_k, d),
                        lambda bi, ki, hi, qi: (bi, hi // groups, ki, 0))
    lse_h = pl.BlockSpec((None, None, block_q, LSE_PAD), _q_idx)
    dkt, dvt = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          groups=groups),
        grid=(b, nk, h, nq),
        in_specs=[q_h, kv_h, kv_h, q_h, q_h, lse_h],
        out_specs=[kv_h, kv_h],
        out_shape=[
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse_pad)

    dq = dqt.transpose(0, 2, 1, 3)
    dk = dkt.transpose(0, 2, 1, 3)
    dv = dvt.transpose(0, 2, 1, 3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)



# --------------------------------------------------------------------------
# Triangular-grid causal family (streamed): the (q_block, kv_block) pairs
# above the causal diagonal are NEVER SCHEDULED — the grid's last dim
# enumerates only the lower-triangle pairs, with the (qi, ki) coordinates
# delivered through scalar prefetch (splash-attention style). Two wins
# over predicating a rectangular grid: masked pairs cost zero grid steps,
# and interior (fully-unmasked) pairs skip the iota/compare/select mask
# entirely — only diagonal-straddling blocks pay it.
# --------------------------------------------------------------------------


def _tri_maps_row(nq: int, nk: int, block_q: int, block_k: int):
    """Row-major (qi, ki) pairs with any unmasked element:
    k_start <= q_start + block_q - 1."""
    qs, ks = [], []
    for qi in range(nq):
        bound = min(nk - 1, (qi * block_q + block_q - 1) // block_k)
        for ki in range(bound + 1):
            qs.append(qi)
            ks.append(ki)
    return (np.asarray(qs, np.int32), np.asarray(ks, np.int32))


def _tri_maps_col(nq: int, nk: int, block_q: int, block_k: int,
                  n_heads: int):
    """Column-major (ki, hi, qi) triples for the dk/dv kernel: for each
    KV block, every query head's unmasked q blocks are consecutive so
    the GQA group-sum accumulates in resident scratch."""
    kks, hhs, qqs = [], [], []
    for ki in range(nk):
        lo = (ki * block_k) // block_q
        for hi in range(n_heads):
            for qi in range(lo, nq):
                kks.append(ki)
                hhs.append(hi)
                qqs.append(qi)
    return (np.asarray(kks, np.int32), np.asarray(hhs, np.int32),
            np.asarray(qqs, np.int32))


def _fwd_kernel_tri(qmap, kmap, q_ref, k_ref, v_ref, o_ref, lse_ref,
                    m_scr, l_scr, acc_scr, *, scale: float,
                    block_q: int, block_k: int, nk: int):
    t = pl.program_id(2)
    qi = qmap[t]
    ki = kmap[t]
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    q_start = qi * bq
    k_start = ki * bk
    bound = jnp.minimum(nk - 1, lax.div(q_start + bq - 1, block_k))

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _step(masked: bool):
        # bf16 MXU inputs, f32 accumulate (preferred_element_type).
        # q arrives PRE-SCALED by scale*log2e (folded in outside the
        # kernel): the per-element s*scale pass over the (bq, bk) score
        # tile — a full VPU/VMEM sweep per grid step — disappears.
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            s = _causal_mask(s, q_start, k_start)
        m_prev = m_scr[...][:, 0:1]
        l_prev = l_scr[...][:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    # Only a block straddling the diagonal needs the mask; interior
    # blocks (k_end - 1 <= q_start) skip the iota/compare/select.
    diag = k_start + bk - 1 > q_start

    @pl.when(diag)
    def _():
        _step(masked=True)

    @pl.when(jnp.logical_not(diag))
    def _():
        _step(masked=False)

    @pl.when(ki == bound)
    def _finish():
        l = jnp.maximum(l_scr[...][:, 0:1], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        # lse in BASE-2 domain (matches the exp2 softmax above; the bwd
        # kernels below consume the same convention).
        lse_ref[...] = jnp.broadcast_to(
            m_scr[...][:, 0:1] + jnp.log2(l), lse_ref.shape)


def _flash_fwd_tri(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   scale: float, block_q: int, block_k: int,
                   keep_lse_pad: bool = False
                   ) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    nq, nk = s // block_q, s // block_k
    qmap, kmap = _tri_maps_row(nq, nk, block_q, block_k)

    # Logit scale (and the exp2-domain log2e) folded into q ONCE here —
    # XLA fuses the scalar mul into the transpose — instead of a
    # per-step elementwise pass over every (bq, bk) score tile.
    qt = (q * (scale * _LOG2E)).astype(q.dtype).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, h, len(qmap))

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_tri, scale=scale, block_q=block_q,
                          block_k=block_k, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, None, block_q, d),
                             lambda bi, hi, t, qm, km: (bi, hi, qm[t], 0)),
                pl.BlockSpec(
                    (None, None, block_k, d),
                    lambda bi, hi, t, qm, km: (bi, hi // groups,
                                               km[t], 0)),
                pl.BlockSpec(
                    (None, None, block_k, d),
                    lambda bi, hi, t, qm, km: (bi, hi // groups,
                                               km[t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, None, block_q, d),
                             lambda bi, hi, t, qm, km: (bi, hi, qm[t], 0)),
                pl.BlockSpec((None, None, block_q, LSE_PAD),
                             lambda bi, hi, t, qm, km: (bi, hi, qm[t], 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, _STAT), jnp.float32),
                pltpu.VMEM((block_q, _STAT), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LSE_PAD), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() == "cpu",
    )(jnp.asarray(qmap), jnp.asarray(kmap), qt, kt, vt)
    return out.transpose(0, 2, 1, 3), (lse if keep_lse_pad
                                       else lse[..., 0])


def _dq_kernel_tri(qmap, kmap, q_ref, k_ref, v_ref, o_ref, do_ref,
                   lse_ref, dq_ref, dq_scr, delta_scr, *, scale: float,
                   block_q: int, block_k: int, nk: int):
    t = pl.program_id(2)
    qi = qmap[t]
    ki = kmap[t]
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    q_start = qi * bq
    k_start = ki * bk
    bound = jnp.minimum(nk - 1, lax.div(q_start + bq - 1, block_k))

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)
        do = do_ref[...].astype(jnp.float32)
        o = o_ref[...].astype(jnp.float32)
        delta_scr[...] = jnp.broadcast_to(
            jnp.sum(do * o, axis=-1, keepdims=True), delta_scr.shape)

    def _step(masked: bool):
        # q arrives pre-scaled by scale*log2e (see _flash_bwd_tri).
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][:, 0:1]
        delta = delta_scr[...][:, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp2(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    diag = k_start + bk - 1 > q_start

    @pl.when(diag)
    def _():
        _step(masked=True)

    @pl.when(jnp.logical_not(diag))
    def _():
        _step(masked=False)

    @pl.when(ki == bound)
    def _finish():
        dq_ref[...] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel_tri(kmap, hmap, qmap, q_ref, k_ref, v_ref, o_ref,
                    do_ref, lse_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    scale: float, block_q: int, block_k: int, nq: int,
                    groups: int):
    t = pl.program_id(1)
    ki = kmap[t]
    hi = hmap[t]
    qi = qmap[t]
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    q_start = qi * bq
    k_start = ki * bk
    lo = lax.div(k_start, block_q)

    first = jnp.logical_and(hi % groups == 0, qi == lo)

    @pl.when(first)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _step(masked: bool):
        # q arrives pre-scaled by c = scale*log2e; dk accumulates
        # ds^T @ (c*q), so _finish divides the c back out and applies
        # the true logit scale in one constant.
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        o = o_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, 0:1]
        delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1,
                        keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp2(s - lse)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # Mask needed while the q block's first row precedes the KV block's
    # last column.
    diag = q_start < k_start + bk - 1

    @pl.when(diag)
    def _():
        _step(masked=True)

    @pl.when(jnp.logical_not(diag))
    def _():
        _step(masked=False)

    last = jnp.logical_and(hi % groups == groups - 1, qi == nq - 1)

    @pl.when(last)
    def _finish():
        # scale / (scale*log2e) = 1/log2e: undo the q pre-scale, apply
        # the logit scale.
        dk_ref[...] = (dk_scr[...] * (1.0 / _LOG2E)).astype(
            dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_tri(res, do, *, scale: float, block_q: int,
                   block_k: int):
    q, k, v, o, lse_pad = res
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    nq, nk = s // block_q, s // block_k
    interpret = jax.default_backend() == "cpu"

    # Same q pre-scale as the tri forward (kills the per-step s*scale
    # pass); the dkv kernel's _finish divides the factor back out of dk.
    qt = (q * (scale * _LOG2E)).astype(q.dtype).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot = o.transpose(0, 2, 1, 3)
    dot_ = do.transpose(0, 2, 1, 3)

    qmap, kmap = _tri_maps_row(nq, nk, block_q, block_k)
    qspec = pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, t, qm, km: (bi, hi, qm[t], 0))
    kvspec = pl.BlockSpec(
        (None, None, block_k, d),
        lambda bi, hi, t, qm, km: (bi, hi // groups, km[t], 0))
    lse_q = pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, t, qm, km: (bi, hi, qm[t], 0))

    dqt = pl.pallas_call(
        functools.partial(_dq_kernel_tri, scale=scale, block_q=block_q,
                          block_k=block_k, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, len(qmap)),
            in_specs=[qspec, kvspec, kvspec, qspec, qspec, lse_q],
            out_specs=qspec,
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                            pltpu.VMEM((block_q, _STAT), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(qmap), jnp.asarray(kmap), qt, kt, vt, ot, dot_,
      lse_pad)

    kmap3, hmap3, qmap3 = _tri_maps_col(nq, nk, block_q, block_k, h)
    q_h = pl.BlockSpec(
        (None, None, block_q, d),
        lambda bi, t, km, hm, qm: (bi, hm[t], qm[t], 0))
    kv_h = pl.BlockSpec(
        (None, None, block_k, d),
        lambda bi, t, km, hm, qm: (bi, hm[t] // groups, km[t], 0))
    lse_h = pl.BlockSpec(
        (None, None, block_q, LSE_PAD),
        lambda bi, t, km, hm, qm: (bi, hm[t], qm[t], 0))
    dkt, dvt = pl.pallas_call(
        functools.partial(_dkv_kernel_tri, scale=scale, block_q=block_q,
                          block_k=block_k, nq=nq, groups=groups),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, len(kmap3)),
            in_specs=[q_h, kv_h, kv_h, q_h, q_h, lse_h],
            out_specs=[kv_h, kv_h],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(kmap3), jnp.asarray(hmap3), jnp.asarray(qmap3),
      qt, kt, vt, ot, dot_, lse_pad)

    dq = dqt.transpose(0, 2, 1, 3)
    dk = dkt.transpose(0, 2, 1, 3)
    dv = dvt.transpose(0, 2, 1, 3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# Resident-KV kernel family: K/V (fwd, dq) and Q/O/dO (dkv) are staged into
# VMEM once per head and reused across the in-kernel block loop — fastest
# for short/medium sequences, but the full-sequence staging caps length.
# The streamed family above keeps O(block) VMEM and scales to 64k+.
# --------------------------------------------------------------------------

def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale: float, block_k: int, causal: bool, seq_len: int):
    # Refs are rank-reduced by the None dims in the BlockSpecs:
    # q_ref/o_ref: (block_q, d); k_ref/v_ref: (seq_len, d);
    # lse_ref: (block_q, LSE_PAD)
    qi = pl.program_id(2)
    q = q_ref[...]                              # (bq, D), bf16 into MXU
    bq, d = q.shape
    q_start = qi * bq

    if causal:
        # Only KV blocks at or before the end of this Q block contribute.
        n_blocks = lax.div(q_start + bq + block_k - 1, block_k)
    else:
        n_blocks = seq_len // block_k

    def body(j, carry):
        m, l, acc = carry
        # bf16 MXU inputs, f32 accumulate; scale after the dot (casting
        # inputs to f32 would run the matmuls at the fp32 rate, ~4x
        # below bf16 peak).
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            qpos = q_start + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                      (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((bq, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((bq, d), dtype=jnp.float32)
    m, l, acc = lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    # lse block is (block_q, LSE_PAD): broadcast across the pad dim, which
    # exists only to satisfy the (8,128)-ish tiling constraint on outputs.
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l),
                                    (bq, lse_ref.shape[-1]))


def _flash_fwd_resident(q: jax.Array, k: jax.Array, v: jax.Array, *,
               causal: bool, scale: float,
               block_q: int, block_k: int,
               keep_lse_pad: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b, h, s // block_q)

    # Kernel operates in (B, H, S, D) layout so the last two dims of every
    # block are MXU/VPU-tileable (S and D); XLA fuses the transposes into
    # the surrounding projections.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_resident, scale=scale, block_k=block_k,
                          causal=causal, seq_len=s),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LSE_PAD), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=jax.default_backend() == "cpu",
    )(qt, kt, vt)
    # keep_lse_pad: the (B,H,S,LSE_PAD) layout feeds the bwd kernels
    # directly (already lane-tileable); [..., 0] is the logical value.
    return out.transpose(0, 2, 1, 3), (lse if keep_lse_pad
                                       else lse[..., 0])


def _dq_kernel_resident(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *,
               scale: float, block_k: int, causal: bool, seq_len: int):
    # q/o/do/dq_ref: (block_q, d); k/v_ref: (seq_len, d);
    # lse_ref: (block_q, LSE_PAD)
    qi = pl.program_id(2)
    q = q_ref[...]                                   # bf16 into MXU
    do = do_ref[...]
    o = o_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, 0:1]                       # (bq, 1)
    delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1,
                    keepdims=True)                   # (bq, 1)
    bq, d = q.shape
    q_start = qi * bq
    if causal:
        n_blocks = lax.div(q_start + bq + block_k - 1, block_k)
    else:
        n_blocks = seq_len // block_k

    def body(j, dq):
        # bf16 MXU inputs, f32 accumulate; scale after the dot.
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            qpos = q_start + lax.broadcasted_iota(jnp.int32, (bq, block_k),
                                                  0)
            kpos = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                      (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((bq, d), dtype=jnp.float32)
    dq = lax.fori_loop(0, n_blocks, body, dq0)
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                dk_ref, dv_ref, *, scale: float, block_q: int,
                causal: bool, seq_len: int, groups: int):
    # k/v/dk/dv_ref: (block_k, d); q/o/do_ref: (seq_len, d);
    # lse_ref: (seq_len, LSE_PAD). Grid is (batch, kv_block, head) with
    # head fastest, so the `groups` query heads of one KV head hit the
    # same (bi, hi // groups, ki) output block on consecutive steps and
    # the GQA group-sum happens by accumulating into the resident block
    # — no per-query-head (B,H,S,D) gradient ever reaches HBM.
    ki = pl.program_id(1)
    hi = pl.program_id(2)
    k = k_ref[...]                                   # bf16 into MXU
    v = v_ref[...]
    bk, d = k.shape
    k_start = ki * bk
    nq = seq_len // block_q
    i0 = lax.div(k_start, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        # bf16 MXU inputs, f32 accumulate; scale folded in after the
        # loop (dk) / after the dot (s).
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        o = o_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :][:, 0:1]
        delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1,
                        keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            kpos = k_start + lax.broadcasted_iota(jnp.int32,
                                                  (block_q, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        # dv += p^T @ do ; dk += ds^T @ q (scale applied after loop)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), dtype=jnp.float32)
    dk, dv = lax.fori_loop(i0, nq, body, (z, z))
    dk = dk * scale

    first_in_group = hi % groups == 0

    @pl.when(first_in_group)
    def _():
        dk_ref[...] = dk
        dv_ref[...] = dv

    @pl.when(jnp.logical_not(first_in_group))
    def _():
        dk_ref[...] += dk
        dv_ref[...] += dv


def _flash_bwd_resident(res, do, *, causal: bool, scale: float,
               block_q: int, block_k: int):
    q, k, v, o, lse_pad = res
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    interpret = jax.default_backend() == "cpu"

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot = o.transpose(0, 2, 1, 3)
    dot_ = do.transpose(0, 2, 1, 3)

    qspec = pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, i: (bi, hi, i, 0))
    kv_full = pl.BlockSpec((None, None, s, d),
                           lambda bi, hi, i: (bi, hi // groups, 0, 0))
    lse_q = pl.BlockSpec((None, None, block_q, LSE_PAD),
                         lambda bi, hi, i: (bi, hi, i, 0))

    dqt = pl.pallas_call(
        functools.partial(_dq_kernel_resident, scale=scale, block_k=block_k,
                          causal=causal, seq_len=s),
        grid=(b, h, s // block_q),
        in_specs=[qspec, kv_full, kv_full, qspec, qspec, lse_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse_pad)

    # Grid (batch, kv_block, head), head fastest: the group's heads
    # accumulate into the same resident (B,KVH,S,D) output block.
    kvspec = pl.BlockSpec((None, None, block_k, d),
                          lambda bi, i, hi: (bi, hi // groups, i, 0))
    fullq_h = pl.BlockSpec((None, None, s, d),
                           lambda bi, i, hi: (bi, hi, 0, 0))
    lse_h = pl.BlockSpec((None, None, s, LSE_PAD),
                         lambda bi, i, hi: (bi, hi, 0, 0))
    dkt, dvt = pl.pallas_call(
        functools.partial(_dkv_kernel_resident, scale=scale, block_q=block_q,
                          causal=causal, seq_len=s, groups=groups),
        grid=(b, s // block_k, h),
        in_specs=[fullq_h, kvspec, kvspec, fullq_h, fullq_h, lse_h],
        out_specs=[kvspec, kvspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, kvh, s, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse_pad)

    dq = dqt.transpose(0, 2, 1, 3)
    dk = dkt.transpose(0, 2, 1, 3)
    dv = dvt.transpose(0, 2, 1, 3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)




# Streamed kernels stage 3 full-seq fp32 tensors at most in the resident
# family; past this budget Mosaic runs out of VMEM, so dispatch by size.
_RESIDENT_MAX_BYTES = 6 * 1024 * 1024


def _use_resident(s: int, d: int) -> bool:
    return 3 * s * d * 4 <= _RESIDENT_MAX_BYTES


def _flash_fwd(q, k, v, *, causal, scale, block_q, block_k,
               keep_lse_pad: bool = False):
    if _use_resident(q.shape[1], q.shape[3]):
        return _flash_fwd_resident(q, k, v, causal=causal, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   keep_lse_pad=keep_lse_pad)
    if causal:
        # Long causal sequences: triangular grid — masked block pairs
        # are never scheduled. (lse is base-2 here; the tri bwd pairs
        # with it, and family dispatch is shape-deterministic so fwd
        # and bwd always agree.)
        return _flash_fwd_tri(q, k, v, scale=scale, block_q=block_q,
                              block_k=block_k,
                              keep_lse_pad=keep_lse_pad)
    return _flash_fwd_streamed(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               keep_lse_pad=keep_lse_pad)


def _flash_bwd(res, do, *, causal, scale, block_q, block_k):
    q = res[0]
    if _use_resident(q.shape[1], q.shape[3]):
        return _flash_bwd_resident(res, do, causal=causal, scale=scale,
                                   block_q=block_q, block_k=block_k)
    if causal:
        return _flash_bwd_tri(res, do, scale=scale, block_q=block_q,
                              block_k=block_k)
    return _flash_bwd_streamed(res, do, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal=causal, scale=scale,
                        block_q=block_q, block_k=block_k)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse_pad = _flash_fwd(q, k, v, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              keep_lse_pad=True)
    # Named so a remat policy can pin EXACTLY the kernel's outputs:
    # jax.checkpoint_policies.save_only_these_names("flash_out",
    # "flash_lse") makes layer-remat recompute the cheap projections but
    # never re-run the quadratic kernel itself (the bwd residuals q/k/v
    # come from the recomputed projections; o/lse from here). See
    # models/llama.py remat_policy="save_flash".
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "flash_out")
    lse_pad = checkpoint_name(lse_pad, "flash_lse")
    # q/k/v names let a larger policy tier also skip the qkv-projection
    # recompute (models/llama.py remat_policy="save_flash_qkv").
    q = checkpoint_name(q, "flash_q")
    k = checkpoint_name(k, "flash_k")
    v = checkpoint_name(v, "flash_v")
    return out, (q, k, v, out, lse_pad)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, do):
    return _flash_bwd(res, do, causal=causal, scale=scale,
                      block_q=block_q, block_k=block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Flash attention. q: (B,S,H,D); k,v: (B,S,KVH,D)."""
    b, s, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    # Halve blocks until they divide the sequence: a seq like 1536 must
    # run the kernel at 512, not fall back to the O(S^2) reference.
    while block_q > 8 and s % block_q:
        block_q //= 2
    while block_k > 8 and s % block_k:
        block_k //= 2
    if (k.shape[1] != s or s % block_q or s % block_k or h % k.shape[2] or
            block_q % 8 or block_k % 8 or d % 8):
        # Irregular/misaligned shapes: fall back to the XLA reference path
        # (Mosaic requires 8-sublane-aligned blocks).
        from skypilot_tpu.ops import attention as attention_ops
        return attention_ops._reference_attention(q, k, v, causal=causal,
                                                  scale=scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)
