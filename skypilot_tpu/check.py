"""Credential probing: which providers can we actually use?

Reference analog: sky/check.py (check:18 — probes each cloud's
credentials AND its per-capability readiness, persists the enabled set to
the state DB so the optimizer only plans over reachable clouds). Here a
"cloud" is a provision provider; each probe returns (ok, reason) and the
enabled set is persisted via global_user_state.set_enabled_clouds.
"""
from __future__ import annotations

import shutil
import subprocess
from typing import Callable, Dict, List, Tuple


def _probe_local() -> Tuple[bool, str]:
    return True, "hermetic provider (always available)"


def _probe_gcp() -> Tuple[bool, str]:
    """Usable = gcloud exists + active credentials + a project is set.

    The TPU API itself is only reachable with network access; like the
    reference we treat credential presence as 'enabled' and surface API
    errors at provision time with failover semantics."""
    if shutil.which("gcloud") is None:
        return False, "gcloud CLI not installed"
    try:
        proc = subprocess.run(
            ["gcloud", "auth", "list",
             "--filter=status:ACTIVE", "--format=value(account)"],
            capture_output=True, text=True, timeout=20)
        if proc.returncode != 0 or not proc.stdout.strip():
            return False, ("no active gcloud credentials "
                           "(run `gcloud auth login`)")
        proc = subprocess.run(
            ["gcloud", "config", "get-value", "project"],
            capture_output=True, text=True, timeout=20)
        project = proc.stdout.strip()
        if proc.returncode != 0 or not project or project == "(unset)":
            return False, ("no GCP project configured "
                           "(run `gcloud config set project ...`)")
        return True, f"project {project}"
    except (subprocess.SubprocessError, OSError) as e:
        return False, f"gcloud probe failed: {e}"


_PROBES: Dict[str, Callable[[], Tuple[bool, str]]] = {
    "local": _probe_local,
    "gcp": _probe_gcp,
}


def check(quiet: bool = False) -> List[str]:
    """Probe every provider, persist and return the enabled set."""
    from skypilot_tpu import global_user_state
    enabled = []
    for name, probe in _PROBES.items():
        ok, reason = probe()
        if ok:
            enabled.append(name)
        if not quiet:
            mark = "✓" if ok else "✗"
            print(f"  {mark} {name}: {reason}")
    global_user_state.set_enabled_clouds(enabled)
    if not quiet:
        print(f"Enabled providers: {', '.join(enabled) or '(none)'}")
    return enabled
