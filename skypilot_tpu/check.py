"""Credential probing: which providers can we actually use?

Reference analog: sky/check.py (check:18 — probes each cloud, persists
the enabled set to the state DB).
"""
from __future__ import annotations

import shutil
import subprocess
from typing import List

from skypilot_tpu import global_user_state


def _gcp_ok() -> bool:
    """True if gcloud credentials (or ADC) appear usable."""
    if shutil.which("gcloud") is None:
        return False
    try:
        proc = subprocess.run(
            ["gcloud", "auth", "list",
             "--filter=status:ACTIVE", "--format=value(account)"],
            capture_output=True, text=True, timeout=20)
        return proc.returncode == 0 and bool(proc.stdout.strip())
    except (subprocess.SubprocessError, OSError):
        return False


def check(quiet: bool = False) -> List[str]:
    enabled = ["local"]  # the hermetic provider always works
    if _gcp_ok():
        enabled.append("gcp")
    elif not quiet:
        print("GCP: no active gcloud credentials "
              "(run `gcloud auth login`); TPU provisioning disabled.")
    global_user_state.set_enabled_clouds(enabled)
    return enabled
