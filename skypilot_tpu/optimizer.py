"""Optimizer: pick the cheapest/fastest concrete placement for a DAG.

Reference analog: sky/optimizer.py (optimize:105, _optimize_by_dp:373 for
chains, _fill_in_launchable_resources:1201, egress accounting :73). The
TPU-native candidate space is (slice type, zone, spot) rows straight from
the catalog; feasibility = "slice offered in zone", with a blocklist fed
back by the provisioner's failover loop so re-optimization after exhaustion
skips known-bad placements (reference provision_with_retries:2030-2045).

Chains use exact DP over (task, candidate) with inter-task egress cost;
general DAGs use exact enumeration of the assignment space with per-edge
egress (the role the reference's ILP plays, sky/optimizer.py:434 — no ILP
solver in this image), falling back to per-task greedy min with a warning
only above GENERAL_DAG_MAX_SPACE.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Set, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources


class OptimizeTarget(enum.Enum):
    COST = "cost"
    TIME = "time"


@dataclasses.dataclass(frozen=True)
class Blocklist:
    """Placements to skip: (accelerator|instance_type, zone|region) pairs.

    ``None`` fields are wildcards: ("tpu-v5e-16", None) blocks everywhere;
    (None, "us-central1-a") blocks the zone for everything. A zoneless
    provider (kubernetes, local) is blocked with the sentinel
    ``cloud:<name>`` so its failure never wildcard-blocks the same
    accelerator on other clouds.
    """
    entries: frozenset = frozenset()

    def blocked(self, res: Resources) -> bool:
        device = res.accelerator or res.instance_type
        for (dev, where) in self.entries:
            if dev is not None and dev != device:
                continue
            if where is None:
                return True
            if res.zone == where or res.region == where:
                return True
            if where == f"cloud:{res.provider_name}":
                return True
        return False

    def add(self, device: Optional[str],
            where: Optional[str]) -> "Blocklist":
        return Blocklist(self.entries | {(device, where)})


@dataclasses.dataclass(frozen=True)
class Candidate:
    resources: Resources         # concrete: has zone
    hourly_price: float
    runtime_seconds: float

    @property
    def cost(self) -> float:
        return self.hourly_price * self.runtime_seconds / 3600.0


def _expand_one(res: Resources) -> List[Resources]:
    """All concrete zone placements of one Resources spec."""
    if res.is_launchable:
        return [res]
    if res.accelerator is not None:
        zones = catalog.tpu_zones(res.accelerator, region=res.region)
        return [res.copy(zone=z, region=z.rsplit("-", 1)[0])
                for z in zones]
    itype = res.instance_type
    if itype is None:
        cpus, mem = res._cpu_mem_floor()
        itype = catalog.default_vm_for(cpus, mem)
    zones = catalog.vm_zones(itype, region=res.region)
    if res.zone is not None:
        # cpus/memory-floor resources carry the zone pin through expansion
        # (an explicit zone must never be silently widened).
        zones = [z for z in zones if z == res.zone]
    return [res.copy(instance_type=itype, zone=z,
                     region=z.rsplit("-", 1)[0]) for z in zones]


def _required_features(task, res):
    """Capability features this (task, resources) pair needs."""
    from skypilot_tpu import clouds as clouds_lib
    F = clouds_lib.CloudImplementationFeatures
    feats = []
    if res.use_spot:
        feats.append(F.SPOT_INSTANCE)
    if res.ports:
        feats.append(F.OPEN_PORTS)
    if res.image_id:
        feats.append(F.IMAGE_ID)
    if task.num_nodes > 1:
        feats.append(F.MULTI_NODE)
    return feats


def launchable_candidates(
        task, blocklist: Optional[Blocklist] = None,
        drop_reasons: Optional[List[str]] = None) -> List[Candidate]:
    """Expand a task's resource set into priced, concrete candidates,
    dropping placements whose cloud lacks a required capability or was
    not enabled by `stpu check --clouds` (reference:
    _fill_in_launchable_resources, sky/optimizer.py:1201).

    `drop_reasons`, if given, collects one human-readable line per
    dropped candidate so an empty result can explain itself.
    """
    from skypilot_tpu import clouds as clouds_lib
    from skypilot_tpu import global_user_state
    blocklist = blocklist or Blocklist()
    # Empty set = `stpu check --clouds` never ran; plan over all clouds
    # (hermetic tests and first-run UX).
    enabled = set(global_user_state.get_enabled_clouds())

    def drop(concrete, why: str) -> None:
        if drop_reasons is not None:
            drop_reasons.append(f"{concrete}: {why}")

    out: List[Candidate] = []
    for res in task.resources:
        for concrete in _expand_one(res):
            if blocklist.blocked(concrete):
                drop(concrete, "blocklisted after provision failure")
                continue
            if enabled and concrete.provider_name not in enabled:
                drop(concrete,
                     f"cloud {concrete.provider_name!r} not enabled "
                     f"(run `stpu check --clouds`)")
                continue
            cloud = clouds_lib.get_cloud(concrete.provider_name)
            unsupported = cloud.unsupported_features_for_resources(
                concrete)
            bad = [f for f in _required_features(task, concrete)
                   if f in unsupported]
            if bad:
                drop(concrete, "; ".join(
                    f"{f.value}: {unsupported[f]}" for f in bad))
                continue
            price = concrete.hourly_price() * task.num_nodes
            out.append(Candidate(
                resources=concrete,
                hourly_price=price,
                runtime_seconds=task.estimate_runtime(concrete)))
    return out


class Optimizer:
    """Static methods only, mirroring the reference's surface."""

    @staticmethod
    def optimize(dag: Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocklist: Optional[Blocklist] = None,
                 quiet: bool = False) -> Dag:
        """Set ``task.best_resources`` on every task in the dag."""
        order = dag.topo_order()
        if not order:
            return dag

        per_task: Dict[int, List[Candidate]] = {}
        for task in order:
            reasons: List[str] = []
            cands = launchable_candidates(task, blocklist, reasons)
            if not cands:
                detail = "".join(f"\n  - {r}" for r in reasons[:20])
                raise exceptions.ResourcesUnavailableError(
                    f"No launchable resources for {task}: all candidates "
                    f"are infeasible or blocklisted.{detail}")
            per_task[id(task)] = cands

        if dag.is_chain():
            plan = Optimizer._optimize_chain_dp(order, per_task, minimize)
        else:
            plan = Optimizer._optimize_general(dag, order, per_task,
                                               minimize)

        for task in order:
            task.best_resources = plan[id(task)].resources
        if not quiet:
            Optimizer.print_optimized_plan(dag, per_task, plan, minimize)
        return dag

    @staticmethod
    def _objective(c: Candidate, minimize: OptimizeTarget) -> Tuple:
        if minimize == OptimizeTarget.TIME:
            return (c.runtime_seconds, c.cost)
        return (c.cost, c.runtime_seconds)

    @staticmethod
    def _best(cands: Sequence[Candidate],
              minimize: OptimizeTarget) -> Candidate:
        return min(cands, key=lambda c: Optimizer._objective(c, minimize))

    @staticmethod
    def _egress_cost(parent, parent_cand: Candidate,
                     child_cand: Candidate) -> float:
        gb = float(getattr(parent, "estimated_output_gb", 0.0) or 0.0)
        if gb == 0.0:
            return 0.0
        return gb * catalog.egress_cost_per_gb(
            parent_cand.resources.region, child_cand.resources.region)

    @staticmethod
    def _optimize_chain_dp(
            order, per_task: Dict[int, List[Candidate]],
            minimize: OptimizeTarget) -> Dict[int, Candidate]:
        """Exact DP over the chain with inter-task egress cost
        (reference: sky/optimizer.py:373 _optimize_by_dp).

        Shares _optimize_general's objective exactly — TIME minimizes
        (makespan, cost incl. egress); COST minimizes (cost incl.
        egress, total runtime) — so both solvers pick the same plan for
        the same chain. Lexicographic tuples accumulate additively, so
        prefix-optimality (and thus the DP) holds for the pair.
        """
        n = len(order)
        cands0 = per_task[id(order[0])]
        # dp[i][j] = best (primary, secondary) for the prefix ending
        # with task i using its candidate j. Egress is money: it adds
        # to the cost component (secondary under TIME, primary under
        # COST), never to the runtime component.
        dp: List[List[Tuple[float, float]]] = [
            [(0.0, 0.0)] * len(per_task[id(t)]) for t in order]
        back: List[List[int]] = [[-1] * len(per_task[id(t)])
                                 for t in order]
        time_mode = minimize == OptimizeTarget.TIME
        for j, c in enumerate(cands0):
            dp[0][j] = Optimizer._objective(c, minimize)
        for i in range(1, n):
            parent = order[i - 1]
            pc = per_task[id(parent)]
            cc = per_task[id(order[i])]
            for j, child in enumerate(cc):
                base = Optimizer._objective(child, minimize)
                best, arg = None, -1
                for pj, pcand in enumerate(pc):
                    egress = Optimizer._egress_cost(parent, pcand, child)
                    prev = dp[i - 1][pj]
                    if time_mode:
                        total = (prev[0] + base[0],
                                 prev[1] + base[1] + egress)
                    else:
                        total = (prev[0] + base[0] + egress,
                                 prev[1] + base[1])
                    if best is None or total < best:
                        best, arg = total, pj
                dp[i][j] = best
                back[i][j] = arg
        j = min(range(len(dp[-1])), key=lambda j: dp[-1][j])
        plan: Dict[int, Candidate] = {}
        for i in range(n - 1, -1, -1):
            plan[id(order[i])] = per_task[id(order[i])][j]
            j = back[i][j]
        return plan

    # Exhaustive general-DAG search caps the assignment-space size; above
    # it we fall back to per-task independent choice (the pre-exact
    # behavior). The reference solves this case with an ILP
    # (sky/optimizer.py:434 _optimize_by_ilp via PuLP); this image has no
    # ILP solver, and real DAGs are small, so exact enumeration fills the
    # same role and is cross-checked against the chain DP in tests.
    GENERAL_DAG_MAX_SPACE = 200_000

    @staticmethod
    def _optimize_general(dag, order, per_task: Dict[int, List[Candidate]],
                          minimize: OptimizeTarget
                          ) -> Dict[int, Candidate]:
        """Exact plan for a general DAG with per-edge egress cost.

        COST: sum of node costs + egress over every edge. TIME: critical-
        path runtime (longest path), cost as tie-break.
        """
        import itertools
        import math
        import sys
        space = math.prod(len(per_task[id(t)]) for t in order)
        if space > Optimizer.GENERAL_DAG_MAX_SPACE:
            print(f"optimizer: DAG assignment space ({space:,}) exceeds "
                  f"{Optimizer.GENERAL_DAG_MAX_SPACE:,}; placing each "
                  f"task independently — inter-task egress cost is NOT "
                  f"optimized. Pin regions to co-locate tasks.",
                  file=sys.stderr)
            return {id(t): Optimizer._best(per_task[id(t)], minimize)
                    for t in order}

        parents = {id(t): dag.parents(t) for t in order}
        edges = [(parent, child) for child in order
                 for parent in parents[id(child)]]
        best_key, best_plan = None, None
        for combo in itertools.product(
                *[per_task[id(t)] for t in order]):
            sel = {id(t): c for t, c in zip(order, combo)}
            cost = sum(c.cost for c in combo)
            for parent, child in edges:
                cost += Optimizer._egress_cost(parent, sel[id(parent)],
                                               sel[id(child)])
            if minimize == OptimizeTarget.TIME:
                # Longest path through the DAG under this assignment.
                finish: Dict[int, float] = {}
                for t in order:  # topo order
                    start = max(
                        (finish[id(p)] for p in parents[id(t)]),
                        default=0.0)
                    finish[id(t)] = start + sel[id(t)].runtime_seconds
                key = (max(finish.values()), cost)
            else:
                key = (cost,
                       sum(c.runtime_seconds for c in combo))
            if best_key is None or key < best_key:
                best_key, best_plan = key, sel
        return best_plan

    @staticmethod
    def print_optimized_plan(dag, per_task, plan, minimize) -> None:
        try:
            from rich.console import Console
            from rich.table import Table
        except ImportError:  # pragma: no cover
            for task in dag.topo_order():
                print(f"  {task.name or '<task>'} -> "
                      f"{plan[id(task)].resources}")
            return
        table = Table(title=f"Optimized plan (minimize {minimize.value})")
        for col in ("task", "nodes", "resources", "$/hr",
                    "est. time (hr)", "est. cost ($)"):
            table.add_column(col)
        for task in dag.topo_order():
            chosen = plan[id(task)]
            table.add_row(
                task.name or "<task>", str(task.num_nodes),
                repr(chosen.resources),
                f"{chosen.hourly_price:.2f}",
                f"{chosen.runtime_seconds / 3600.0:.2f}",
                f"{chosen.cost:.2f}")
        Console().print(table)


def optimize(dag: Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             blocklist: Optional[Blocklist] = None,
             quiet: bool = False) -> Dag:
    return Optimizer.optimize(dag, minimize, blocklist, quiet)
