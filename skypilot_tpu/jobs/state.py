"""Managed-job state: sqlite table of jobs owned by the jobs controller.

Reference analog: sky/jobs/state.py (ManagedJobStatus, spot table on the
controller; 613 LoC). Here the controller runs as a detached local process,
so the DB lives under the client's state dir (``paths.home()``).
"""
from __future__ import annotations

import enum
import pathlib
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import paths


class ManagedJobStatus(enum.Enum):
    """Lifecycle of a managed job (reference: sky/jobs/state.py).

    PENDING → SUBMITTED → STARTING → RUNNING ⇄ RECOVERING → SUCCEEDED
    with FAILED / FAILED_SETUP / FAILED_NO_RESOURCE / FAILED_CONTROLLER /
    CANCELLING → CANCELLED as terminal branches.
    """
    PENDING = "PENDING"
    SUBMITTED = "SUBMITTED"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    RECOVERING = "RECOVERING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    FAILED_SETUP = "FAILED_SETUP"
    FAILED_NO_RESOURCE = "FAILED_NO_RESOURCE"
    FAILED_CONTROLLER = "FAILED_CONTROLLER"
    CANCELLING = "CANCELLING"
    CANCELLED = "CANCELLED"

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    def is_failed(self) -> bool:
        return self in (ManagedJobStatus.FAILED,
                        ManagedJobStatus.FAILED_SETUP,
                        ManagedJobStatus.FAILED_NO_RESOURCE,
                        ManagedJobStatus.FAILED_CONTROLLER)


_TERMINAL = {
    ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
    ManagedJobStatus.FAILED_SETUP, ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER, ManagedJobStatus.CANCELLED,
}


def _db_path() -> pathlib.Path:
    p = paths.home() / "managed_jobs.db"
    p.parent.mkdir(parents=True, exist_ok=True)
    return p


# DB paths whose schema migration already ran in this process (keyed by
# path, not a bare flag: tests repoint STPU_HOME per test).
_MIGRATED: set = set()


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(), timeout=10)
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("""CREATE TABLE IF NOT EXISTS managed_jobs (
        job_id INTEGER PRIMARY KEY AUTOINCREMENT,
        job_name TEXT,
        dag_yaml_path TEXT,
        resources_str TEXT,
        cluster_name TEXT,
        status TEXT,
        submitted_at REAL,
        start_at REAL,
        end_at REAL,
        last_recovered_at REAL,
        recovery_count INTEGER DEFAULT 0,
        task_index INTEGER DEFAULT 0,
        num_tasks INTEGER DEFAULT 1,
        controller_pid INTEGER,
        failure_reason TEXT,
        last_ckpt_step INTEGER,
        ckpt_dir TEXT,
        cluster_job_id INTEGER,
        mfu REAL,
        tok_s REAL,
        goodput REAL)""")
    # Schema migration for DBs created before the checkpoint columns
    # existed (sqlite has no ADD COLUMN IF NOT EXISTS). Once per
    # process per DB path: every jobs_state call opens a fresh
    # connection, and three always-failing DDL statements per watch
    # tick is pointless overhead.
    db_key = str(_db_path())
    if db_key not in _MIGRATED:
        migrated = True
        for column, decl in (("last_ckpt_step", "INTEGER"),
                             ("ckpt_dir", "TEXT"),
                             ("cluster_job_id", "INTEGER"),
                             ("mfu", "REAL"),
                             ("tok_s", "REAL"),
                             ("goodput", "REAL")):
            try:
                conn.execute(f"ALTER TABLE managed_jobs "
                             f"ADD COLUMN {column} {decl}")
            except sqlite3.OperationalError as e:
                if "duplicate column" not in str(e).lower():
                    # Transient failure (locked DB): DON'T pin the
                    # path — retry on the next connection, or every
                    # later write to the new columns breaks.
                    migrated = False
        if migrated:
            _MIGRATED.add(db_key)
    conn.commit()
    return conn


_COLUMNS = ("job_id", "job_name", "dag_yaml_path", "resources_str",
            "cluster_name", "status", "submitted_at", "start_at", "end_at",
            "last_recovered_at", "recovery_count", "task_index",
            "num_tasks", "controller_pid", "failure_reason",
            "last_ckpt_step", "ckpt_dir", "cluster_job_id",
            "mfu", "tok_s", "goodput")


def add_job(job_name: str, dag_yaml_path: str, resources_str: str,
            num_tasks: int) -> int:
    with _conn() as conn:
        cur = conn.execute(
            "INSERT INTO managed_jobs (job_name, dag_yaml_path, "
            "resources_str, status, submitted_at, num_tasks) "
            "VALUES (?, ?, ?, ?, ?, ?)",
            (job_name, dag_yaml_path, resources_str,
             ManagedJobStatus.PENDING.value, time.time(), num_tasks))
        return int(cur.lastrowid)


def _emit_job_event(job_id: int, status_value: str,
                    failure_reason: Optional[str] = None) -> None:
    """One lifecycle event per successful status write — emitted from
    this DB layer so every writer (controller, cancel path, finalizer)
    is covered by the same hook."""
    from skypilot_tpu.observability import events
    events.emit("job", str(job_id), status_value,
                failure_reason=failure_reason)


def set_status(job_id: int, status: ManagedJobStatus,
               failure_reason: Optional[str] = None) -> None:
    now = time.time()
    with _conn() as conn:
        if status == ManagedJobStatus.RUNNING:
            cur = conn.execute(
                "UPDATE managed_jobs SET status=?, start_at="
                "COALESCE(start_at, ?) WHERE job_id=?",
                (status.value, now, job_id))
        elif status.is_terminal():
            cur = conn.execute(
                "UPDATE managed_jobs SET status=?, end_at=?, "
                "failure_reason=COALESCE(?, failure_reason) "
                "WHERE job_id=?",
                (status.value, now, failure_reason, job_id))
        else:
            cur = conn.execute(
                "UPDATE managed_jobs SET status=? WHERE job_id=?",
                (status.value, job_id))
        updated = cur.rowcount > 0
    if updated:   # a nonexistent job_id must not log a transition
        _emit_job_event(job_id, status.value, failure_reason)


def set_cancelling(job_id: int) -> bool:
    """Move a job to CANCELLING unless it already reached a terminal
    status (the controller may finish between the caller's queue()
    snapshot and this write). Returns True iff the row was updated."""
    with _conn() as conn:
        cur = conn.execute(
            "UPDATE managed_jobs SET status=? "
            "WHERE job_id=? AND status NOT IN (%s)" %
            ",".join("?" * len(_TERMINAL)),
            (ManagedJobStatus.CANCELLING.value, job_id,
             *[s.value for s in _TERMINAL]))
        updated = cur.rowcount > 0
    if updated:
        _emit_job_event(job_id, ManagedJobStatus.CANCELLING.value)
    return updated


def finalize_status(job_id: int, status: ManagedJobStatus,
                    failure_reason: Optional[str] = None) -> bool:
    """Set a terminal status only if the job is not already terminal.

    Used when finalizing a dead controller: if the controller exited
    normally between the caller's queue() snapshot and the signal (job
    just reached SUCCEEDED/FAILED), that terminal status must win.
    Returns True iff the row was updated.
    """
    assert status.is_terminal(), status
    with _conn() as conn:
        cur = conn.execute(
            "UPDATE managed_jobs SET status=?, end_at=?, "
            "failure_reason=COALESCE(?, failure_reason) "
            "WHERE job_id=? AND status NOT IN (%s)" %
            ",".join("?" * len(_TERMINAL)),
            (status.value, time.time(), failure_reason, job_id,
             *[s.value for s in _TERMINAL]))
        updated = cur.rowcount > 0
    if updated:
        _emit_job_event(job_id, status.value, failure_reason)
    return updated


def set_recovering(job_id: int) -> None:
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET status=?, recovery_count="
            "recovery_count+1, last_recovered_at=? WHERE job_id=?",
            (ManagedJobStatus.RECOVERING.value, time.time(), job_id))
    _emit_job_event(job_id, ManagedJobStatus.RECOVERING.value)


def set_dag_yaml_path(job_id: int, dag_yaml_path: str) -> None:
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET dag_yaml_path=? WHERE job_id=?",
            (dag_yaml_path, job_id))


def set_cluster_name(job_id: int, cluster_name: str) -> None:
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET cluster_name=? WHERE job_id=?",
            (cluster_name, job_id))


def set_controller_pid(job_id: int, pid: int) -> None:
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET controller_pid=? WHERE job_id=?",
            (pid, job_id))


def set_task_index(job_id: int, task_index: int) -> None:
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET task_index=? WHERE job_id=?",
            (task_index, job_id))


def set_ckpt_dir(job_id: int, ckpt_dir: str) -> None:
    """Record the job's stable checkpoint directory (stamped into the
    task env as $STPU_JOB_CKPT_DIR by the controller)."""
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET ckpt_dir=? WHERE job_id=?",
            (ckpt_dir, job_id))


def set_last_ckpt_step(job_id: int, step: int) -> None:
    """Newest durable checkpoint step the controller observed —
    `stpu jobs queue` surfaces it as resume progress."""
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET last_ckpt_step=? WHERE job_id=?",
            (step, job_id))


def set_train_stats(job_id: int, mfu: Optional[float],
                    tok_s: Optional[float],
                    goodput: Optional[float]) -> None:
    """Latest training telemetry the controller scraped from the
    task's trainstats snapshot (live MFU, token rate, productive
    goodput fraction) — `stpu jobs queue`/`top` surface them."""
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET mfu=?, tok_s=?, goodput=? "
            "WHERE job_id=?",
            (mfu, tok_s, goodput, job_id))


def claim_controller(job_id: int, expected_pid: Optional[int],
                     claim_pid: int) -> bool:
    """Atomically take ownership of a job's controller slot:
    compare-and-swap controller_pid from the observed (dead) value to
    ``claim_pid``. Two concurrent reconcile passes both observe the
    same dead pid; only the CAS winner may spawn an adopter — the
    loser's rowcount is 0. Returns True iff the claim won."""
    with _conn() as conn:
        cur = conn.execute(
            "UPDATE managed_jobs SET controller_pid=? "
            "WHERE job_id=? AND controller_pid IS ?",
            (claim_pid, job_id, expected_pid))
        return cur.rowcount > 0


def set_cluster_job_id(job_id: int, cluster_job_id: Optional[int]) -> None:
    """On-cluster job id of the current launch/recovery attempt; an
    adopting controller resumes the watch with it instead of blindly
    relaunching."""
    with _conn() as conn:
        conn.execute(
            "UPDATE managed_jobs SET cluster_job_id=? WHERE job_id=?",
            (cluster_job_id, job_id))


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        row = conn.execute(
            f"SELECT {', '.join(_COLUMNS)} FROM managed_jobs "
            "WHERE job_id=?", (job_id,)).fetchone()
    return dict(zip(_COLUMNS, row)) if row else None


def get_status(job_id: int) -> Optional[ManagedJobStatus]:
    job = get_job(job_id)
    return ManagedJobStatus(job["status"]) if job else None


def queue(skip_finished: bool = False) -> List[Dict[str, Any]]:
    with _conn() as conn:
        rows = conn.execute(
            f"SELECT {', '.join(_COLUMNS)} FROM managed_jobs "
            "ORDER BY job_id DESC").fetchall()
    jobs = [dict(zip(_COLUMNS, r)) for r in rows]
    if skip_finished:
        jobs = [j for j in jobs
                if not ManagedJobStatus(j["status"]).is_terminal()]
    return jobs
