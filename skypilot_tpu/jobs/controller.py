"""Managed-jobs controller: launch, watch, recover.

Reference analog: sky/jobs/controller.py (JobsController:46, monitor loop
_run_one_task:103 — poll the on-cluster job status; distinguish user
failure from preemption by asking the *cloud* for instance health
:250-325, because a preempted spot TPU can't report its own death).

Deployment difference: the reference runs this on a launched controller VM;
here it runs as a detached local process per managed job (the client is the
controller host). The control flow is identical, so moving it onto a
controller VM is a transport change, not a logic change.

Checkpoint/resume contract: every task gets a stable per-task checkpoint
directory stamped into its env as $STPU_JOB_CKPT_DIR (train/checkpoint.py
format; recipes default --checkpoint-dir to it). Recovery relaunches the
SAME task with the SAME env, so the relaunched run resumes from the last
durable checkpoint instead of step 0; the controller polls the directory
each watch tick and records the newest step (``stpu jobs queue`` shows it
as resume progress).

Adoption: a controller that dies mid-flight (OOM, host reboot, SIGKILL
mid-recovery) must not orphan its job. ``--adopt`` re-attaches a fresh
controller to a non-terminal job whose recorded controller pid is dead:
a healthy cluster resumes the watch in place; a missing/preempted one
finishes the interrupted recovery — the same rule PR 4's drain adoption
follows for serve replicas. ``jobs.core.reconcile()`` scans for such
orphans and spawns adopters.

Runnable:  python -m skypilot_tpu.jobs.controller --job-id N [--adopt] dag.yaml
"""
from __future__ import annotations

import argparse
import os
import signal
import threading
import time
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision as provision_api
from skypilot_tpu.agent import job_lib
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.utils import dag_utils

_RECOVERIES = metrics.counter(
    "stpu_jobs_recoveries_total",
    "Managed-job recovery attempts (relaunch after loss).")
_PREEMPTIONS = metrics.counter(
    "stpu_jobs_preemptions_total",
    "Recoveries triggered by provider-confirmed instance loss "
    "(vs. a lost job record on a healthy cluster).")
_RECOVERY_SECONDS = metrics.histogram(
    "stpu_jobs_recovery_duration_seconds",
    "Wall time from loss detection to the job RUNNING again.",
    buckets=(1, 5, 15, 30, 60, 120, 300, 600, 1800, 3600))
_ADOPTIONS = metrics.counter(
    "stpu_jobs_adoptions_total",
    "Jobs adopted by a fresh controller after the previous controller "
    "process died.", ("mode",))
_RECOVERED_STEP = metrics.gauge(
    "stpu_jobs_recovered_step",
    "Checkpoint step the most recent recovery resumed from (0 = no "
    "checkpoint existed; the relaunch recomputes from scratch).")
_LAST_CKPT_STEP = metrics.gauge(
    "stpu_jobs_last_ckpt_step",
    "Newest durable checkpoint step observed in the job's ckpt dir.")

# Poll gap between on-cluster job status checks (reference:
# JOB_STATUS_CHECK_GAP_SECONDS). Overridable for hermetic tests.
def _poll_seconds() -> float:
    return float(os.environ.get("STPU_JOBS_POLL_SECONDS", "15"))


class _Cancelled(Exception):
    pass


def _pid_alive(pid: Optional[int]) -> bool:
    """Is ``pid`` a live controller-ish process? Liveness alone is not
    enough — a recycled pid belonging to an unrelated daemon would
    make reconcile skip an orphaned job forever — so when /proc is
    available the cmdline must look like a controller: the detached
    module invocation (``jobs.controller``) or any python interpreter
    (inline ``detach=False`` controllers and reconciler claim tokens
    live in the SDK caller's process). A pid recycled by another
    *python* process remains a false-alive tail case; zombies (exited,
    unreaped) are dead for adoption purposes."""
    if not pid or pid <= 0:
        return False
    from skypilot_tpu.utils import proc_utils
    if proc_utils.pid_state(pid) != "running":
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().replace(b"\x00", b" ")
    except OSError:
        return True  # no /proc (non-linux): liveness is the answer
    return (b"jobs.controller" in cmdline or b"python" in cmdline)


class JobsController:
    def __init__(self, job_id: int, dag_yaml_path: str,
                 adopt: bool = False):
        self.job_id = job_id
        self.dag = dag_utils.load_chain_dag_from_yaml(dag_yaml_path)
        self.backend = slice_backend.SliceBackend()
        self._cancel_requested = False
        self._adopt = adopt
        self._last_ckpt_reported: Optional[int] = None
        # Training telemetry scraped from the task's trainstats
        # snapshot each watch tick (PR 14 store; dumped as JSON next
        # to the .prom so `stpu jobs top` — a separate process — can
        # read the series back).
        from skypilot_tpu.observability import timeseries
        self._train_store = timeseries.TimeSeriesStore()
        self._last_train_stats: Optional[tuple] = None

    # ------------------------------------------------------------------
    def _export_metrics(self) -> None:
        """Dump this controller's registry next to its log (textfile
        pattern — the controller is its own process with no HTTP
        surface, so the .prom file IS its exposition path)."""
        from skypilot_tpu.utils import paths
        log_dir = paths.logs_dir() / "managed_jobs"
        try:
            log_dir.mkdir(parents=True, exist_ok=True)
        except OSError:
            return
        metrics.dump_to_file(log_dir / f"controller-{self.job_id}.prom")

    def run(self) -> None:
        jobs_state.set_controller_pid(self.job_id, os.getpid())
        installed = []
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                installed.append(
                    (sig, signal.signal(sig, self._handle_cancel_signal)))
        try:
            self._run()
        finally:
            for sig, old in installed:
                signal.signal(sig, old)

    def _run(self) -> None:
        # Adoption skips tasks the dead controller already completed:
        # task_index is persisted before each task starts, so resuming
        # there finishes the interrupted task and continues the chain.
        start_index = 0
        if self._adopt:
            job = jobs_state.get_job(self.job_id)
            start_index = int(job.get("task_index") or 0) if job else 0
        try:
            for task_index, task in enumerate(self.dag.topo_order()):
                if task_index < start_index:
                    continue
                jobs_state.set_task_index(self.job_id, task_index)
                self._run_one_task(
                    task_index, task,
                    adopt=self._adopt and task_index == start_index)
            jobs_state.set_status(self.job_id, ManagedJobStatus.SUCCEEDED)
        except _Cancelled:
            jobs_state.set_status(self.job_id, ManagedJobStatus.CANCELLED)
        except exceptions.ResourcesUnavailableError as e:
            jobs_state.set_status(self.job_id,
                                  ManagedJobStatus.FAILED_NO_RESOURCE,
                                  failure_reason=str(e))
        except _UserFailure as e:
            jobs_state.set_status(self.job_id, e.status,
                                  failure_reason=str(e))
        except Exception as e:  # noqa: BLE001 — controller crash
            jobs_state.set_status(self.job_id,
                                  ManagedJobStatus.FAILED_CONTROLLER,
                                  failure_reason=repr(e))
            raise
        finally:
            # Final metrics state survives the process (recovery counts
            # of a finished job stay inspectable).
            self._export_metrics()
            # Job-scoped translated buckets (workdir/file mounts) die
            # with the job — they were only ever recovery intermediates.
            from skypilot_tpu.utils import controller_utils
            controller_utils.cleanup_translated_buckets(self.dag)

    def _handle_cancel_signal(self, signum, frame) -> None:
        del signum, frame
        self._cancel_requested = True

    def _check_cancelled(self) -> None:
        # Signal path (SIGTERM from `jobs cancel`) OR DB path: a cancel
        # issued before our pid was recorded leaves status=CANCELLING with
        # no signal delivered — honor it here.
        if not self._cancel_requested:
            if jobs_state.get_status(self.job_id) == \
                    ManagedJobStatus.CANCELLING:
                self._cancel_requested = True
        if self._cancel_requested:
            jobs_state.set_status(self.job_id,
                                  ManagedJobStatus.CANCELLING)
            raise _Cancelled()

    # ------------------------------------------------------------------
    def _cluster_name(self, task_index: int) -> str:
        job = jobs_state.get_job(self.job_id)
        base = (job["job_name"] or "job").replace("_", "-")[:20]
        return f"stpu-jobs-{base}-{self.job_id}-{task_index}"

    def _task_ckpt_dir(self, task_index: int) -> str:
        """Stable per-task checkpoint dir: survives the controller, the
        task cluster, and every recovery relaunch. Point workloads at a
        bucket via their own --checkpoint-dir to override."""
        from skypilot_tpu.utils import paths
        return str(paths.home() / "job_ckpts" / f"job-{self.job_id}" /
                   f"task-{task_index}")

    def _poll_ckpt_progress(self, ckpt_dir: str) -> Optional[int]:
        """Record the newest durable checkpoint step (resume progress
        for `stpu jobs queue`). Cheap manifest scan; the dir may be a
        bucket mount that does not exist controller-side — skip then."""
        from skypilot_tpu.train import checkpoint as checkpoint_lib
        if not os.path.isdir(ckpt_dir):
            return None
        step = checkpoint_lib.latest_step(ckpt_dir)
        if step is not None and step != self._last_ckpt_reported:
            # Write-on-change only: re-stamping the same step every
            # poll tick is pure WAL churn on the shared jobs DB.
            jobs_state.set_last_ckpt_step(self.job_id, step)
            _LAST_CKPT_STEP.set(step)
            self._last_ckpt_reported = step
        return step

    def _poll_trainstats(self, ckpt_dir: str) -> None:
        """Scrape the task's trainstats aggregate snapshot (host 0
        writes ``<ckpt_dir>/trainstats/snapshot.json``) into the
        controller's time-series store, persist the headline gauges
        on the jobs row (write-on-change), and dump the series as
        JSON for `stpu jobs top`. Best-effort: an absent or torn
        snapshot is simply skipped."""
        import json as json_lib
        path = os.path.join(ckpt_dir, "trainstats", "snapshot.json")
        try:
            with open(path) as f:
                snap = json_lib.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(snap, dict):
            return
        now = time.time()
        label = {"job": str(self.job_id)}
        mfu = snap.get("mfu")
        tok_s = snap.get("tokens_per_sec")
        goodput = (snap.get("goodput") or {}).get("productive")
        if mfu is not None:
            self._train_store.record("stpu_train_mfu", mfu, now,
                                     **label)
        if tok_s is not None:
            self._train_store.record("stpu_train_tokens_per_sec",
                                     tok_s, now, **label)
        if goodput is not None:
            self._train_store.record("stpu_train_goodput_fraction",
                                     goodput, now, **label)
        if snap.get("host_skew_s") is not None:
            self._train_store.record("stpu_train_host_skew_seconds",
                                     snap["host_skew_s"], now, **label)
        stats = (mfu, tok_s, goodput)
        if stats != self._last_train_stats:
            # Write-on-change only, like _poll_ckpt_progress: stamping
            # identical gauges every tick is pure WAL churn.
            jobs_state.set_train_stats(self.job_id, mfu, tok_s,
                                       goodput)
            self._last_train_stats = stats
        from skypilot_tpu.utils import paths
        log_dir = paths.logs_dir() / "managed_jobs"
        try:
            log_dir.mkdir(parents=True, exist_ok=True)
            out = log_dir / f"controller-{self.job_id}-train.json"
            doc = {
                "ts": now,
                "job_id": self.job_id,
                "snapshot": snap,
                "series": {
                    name: self._train_store.points(name, job=str(
                        self.job_id))
                    for name in ("stpu_train_mfu",
                                 "stpu_train_tokens_per_sec",
                                 "stpu_train_goodput_fraction",
                                 "stpu_train_host_skew_seconds")
                },
            }
            tmp = str(out) + ".tmp"
            with open(tmp, "w") as f:
                json_lib.dump(doc, f, default=str)
            os.replace(tmp, out)
        except OSError:
            pass

    def _dump_train_flight(self, ckpt_dir: str, reason: str) -> None:
        """Post-mortem of a preempted/lost task: synthesize a
        gang-wide flight dump from the per-host trainstats JSONL
        sinks — the training processes are already dead, so the
        controller writes it for them."""
        if not ckpt_dir:
            return
        stats_dir = os.path.join(ckpt_dir, "trainstats")
        if not os.path.isdir(stats_dir):
            return
        from skypilot_tpu.observability import trainstats
        trainstats.dump_dir_flight(reason, stats_dir)

    def _run_one_task(self, task_index: int, task,
                      adopt: bool = False) -> None:
        cluster_name = self._cluster_name(task_index)
        jobs_state.set_cluster_name(self.job_id, cluster_name)
        from skypilot_tpu.train import checkpoint as checkpoint_lib
        ckpt_dir = self._task_ckpt_dir(task_index)
        jobs_state.set_ckpt_dir(self.job_id, ckpt_dir)
        # The env rides the task through EVERY launch — initial and
        # recovery relaunches alike — so a preempted run resumes from
        # its own checkpoints (resume args point at the job's dir).
        task.update_envs({checkpoint_lib.CKPT_DIR_ENV: ckpt_dir})
        strategy = recovery_strategy.StrategyExecutor.make(
            cluster_name, task, retry_gap_seconds=min(
                _poll_seconds(), recovery_strategy.RETRY_INIT_GAP_SECONDS))
        # Launch-side trace: one span per managed-job task, parented on
        # whatever the submitting environment carried (STPU_TRACE_CTX —
        # the STPU_RUN_ID pattern); exported to the env so the gang
        # driver (and every host it spawns) nests under it. The
        # submitter's context is RESTORED afterwards: pipeline tasks in
        # this one controller process must parent as siblings on the
        # submitter, not chain-nest under each other's ended spans.
        prev_ctx = os.environ.get(tracing.ENV_CTX)
        span = tracing.start_span(
            "jobs.task", kind="jobs",
            parent=tracing.parse_ctx(prev_ctx),
            attrs={"job_id": self.job_id, "task_index": task_index,
                   "cluster": cluster_name})
        tracing.set_env_context(span.context())
        status = "error"
        try:
            if adopt:
                cluster_job_id = self._adopt_task(strategy, cluster_name,
                                                  ckpt_dir, span)
            else:
                jobs_state.set_status(self.job_id,
                                      ManagedJobStatus.STARTING)
                with tracing.start_span("jobs.launch", kind="jobs",
                                        parent=span,
                                        attrs={"cluster": cluster_name}):
                    cluster_job_id = strategy.launch()
                jobs_state.set_cluster_job_id(self.job_id,
                                              cluster_job_id)
            jobs_state.set_status(self.job_id, ManagedJobStatus.RUNNING)
            self._watch(strategy, cluster_name, cluster_job_id, span,
                        ckpt_dir)
            status = "ok"
        finally:
            span.end(status=status)
            if prev_ctx is None:
                os.environ.pop(tracing.ENV_CTX, None)
            else:
                os.environ[tracing.ENV_CTX] = prev_ctx
            # Task done (or cancelled/failed/launch half-succeeded): the
            # task cluster must not outlive its managed job (reference:
            # controller.py cleanup).
            self._teardown_cluster(cluster_name)

    def _adopt_task(self, strategy, cluster_name: str, ckpt_dir: str,
                    span) -> Optional[int]:
        """Re-attach to the task a dead controller left behind: a
        healthy cluster keeps running and we just resume the watch; a
        missing/preempted one gets the interrupted recovery finished
        (cleanup + relaunch, resuming from the job's checkpoints)."""
        from skypilot_tpu.observability import events
        job = jobs_state.get_job(self.job_id) or {}
        cluster_job_id = job.get("cluster_job_id")
        if cluster_job_id is not None and \
                self._cluster_healthy(cluster_name):
            _ADOPTIONS.labels(mode="watch").inc()
            events.emit("job", str(self.job_id), "adopted",
                        mode="watch", cluster=cluster_name)
            span.event("adopted", mode="watch")
            return cluster_job_id
        # Finish the interrupted recovery (the dead controller may have
        # been killed anywhere between cleanup and relaunch). Adoption
        # is AT-LEAST-ONCE: a controller that died after the task
        # finished but before SUCCEEDED was persisted is
        # indistinguishable from one that died mid-recovery, so the
        # task re-runs — checkpoint-aware workloads resume at their
        # final step (near-free); side-effecting run commands must be
        # idempotent, same as under any preemption recovery.
        _ADOPTIONS.labels(mode="recover").inc()
        events.emit("job", str(self.job_id), "adopted",
                    mode="recover", cluster=cluster_name)
        span.event("adopted", mode="recover")
        resumed_step = self._poll_ckpt_progress(ckpt_dir) or 0
        self._dump_train_flight(ckpt_dir, "controller_adopt")
        jobs_state.set_recovering(self.job_id)
        _RECOVERIES.inc()
        with tracing.start_span("jobs.recover", kind="jobs", parent=span,
                                attrs={"cluster": cluster_name,
                                       "adopted": True}):
            cluster_job_id = strategy.recover()
        jobs_state.set_cluster_job_id(self.job_id, cluster_job_id)
        _RECOVERED_STEP.set(resumed_step)
        return cluster_job_id

    def _watch(self, strategy, cluster_name: str,
               cluster_job_id: Optional[int], span=None,
               ckpt_dir: str = "") -> None:
        """Poll until SUCCEEDED; recover on preemption; raise on failure."""
        missing_count = 0
        while True:
            self._check_cancelled()
            self._export_metrics()
            time.sleep(_poll_seconds())
            self._check_cancelled()
            if ckpt_dir:
                self._poll_ckpt_progress(ckpt_dir)
                self._poll_trainstats(ckpt_dir)
            status = self._job_status(cluster_name, cluster_job_id)
            healthy = self._cluster_healthy(cluster_name)
            if status == job_lib.JobStatus.SUCCEEDED:
                return
            if status == job_lib.JobStatus.CANCELLED:
                raise _Cancelled()
            if status in (job_lib.JobStatus.FAILED,
                          job_lib.JobStatus.FAILED_SETUP):
                # Distinguish true user failure from a preemption that
                # killed the gang: ask the provider for instance health
                # (reference: controller.py:250-325).
                if healthy:
                    raise _UserFailure(
                        ManagedJobStatus.FAILED
                        if status == job_lib.JobStatus.FAILED
                        else ManagedJobStatus.FAILED_SETUP,
                        f"Task failed on cluster ({status.value}); see "
                        f"`stpu logs {cluster_name}`.")
            elif status is not None and healthy:
                missing_count = 0
                continue  # INIT/PENDING/SETTING_UP/RUNNING, all hosts up.
            elif status is None and healthy:
                # Transient job-DB read hiccup on a live cluster: retry a
                # few times before declaring the job lost.
                missing_count += 1
                if missing_count < recovery_strategy.MAX_JOB_CHECKING_RETRY:
                    continue
            # The step the relaunch will resume from — observed BEFORE
            # recovery so the gauge reflects what the preemption cost.
            resumed_step = (self._poll_ckpt_progress(ckpt_dir) or 0
                            if ckpt_dir else 0)
            # Post-mortem BEFORE recovery scribbles over the sinks:
            # the dump captures the last steps of the dying attempt.
            self._dump_train_flight(ckpt_dir, "job_preempted")
            jobs_state.set_recovering(self.job_id)
            _RECOVERIES.inc()
            if not healthy:
                _PREEMPTIONS.inc()
            t0 = time.perf_counter()
            with tracing.start_span(
                    "jobs.recover", kind="jobs", parent=span,
                    attrs={"cluster": cluster_name,
                           "preempted": not healthy,
                           "resumed_step": resumed_step}):
                cluster_job_id = strategy.recover()
            _RECOVERY_SECONDS.observe(time.perf_counter() - t0)
            jobs_state.set_cluster_job_id(self.job_id, cluster_job_id)
            _RECOVERED_STEP.set(resumed_step)
            jobs_state.set_status(self.job_id, ManagedJobStatus.RUNNING)
            missing_count = 0

    # ------------------------------------------------------------------
    def _job_status(self, cluster_name: str, cluster_job_id: Optional[int]
                    ) -> Optional[job_lib.JobStatus]:
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is None or record["handle"] is None:
            return None
        if cluster_job_id is None:
            return None
        try:
            value = self.backend.job_status(record["handle"],
                                            cluster_job_id)
        except Exception:  # noqa: BLE001 — unreachable head host
            return None
        return job_lib.JobStatus(value) if value else None

    def _cluster_healthy(self, cluster_name: str) -> bool:
        """All hosts still 'running' per the provider (cloud truth)."""
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is None or record["handle"] is None:
            return False
        handle = record["handle"]
        try:
            statuses = provision_api.query_instances(
                handle.provider_name, handle.cluster_name,
                handle.cluster_info.provider_config)
        except Exception:  # noqa: BLE001
            return False
        return (len(statuses) == handle.num_hosts and
                set(statuses.values()) == {"running"})

    def _teardown_cluster(self, cluster_name: str) -> None:
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is None or record["handle"] is None:
            return
        try:
            self.backend.teardown(record["handle"], terminate=True,
                                  purge=True)
        except Exception:  # noqa: BLE001 — already gone
            global_user_state.remove_cluster(cluster_name, terminate=True)


class _UserFailure(Exception):
    def __init__(self, status: ManagedJobStatus, msg: str):
        super().__init__(msg)
        self.status = status


def run_controller(job_id: int, dag_yaml_path: str,
                   adopt: bool = False) -> None:
    if adopt:
        # Two live controllers on one job would double-launch clusters;
        # adoption is only legal once the recorded owner is dead.
        job = jobs_state.get_job(job_id)
        if job is None:
            raise exceptions.SkyTpuError(
                f"Managed job {job_id} not found; cannot adopt.")
        pid = job.get("controller_pid")
        if _pid_alive(pid) and pid != os.getpid():
            raise exceptions.SkyTpuError(
                f"Managed job {job_id} still has a live controller "
                f"(pid {pid}); refusing to adopt.")
        if ManagedJobStatus(job["status"]).is_terminal():
            return  # nothing to adopt
    JobsController(job_id, dag_yaml_path, adopt=adopt).run()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--job-id", type=int, required=True)
    parser.add_argument("--adopt", action="store_true",
                        help="re-attach to a job whose previous "
                             "controller died (refuses if it is alive)")
    parser.add_argument("dag_yaml")
    args = parser.parse_args()
    run_controller(args.job_id, args.dag_yaml, adopt=args.adopt)


if __name__ == "__main__":
    main()
