"""Managed-jobs dashboard: one-file HTTP view of the jobs queue.

Reference analog: sky/jobs/dashboard/ (a flask app on the controller
serving an auto-refreshing jobs table). Stdlib-only here; reads through
jobs.core.queue(), which transparently proxies to the self-hosted
controller cluster when one exists.

    stpu jobs dashboard --port 8265
"""
from __future__ import annotations

import argparse
import html
import json
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!doctype html>
<html><head><title>stpu managed jobs</title>
<meta http-equiv="refresh" content="10">
<style>
 body {{ font-family: monospace; margin: 2em; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #999; padding: 4px 10px; text-align: left; }}
 th {{ background: #eee; }}
 .SUCCEEDED {{ color: #080; }} .RUNNING {{ color: #06c; }}
 .FAILED, .FAILED_SETUP, .FAILED_NO_RESOURCE, .FAILED_CONTROLLER
   {{ color: #c00; }}
 .RECOVERING, .CANCELLING {{ color: #c60; }}
</style></head>
<body><h2>Managed jobs</h2><p>{now}</p>
<table><tr><th>ID</th><th>Name</th><th>Status</th><th>Recoveries</th>
<th>MFU</th><th>Goodput</th>
<th>Cluster</th><th>Submitted</th><th>Failure</th></tr>
{rows}
</table></body></html>"""


def _pct(value) -> str:
    """Render a 0..1 fraction as a percentage cell ('-' when the
    controller has not scraped one yet)."""
    if value is None:
        return "-"
    return f"{float(value) * 100:.1f}%"


def _render(jobs) -> str:
    rows = []
    for j in jobs:
        submitted = time.strftime(
            "%Y-%m-%d %H:%M:%S",
            time.localtime(j.get("submitted_at") or 0))
        rows.append(
            "<tr><td>{}</td><td>{}</td>"
            "<td class=\"{}\">{}</td><td>{}</td><td>{}</td><td>{}</td>"
            "<td>{}</td><td>{}</td><td>{}</td></tr>".format(
                j["job_id"], html.escape(str(j.get("job_name") or "-")),
                html.escape(str(j["status"])),
                html.escape(str(j["status"])),
                j.get("recovery_count") or 0,
                _pct(j.get("mfu")),
                _pct(j.get("goodput")),
                html.escape(str(j.get("cluster_name") or "-")),
                submitted,
                html.escape(str(j.get("failure_reason") or ""))))
    return _PAGE.format(now=time.strftime("%Y-%m-%d %H:%M:%S"),
                        rows="\n".join(rows))


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def do_GET(self):
        from skypilot_tpu.jobs import core as jobs_core
        try:
            jobs = jobs_core.queue()
        except Exception as e:  # noqa: BLE001 — render, don't crash
            jobs, err = [], str(e)
        else:
            err = None
        if self.path.startswith("/api"):
            body = json.dumps({"jobs": jobs, "error": err}).encode()
            ctype = "application/json"
        else:
            page = _render(jobs)
            if err:
                page = page.replace("<table>",
                                    f"<p style='color:#c00'>"
                                    f"{html.escape(err)}</p><table>")
            body, ctype = page.encode(), "text/html"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


DEFAULT_PORT = 8265
DEFAULT_HOST = "127.0.0.1"


def serve(port: int, host: str = DEFAULT_HOST) -> ThreadingHTTPServer:
    return ThreadingHTTPServer((host, port), _Handler)


def run(port: int = DEFAULT_PORT, host: str = DEFAULT_HOST) -> None:
    """Print the URL and serve until interrupted (shared by the CLI and
    `python -m` entrypoints)."""
    httpd = serve(port, host)
    print(f"Jobs dashboard: http://{host}:{port} (ctrl-c to stop)",
          flush=True)
    httpd.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument("--host", default=DEFAULT_HOST)
    args = parser.parse_args()
    run(args.port, args.host)


if __name__ == "__main__":
    main()
