"""Managed jobs SDK: launch/queue/cancel/tail_logs.

Reference analog: sky/jobs/core.py (launch:30 wraps the user DAG into a
controller task launched on the jobs-controller cluster; queue/cancel/
tail_logs reach the controller via codegen over SSH). Same architecture
here: by default (`controller mode: cluster`) the job's controller process
runs **on the stpu-jobs-controller cluster** — the client can exit and
preemption recovery keeps running — and the client SDK proxies state reads
through the controller head. `mode: local` keeps the controller as a
client-local process (controller-logic unit tests, debugging).

This module doubles as the controller-side RPC surface:

    python -m skypilot_tpu.jobs.core submit --dag-yaml P --name N
    python -m skypilot_tpu.jobs.core queue [--skip-finished]
    python -m skypilot_tpu.jobs.core cancel (--ids 1,2 | --all)
    python -m skypilot_tpu.jobs.core status --job-id N

each printing one JSON document (the remote-RPC convention; reference:
ManagedJobCodeGen, sky/jobs/utils.py).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.task import Task
from skypilot_tpu.utils import controller_utils
from skypilot_tpu.utils import dag_utils
from skypilot_tpu.utils import paths

_JOBS = controller_utils.Controllers.JOBS


def launch(entrypoint: Union[Task, dag_lib.Dag],
           name: Optional[str] = None,
           detach: bool = True,
           controller: Optional[str] = None) -> int:
    """Start a managed job; returns its managed-job id.

    controller='cluster' (default, via config jobs.controller.mode) runs
    the job's controller process on the self-hosted controller cluster;
    'local' keeps it on the client. ``detach=False`` with 'local' runs the
    controller inline (blocking) — hermetic tests and debugging.
    """
    dag = dag_utils.convert_entrypoint_to_dag(entrypoint)
    if not dag.is_chain():
        raise exceptions.NotSupportedError(
            "Managed jobs support single tasks or chain pipelines only.")
    dag.name = name or dag.name or dag.tasks[0].name or "unnamed"

    # Client-local workdir/file_mounts become bucket mounts NOW, while
    # the paths exist: the controller (possibly on another machine) and
    # every preemption-recovery relaunch restore them from the bucket
    # (reference: maybe_translate_local_file_mounts_and_sync_up,
    # sky/utils/controller_utils.py:568).
    run_id = f"{int(time.time() * 1000) % 10**10}-{os.getpid()}"
    for i, task in enumerate(dag.tasks):
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task, run_id=f"{run_id}-t{i}")

    mode = controller or controller_utils.controller_mode(_JOBS)
    if mode == "local" or not detach:
        return _launch_local(dag, detach)

    # Self-hosted path: ship the DAG to the controller cluster and submit
    # there; the controller process outlives this client.
    handle = controller_utils.ensure_controller_up(_JOBS)
    stamp = f"{dag.name}-{int(time.time()*1000)}-{os.getpid()}"
    inbox = f"~/.stpu/jobs_inbox/{stamp}.yaml"
    local_yaml = paths.generated_dir() / "managed_jobs" / f"{stamp}.yaml"
    local_yaml.parent.mkdir(parents=True, exist_ok=True)
    dag_utils.dump_chain_dag_to_yaml(dag, str(local_yaml))
    runner = handle.get_command_runners()[0]
    runner.run("mkdir -p ~/.stpu/jobs_inbox")
    runner.rsync(str(local_yaml), inbox, up=True)
    out = controller_utils.run_on_controller(
        handle, controller_utils.module_command(
            "skypilot_tpu.jobs.core", "submit", "--dag-yaml", inbox,
            "--name", dag.name))
    return int(out["job_id"])


def _launch_local(dag: dag_lib.Dag, detach: bool) -> int:
    """Register + spawn the controller process on *this* host. Runs on the
    client in 'local' mode and on the controller head in 'cluster' mode
    (invoked there by the `submit` RPC)."""
    resources_str = ", ".join(
        str(res) for task in dag.tasks for res in task.resources)
    jobs_dir = paths.generated_dir() / "managed_jobs"
    jobs_dir.mkdir(parents=True, exist_ok=True)
    job_id = jobs_state.add_job(dag.name, "", resources_str,
                                num_tasks=len(dag.tasks))
    dag_yaml_path = str(jobs_dir / f"job-{job_id}.yaml")
    dag_utils.dump_chain_dag_to_yaml(dag, dag_yaml_path)
    jobs_state.set_dag_yaml_path(job_id, dag_yaml_path)
    jobs_state.set_status(job_id, ManagedJobStatus.SUBMITTED)

    if detach:
        _spawn_controller(job_id, dag_yaml_path)
    else:
        from skypilot_tpu.jobs import controller
        controller.run_controller(job_id, dag_yaml_path)
    return job_id


def _spawn_controller(job_id: int, dag_yaml_path: str,
                      adopt: bool = False) -> int:
    """Detached controller process for a managed job (appends to the
    job's controller log, so an adopter continues the same file).
    Returns the spawned pid."""
    log_dir = paths.logs_dir() / "managed_jobs"
    log_dir.mkdir(parents=True, exist_ok=True)
    argv = [sys.executable, "-m", "skypilot_tpu.jobs.controller",
            "--job-id", str(job_id)]
    if adopt:
        argv.append("--adopt")
    argv.append(dag_yaml_path)
    with open(log_dir / f"controller-{job_id}.log", "ab") as log_f:
        proc = subprocess.Popen(
            argv, stdout=log_f, stderr=subprocess.STDOUT,
            start_new_session=True, env=dict(os.environ))
    return proc.pid


# ---------------------------------------------------------------- queries
def _proxy() -> Optional[Any]:
    """Controller-cluster handle when jobs state is self-hosted."""
    return controller_utils.controller_handle(_JOBS)


def queue(skip_finished: bool = False) -> List[Dict[str, Any]]:
    """List managed jobs (reference: sky jobs queue)."""
    handle = _proxy()
    if handle is None:
        return jobs_state.queue(skip_finished=skip_finished)
    args = ["queue"] + (["--skip-finished"] if skip_finished else [])
    return controller_utils.run_on_controller(
        handle, controller_utils.module_command(
            "skypilot_tpu.jobs.core", *args))


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    handle = _proxy()
    if handle is None:
        return jobs_state.get_job(job_id)
    out = controller_utils.run_on_controller(
        handle, controller_utils.module_command(
            "skypilot_tpu.jobs.core", "status", "--job-id", str(job_id)))
    return out or None


def get_status(job_id: int) -> Optional[ManagedJobStatus]:
    job = get_job(job_id)
    return ManagedJobStatus(job["status"]) if job else None


def cancel(job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    """Cancel managed jobs: signal their controllers; each controller
    cancels its cluster job and tears the cluster down. A job whose
    controller died is finalized (incl. orphaned-cluster teardown)."""
    if not job_ids and not all_jobs:
        raise exceptions.SkyTpuError(
            "Specify managed job ids to cancel, or all_jobs=True "
            "(`stpu jobs cancel --all`).")
    handle = _proxy()
    if handle is None:
        return _cancel_local(job_ids, all_jobs)
    args = ["cancel"]
    args += ["--all"] if all_jobs else ["--ids", ",".join(
        str(i) for i in (job_ids or []))]
    out = controller_utils.run_on_controller(
        handle, controller_utils.module_command(
            "skypilot_tpu.jobs.core", *args))
    return list(out["cancelled"])


def _cancel_local(job_ids: Optional[List[int]],
                  all_jobs: bool) -> List[int]:
    """Cancel on this host (controller pids are local here)."""
    jobs = jobs_state.queue(skip_finished=True)
    if not all_jobs:
        jobs = [j for j in jobs if j["job_id"] in (job_ids or [])]
    cancelled = []
    for job in jobs:
        pid = job.get("controller_pid")
        # CANCELLING is observed by the controller at its next poll even
        # if it never received our signal (e.g. pid not yet recorded).
        # Conditional: a controller that just reached a terminal status
        # must keep it — and such a job needs no cancelling at all.
        if not jobs_state.set_cancelling(job["job_id"]):
            continue
        if pid:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                _finalize_dead_controller(job)
        elif time.time() - (job.get("submitted_at") or 0) > 60:  # noqa: stpu-wallclock submitted_at was persisted by another process
            # No pid a minute after submission: the controller died on
            # startup and will never observe CANCELLING — finalize here.
            _finalize_dead_controller(job)
        cancelled.append(job["job_id"])
    return cancelled


def _finalize_dead_controller(job: Dict[str, Any]) -> None:
    """The controller died without cleaning up: tear down its orphaned
    task cluster and mark the job CANCELLED."""
    cluster_name = job.get("cluster_name")
    if cluster_name:
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is not None and record["handle"] is not None:
            backend = slice_backend.SliceBackend()
            try:
                backend.teardown(record["handle"], terminate=True,
                                 purge=True)
            except Exception:  # noqa: BLE001 — already gone
                global_user_state.remove_cluster(cluster_name,
                                                 terminate=True)
    # Conditional: the controller may have exited normally between our
    # queue() snapshot and the kill — a just-reached SUCCEEDED/FAILED
    # must not be overwritten with CANCELLED.
    jobs_state.finalize_status(job["job_id"], ManagedJobStatus.CANCELLED)


def reconcile(detach: bool = True) -> List[int]:
    """Adopt orphaned managed jobs: every non-terminal job whose
    recorded controller pid is dead gets a fresh controller with
    ``--adopt`` (resume the watch on a healthy cluster, or finish the
    interrupted recovery — mirroring the serve layer's drain-adoption
    rule). Returns the adopted job ids. ``detach=False`` runs the
    adopting controllers inline (tests)."""
    handle = _proxy()
    if handle is not None:
        out = controller_utils.run_on_controller(
            handle, controller_utils.module_command(
                "skypilot_tpu.jobs.core", "reconcile"))
        return list(out["adopted"])
    return _reconcile_local(detach)


def _reconcile_local(detach: bool) -> List[int]:
    from skypilot_tpu.jobs import controller as controller_mod
    adopted = []
    for job in jobs_state.queue(skip_finished=True):
        pid = job.get("controller_pid")
        status = ManagedJobStatus(job["status"])
        if status.is_terminal():
            continue
        if controller_mod._pid_alive(pid):
            continue
        if pid is not None and pid < 0 and \
                controller_mod._pid_alive(-pid):
            # Negative pid = another reconciler's in-flight claim (see
            # below) and that reconciler is still alive (same
            # recycled-pid-aware liveness as controllers — a stale
            # claim whose reconciler died must not wedge the job).
            continue
        if pid is None and (
                time.time() - (job.get("submitted_at") or 0) < 60):  # noqa: stpu-wallclock submitted_at was persisted by another process
            # Controller may still be starting up (pid not yet
            # recorded); give it the same minute the cancel path does.
            continue
        dag_yaml_path = job.get("dag_yaml_path")
        if not dag_yaml_path or not os.path.exists(dag_yaml_path):
            _finalize_dead_controller(job)
            continue
        # Atomic claim (CAS on controller_pid): two concurrent
        # reconcile passes both observe the same dead pid, but only
        # the CAS winner may spawn — the loser skips. The claim token
        # is this reconciler's NEGATED pid: distinguishable from a
        # real controller pid, and a claimer that crashes mid-claim is
        # itself detectably dead, so the next pass re-claims.
        if not jobs_state.claim_controller(job["job_id"], pid,
                                           -os.getpid()):
            continue
        if detach:
            new_pid = _spawn_controller(job["job_id"], dag_yaml_path,
                                        adopt=True)
            # Replace the claim with the adopter's real pid NOW, not
            # when it finishes booting: a reconcile pass inside the
            # adopter's startup window must see a live controller.
            jobs_state.set_controller_pid(job["job_id"], new_pid)
        else:
            controller_mod.run_controller(job["job_id"], dag_yaml_path,
                                          adopt=True)
        adopted.append(job["job_id"])
    return adopted


def tail_logs(job_id: Optional[int] = None, follow: bool = True) -> int:
    """Stream the task logs of a managed job via its current cluster."""
    handle = _proxy()
    if handle is not None:
        args = ["tail"]
        if job_id is not None:
            args += ["--job-id", str(job_id)]
        if not follow:
            args += ["--no-follow"]
        rc = controller_utils.run_on_controller(
            handle, controller_utils.module_command(
                "skypilot_tpu.jobs.core", *args), stream=True)
        return int(rc)
    return _tail_logs_local(job_id, follow)


def _tail_logs_local(job_id: Optional[int], follow: bool) -> int:
    if job_id is None:
        jobs = jobs_state.queue()
        if not jobs:
            print("No managed jobs.")
            return 1
        job_id = jobs[0]["job_id"]
    job = jobs_state.get_job(job_id)
    if job is None:
        raise exceptions.SkyTpuError(f"Managed job {job_id} not found.")
    deadline = time.time() + 30
    while True:
        job = jobs_state.get_job(job_id)
        cluster_name = job.get("cluster_name")
        if cluster_name:
            record = global_user_state.get_cluster_from_name(cluster_name)
            if record is not None and record["handle"] is not None:
                backend = slice_backend.SliceBackend()
                return backend.tail_logs(record["handle"], None,
                                         follow=follow)
        if (ManagedJobStatus(job["status"]).is_terminal() or
                time.time() > deadline or not follow):
            print(f"Managed job {job_id} is {job['status']}; "
                  f"no live cluster to stream from.")
            return 0 if job["status"] == "SUCCEEDED" else 1
        time.sleep(0.5)


def wait(job_id: int, timeout: float = 300.0) -> ManagedJobStatus:
    """Block until the managed job reaches a terminal state."""
    deadline = time.time() + timeout
    # Proxied polls spawn a controller-side interpreter per call; use a
    # gentler interval than the local sqlite path.
    interval = 0.3 if _proxy() is None else 1.5
    status = None
    while time.time() < deadline:
        status = get_status(job_id)
        if status is not None and status.is_terminal():
            return status
        time.sleep(interval)
    raise TimeoutError(
        f"Managed job {job_id} not terminal after {timeout}s "
        f"(status={status})")


# ------------------------------------------------------- controller-side RPC
def main() -> None:
    parser = argparse.ArgumentParser(prog="skypilot_tpu.jobs.core")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("submit")
    p.add_argument("--dag-yaml", required=True)
    p.add_argument("--name", required=True)

    p = sub.add_parser("queue")
    p.add_argument("--skip-finished", action="store_true")

    p = sub.add_parser("cancel")
    p.add_argument("--ids", default=None)
    p.add_argument("--all", action="store_true", dest="all_jobs")

    p = sub.add_parser("status")
    p.add_argument("--job-id", type=int, required=True)

    sub.add_parser("reconcile")

    p = sub.add_parser("tail")
    p.add_argument("--job-id", type=int, default=None)
    p.add_argument("--no-follow", action="store_true")

    args = parser.parse_args()
    if args.cmd == "submit":
        dag = dag_utils.load_chain_dag_from_yaml(
            os.path.expanduser(args.dag_yaml))
        dag.name = args.name
        job_id = _launch_local(dag, detach=True)
        print(json.dumps({"job_id": job_id}))
    elif args.cmd == "queue":
        print(json.dumps(jobs_state.queue(
            skip_finished=args.skip_finished)))
    elif args.cmd == "cancel":
        ids = ([int(i) for i in args.ids.split(",") if i]
               if args.ids else None)
        print(json.dumps(
            {"cancelled": _cancel_local(ids, args.all_jobs)}))
    elif args.cmd == "status":
        print(json.dumps(jobs_state.get_job(args.job_id)))
    elif args.cmd == "reconcile":
        print(json.dumps({"adopted": _reconcile_local(detach=True)}))
    elif args.cmd == "tail":
        raise SystemExit(_tail_logs_local(args.job_id,
                                          follow=not args.no_follow))


if __name__ == "__main__":
    main()
