"""Managed jobs SDK: launch/queue/cancel/tail_logs.

Reference analog: sky/jobs/core.py (launch:30 wraps the user DAG into a
controller task; queue/cancel/tail_logs shell out to the controller via
codegen). Here the controller is a detached local process
(`python -m skypilot_tpu.jobs.controller`), and state is read directly
from the managed-jobs DB.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.task import Task
from skypilot_tpu.utils import dag_utils
from skypilot_tpu.utils import paths


def launch(entrypoint: Union[Task, dag_lib.Dag],
           name: Optional[str] = None,
           detach: bool = True) -> int:
    """Start a managed job; returns its managed-job id.

    ``detach=False`` runs the controller inline (blocking) — used by
    hermetic tests and debugging; the default spawns it detached.
    """
    dag = dag_utils.convert_entrypoint_to_dag(entrypoint)
    if not dag.is_chain():
        raise exceptions.NotSupportedError(
            "Managed jobs support single tasks or chain pipelines only.")
    dag.name = name or dag.name or dag.tasks[0].name or "unnamed"

    resources_str = ", ".join(
        str(res) for task in dag.tasks for res in task.resources)
    jobs_dir = paths.generated_dir() / "managed_jobs"
    jobs_dir.mkdir(parents=True, exist_ok=True)
    job_id = jobs_state.add_job(dag.name, "", resources_str,
                                num_tasks=len(dag.tasks))
    dag_yaml_path = str(jobs_dir / f"job-{job_id}.yaml")
    dag_utils.dump_chain_dag_to_yaml(dag, dag_yaml_path)
    jobs_state.set_dag_yaml_path(job_id, dag_yaml_path)
    jobs_state.set_status(job_id, ManagedJobStatus.SUBMITTED)

    if detach:
        log_dir = paths.logs_dir() / "managed_jobs"
        log_dir.mkdir(parents=True, exist_ok=True)
        with open(log_dir / f"controller-{job_id}.log", "ab") as log_f:
            subprocess.Popen(
                [sys.executable, "-m", "skypilot_tpu.jobs.controller",
                 "--job-id", str(job_id), dag_yaml_path],
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True, env=dict(os.environ))
    else:
        from skypilot_tpu.jobs import controller
        controller.run_controller(job_id, dag_yaml_path)
    return job_id


def queue(skip_finished: bool = False) -> List[Dict[str, Any]]:
    """List managed jobs (reference: sky jobs queue)."""
    return jobs_state.queue(skip_finished=skip_finished)


def cancel(job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    """Cancel managed jobs: signal their controllers; each controller
    cancels its cluster job and tears the cluster down. A job whose
    controller died is finalized here (incl. orphaned-cluster teardown)."""
    if job_ids is None and not all_jobs:
        raise exceptions.SkyTpuError(
            "Specify managed job ids to cancel, or all_jobs=True "
            "(`stpu jobs cancel --all`).")
    jobs = jobs_state.queue(skip_finished=True)
    if not all_jobs:
        jobs = [j for j in jobs if j["job_id"] in job_ids]
    cancelled = []
    for job in jobs:
        pid = job.get("controller_pid")
        # CANCELLING is observed by the controller at its next poll even
        # if it never received our signal (e.g. pid not yet recorded).
        # Conditional: a controller that just reached a terminal status
        # must keep it — and such a job needs no cancelling at all.
        if not jobs_state.set_cancelling(job["job_id"]):
            continue
        if pid:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                _finalize_dead_controller(job)
        elif time.time() - (job.get("submitted_at") or 0) > 60:
            # No pid a minute after submission: the controller died on
            # startup and will never observe CANCELLING — finalize here.
            _finalize_dead_controller(job)
        cancelled.append(job["job_id"])
    return cancelled


def _finalize_dead_controller(job: Dict[str, Any]) -> None:
    """The controller died without cleaning up: tear down its orphaned
    task cluster and mark the job CANCELLED."""
    cluster_name = job.get("cluster_name")
    if cluster_name:
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is not None and record["handle"] is not None:
            backend = slice_backend.SliceBackend()
            try:
                backend.teardown(record["handle"], terminate=True,
                                 purge=True)
            except Exception:  # noqa: BLE001 — already gone
                global_user_state.remove_cluster(cluster_name,
                                                 terminate=True)
    # Conditional: the controller may have exited normally between our
    # queue() snapshot and the kill — a just-reached SUCCEEDED/FAILED
    # must not be overwritten with CANCELLED.
    jobs_state.finalize_status(job["job_id"], ManagedJobStatus.CANCELLED)


def tail_logs(job_id: Optional[int] = None, follow: bool = True) -> int:
    """Stream the task logs of a managed job via its current cluster."""
    if job_id is None:
        jobs = jobs_state.queue()
        if not jobs:
            print("No managed jobs.")
            return 1
        job_id = jobs[0]["job_id"]
    job = jobs_state.get_job(job_id)
    if job is None:
        raise exceptions.SkyTpuError(f"Managed job {job_id} not found.")
    deadline = time.time() + 30
    while True:
        job = jobs_state.get_job(job_id)
        cluster_name = job.get("cluster_name")
        if cluster_name:
            record = global_user_state.get_cluster_from_name(cluster_name)
            if record is not None and record["handle"] is not None:
                backend = slice_backend.SliceBackend()
                return backend.tail_logs(record["handle"], None,
                                         follow=follow)
        if (ManagedJobStatus(job["status"]).is_terminal() or
                time.time() > deadline or not follow):
            print(f"Managed job {job_id} is {job['status']}; "
                  f"no live cluster to stream from.")
            return 0 if job["status"] == "SUCCEEDED" else 1
        time.sleep(0.5)


def wait(job_id: int, timeout: float = 300.0) -> ManagedJobStatus:
    """Block until the managed job reaches a terminal state."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        status = jobs_state.get_status(job_id)
        if status is not None and status.is_terminal():
            return status
        time.sleep(0.3)
    raise TimeoutError(
        f"Managed job {job_id} not terminal after {timeout}s "
        f"(status={jobs_state.get_status(job_id)})")
