"""Managed jobs: launch a task under a controller that watches it and
recovers from (spot TPU) preemptions.

Reference analog: sky/jobs/ (SURVEY §2.3, §3.2).
"""
from skypilot_tpu.jobs.state import ManagedJobStatus  # noqa: F401


def __getattr__(name):
    if name in ("launch", "queue", "cancel", "tail_logs", "wait",
                "reconcile"):
        from skypilot_tpu.jobs import core
        return getattr(core, name)
    raise AttributeError(f"module 'skypilot_tpu.jobs' has no attribute "
                         f"{name!r}")
