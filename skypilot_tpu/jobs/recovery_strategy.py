"""Preemption-recovery strategies for managed jobs.

Reference analog: sky/jobs/recovery_strategy.py (StrategyExecutor:62 with
__init_subclass__ registry :85, FAILOVER:372, EAGER_NEXT_REGION:458 — the
default). A strategy owns the task's cluster: it launches it, and after a
preemption relaunches it — either retrying the same placement first
(FAILOVER) or immediately re-optimizing to the next cheapest placement
(EAGER_NEXT_REGION). TPU note: spot-TPU preemption is only visible via the
cloud API (reference jobs/controller.py:236-262), so recovery always starts
by force-terminating whatever half-dead slice remains.
"""
from __future__ import annotations

import random
import time
import traceback
from typing import Dict, Optional, Type

from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_user_state
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.observability import events
from skypilot_tpu.observability import metrics
from skypilot_tpu.utils import fault_injection

RECOVERY_REGISTRY: Dict[str, Type["StrategyExecutor"]] = {}

_LAUNCH_ATTEMPTS = metrics.counter(
    "stpu_jobs_launch_attempts_total",
    "Task-cluster launch attempts by the recovery strategy.",
    ("outcome",))

DEFAULT_RECOVERY_STRATEGY = "EAGER_NEXT_REGION"
MAX_JOB_CHECKING_RETRY = 10
RETRY_INIT_GAP_SECONDS = 60
# Exponential-backoff ceiling for launch retries: a regional stockout
# lasts minutes-to-hours; retrying a dead zone every minute forever just
# burns API quota, but capping keeps the job responsive once capacity
# returns.
RETRY_BACKOFF_CAP_SECONDS = 600
# ±fraction of jitter on every gap so many controllers recovering from
# the same zone-wide preemption don't relaunch in lockstep.
RETRY_JITTER_FRACTION = 0.25


class StrategyExecutor:
    """Launch/recover the cluster running one managed task."""

    NAME = "STRATEGY_BASE"

    def __init__(self, cluster_name: str, task, max_restarts_on_errors: int,
                 retry_gap_seconds: Optional[float] = None):
        self.cluster_name = cluster_name
        self.task = task
        self.max_restarts_on_errors = max_restarts_on_errors
        self.restart_count = 0
        self.retry_gap_seconds = (RETRY_INIT_GAP_SECONDS
                                  if retry_gap_seconds is None
                                  else retry_gap_seconds)
        self.backend = slice_backend.SliceBackend()

    def __init_subclass__(cls, name: Optional[str] = None, **kwargs):
        super().__init_subclass__(**kwargs)
        if name is not None:
            cls.NAME = name
            RECOVERY_REGISTRY[name] = cls

    @classmethod
    def make(cls, cluster_name: str, task,
             retry_gap_seconds: Optional[float] = None
             ) -> "StrategyExecutor":
        name = None
        for res in task.resources:
            name = res.spot_recovery or res.job_recovery or name
        name = (name or DEFAULT_RECOVERY_STRATEGY).upper()
        if name not in RECOVERY_REGISTRY:
            raise exceptions.NotSupportedError(
                f"Unknown recovery strategy {name!r}; available: "
                f"{sorted(RECOVERY_REGISTRY)}")
        return RECOVERY_REGISTRY[name](cluster_name, task,
                                       max_restarts_on_errors=0,
                                       retry_gap_seconds=retry_gap_seconds)

    # ------------------------------------------------------------------
    def launch(self) -> Optional[int]:
        """Initial launch. Returns the on-cluster job id."""
        return self._launch(raise_on_failure=True)

    def recover(self) -> Optional[int]:
        """Relaunch after a preemption/failure. Subclasses decide where."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    def _cleanup_cluster(self) -> None:
        """Force-terminate the (possibly half-dead) task cluster."""
        record = global_user_state.get_cluster_from_name(self.cluster_name)
        if record is None or record["handle"] is None:
            global_user_state.remove_cluster(self.cluster_name,
                                             terminate=True)
            return
        try:
            self.backend.teardown(record["handle"], terminate=True,
                                  purge=True)
        except Exception:  # cluster may already be gone
            global_user_state.remove_cluster(self.cluster_name,
                                             terminate=True)

    def _launch(self, raise_on_failure: bool = True,
                max_retry: int = 3) -> Optional[int]:
        """Launch with retries; returns on-cluster job id or None.

        Backoff is exponential (doubling from ``retry_gap_seconds`` up
        to ``RETRY_BACKOFF_CAP_SECONDS``) with ±25% jitter, and the
        final failed attempt returns/raises immediately — no pointless
        trailing sleep before the caller sees the outcome.
        """
        backoff = self.retry_gap_seconds
        for attempt in range(max_retry):
            try:
                # Chaos seam: a launch attempt failing (InjectedFault is
                # a ConnectionError, so it rides the generic-error retry
                # path a real provisioning outage would).
                if fault_injection.ENABLED:
                    fault_injection.fire("jobs.launch",
                                         cluster=self.cluster_name,
                                         attempt=attempt)
                job_id, handle = execution.launch(
                    self.task, cluster_name=self.cluster_name,
                    detach_run=True, stream_logs=False)
                assert handle is not None
                _LAUNCH_ATTEMPTS.labels(outcome="ok").inc()
                return job_id
            except exceptions.ResourcesUnavailableError as e:
                _LAUNCH_ATTEMPTS.labels(outcome="unavailable").inc()
                if raise_on_failure and attempt == max_retry - 1:
                    raise exceptions.ResourcesUnavailableError(
                        f"Failed to launch cluster after {max_retry} "
                        f"attempts: {e}",
                        failover_history=e.failover_history) from e
            except Exception:  # noqa: BLE001 — surfaced via controller log
                _LAUNCH_ATTEMPTS.labels(outcome="error").inc()
                if raise_on_failure and attempt == max_retry - 1:
                    raise
                traceback.print_exc()
            if attempt < max_retry - 1:
                jitter = 1.0 + RETRY_JITTER_FRACTION * (
                    2.0 * random.random() - 1.0)
                time.sleep(backoff * jitter)
                backoff = min(backoff * 2,
                              RETRY_BACKOFF_CAP_SECONDS)
        return None


class FailoverStrategyExecutor(StrategyExecutor, name="FAILOVER"):
    """Retry the previous placement first; widen only when that fails.

    Reference: recovery_strategy.py:372 — keeps data/ckpt locality by
    preferring the same region before re-optimizing.
    """

    def recover(self) -> Optional[int]:
        events.emit("recovery", self.cluster_name, "recover_start",
                    strategy=self.NAME)
        self._cleanup_cluster()
        # 1. Same placement (zone pinned from the last launch). The
        #    original resource set (incl. any_of alternatives) is restored
        #    afterwards, whatever happens.
        prev = self.task.best_resources
        original = self.task.resources
        if prev is not None:
            try:
                self.task.set_resources(prev)
                job_id = self._launch(raise_on_failure=False, max_retry=1)
                if job_id is not None:
                    return job_id
            except Exception:  # noqa: stpu-except — same-placement retry is opportunistic; step 2 relaunches anywhere
                pass
            finally:
                self.task.resources = original
        # 2. Anywhere the user allowed: drop the pin and re-optimize.
        self._relax_placement()
        return self._launch(raise_on_failure=True)

    def _relax_placement(self) -> None:
        self.task.best_resources = None


class EagerNextRegionStrategyExecutor(FailoverStrategyExecutor,
                                      name="EAGER_NEXT_REGION"):
    """Immediately re-optimize to the next cheapest placement (default).

    Reference: recovery_strategy.py:458 — a preempted zone's spot capacity
    is likely still bad, so don't waste the retry on it.
    """

    def recover(self) -> Optional[int]:
        events.emit("recovery", self.cluster_name, "recover_start",
                    strategy=self.NAME)
        self._cleanup_cluster()
        self._relax_placement()
        return self._launch(raise_on_failure=True)
