"""Gemma-class decoder transformer — third model family of the recipe
tree (reference analog: llm/gemma — the reference launches Gemma through
HF TGI/vLLM serve YAMLs, /root/reference/llm/gemma/README.md; here the
model is native).

Gemma exercises the generality of the shared llama kernel family with
three architectural deltas, all expressed as config knobs the shared
blocks honor (models/llama.py):

  * **RMSNorm with a (1 + w) scale** (weights init to zeros) —
    ``norm_offset = 1.0``;
  * **GeGLU MLP** (tanh-approx gelu gate instead of SiLU) —
    ``mlp_activation = "gelu_tanh"``;
  * **sqrt(dim)-scaled embeddings + tied LM head** —
    ``embed_multiplier``, no ``lm_head`` param;

plus **MQA** (n_kv_heads=1, the gemma-2B layout) and a head_dim (256)
decoupled from dim/n_heads, both of which the GQA attention stack and
the Pallas flash kernel already support — that coverage is the point of
the family (VERDICT r4 next #6).

Training, KV-cache decode, LoRA injection, and the serving loop are the
shared llama machinery applied to this config; only init/specs and the
config live here, exactly like mixtral shares the attention stack.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GemmaConfig:
    vocab_size: int = 256000
    dim: int = 2048
    n_layers: int = 18
    n_heads: int = 8
    n_kv_heads: int = 1          # MQA (gemma-2B); gemma-7B is MHA 16/16
    head_dim_: int = 256         # decoupled from dim // n_heads
    mlp_dim: int = 16384
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"  # auto|pallas|reference|ring
    remat: bool = True
    remat_policy: str = "full"

    # Knobs the shared llama blocks read (see module docstring).
    norm_offset: float = 1.0
    mlp_activation: str = "gelu_tanh"
    tie_embeddings: bool = True

    @property
    def head_dim(self) -> int:
        return self.head_dim_

    @property
    def embed_multiplier(self) -> float:
        return math.sqrt(self.dim)

    @staticmethod
    def gemma_2b() -> "GemmaConfig":
        return GemmaConfig()

    @staticmethod
    def gemma_7b() -> "GemmaConfig":
        return GemmaConfig(dim=3072, n_layers=28, n_heads=16,
                           n_kv_heads=16, mlp_dim=24576)

    @staticmethod
    def tiny(vocab_size: int = 512) -> "GemmaConfig":
        return GemmaConfig(vocab_size=vocab_size, dim=128, n_layers=4,
                           n_heads=8, n_kv_heads=1, head_dim_=32,
                           mlp_dim=256, max_seq_len=512)

    @staticmethod
    def single_chip_bench() -> "GemmaConfig":
        """Gemma-2B geometry scaled to a 16 GB v5e chip for the serving
        bench (vocab shrunk like the llama/mixtral bench configs; the
        256k tied table alone is 1 GB bf16)."""
        return GemmaConfig(vocab_size=32768, dim=2048, n_layers=18,
                           n_heads=8, n_kv_heads=1, head_dim_=256,
                           mlp_dim=16384, max_seq_len=2048)

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """6N convention; with seq_len adds causal attention matmuls
        (same accounting as LlamaConfig.flops_per_token)."""
        p_layer = (self.dim * (self.n_heads + 2 * self.n_kv_heads) *
                   self.head_dim +
                   self.n_heads * self.head_dim * self.dim +
                   3 * self.dim * self.mlp_dim)
        p = self.n_layers * p_layer + self.vocab_size * self.dim * (
            1 if self.tie_embeddings else 2)
        flops = 6.0 * p
        if seq_len is not None:
            flops += 6.0 * self.n_layers * seq_len * \
                self.n_heads * self.head_dim
        return flops

    def num_params(self) -> int:
        p_layer = (self.dim * (self.n_heads + 2 * self.n_kv_heads) *
                   self.head_dim +
                   self.n_heads * self.head_dim * self.dim +
                   3 * self.dim * self.mlp_dim + 2 * self.dim)
        return (self.n_layers * p_layer + self.dim +
                self.vocab_size * self.dim * (
                    1 if self.tie_embeddings else 2))


def param_specs(cfg: GemmaConfig, *, quantized: bool = False) -> Params:
    """Logical-axis names, mirroring init()'s tree (the default tied
    head has no lm_head leaf; ``tie_embeddings=False`` adds one).
    ``quantized`` mirrors the quantize_params tree — see
    llama.param_specs."""
    specs = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "q_heads_x_dim"),
            "wk": ("layers", "embed", "kv_heads_x_dim"),
            "wv": ("layers", "embed", "kv_heads_x_dim"),
            "wo": ("layers", "q_heads_x_dim", "embed"),
            "mlp_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = ("embed", "vocab")
    if quantized:
        specs["embed_scale"] = ("vocab",)
        for name in llama.QUANT_LAYER_WEIGHTS:
            out_axis = specs["layers"][name][-1]
            specs["layers"][name + "_scale"] = ("layers", out_axis)
        if "lm_head" in specs:
            specs["lm_head_scale"] = ("vocab",)
    return specs


def init(cfg: GemmaConfig, key: jax.Array) -> Params:
    """Stacked-layer params. Norm weights are ZEROS (the (1 + w) scale
    starts at identity — gemma's checkpoint convention); with the
    default ``tie_embeddings=True`` the LM head is embed^T
    (llama.head_weights handles the absent lm_head), with it False an
    untied lm_head is created — config, num_params and flops_per_token
    all honor the flag."""
    k = jax.random.split(key, 9)
    d, hd = cfg.dim, cfg.head_dim
    L = cfg.n_layers
    dt = cfg.dtype

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32) *
                (fan_in ** -0.5)).astype(dt)

    params: Params = {
        "embed": dense(k[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.zeros((L, d), dtype=dt),
            "wq": dense(k[1], (L, d, cfg.n_heads * hd), d),
            "wk": dense(k[2], (L, d, cfg.n_kv_heads * hd), d),
            "wv": dense(k[3], (L, d, cfg.n_kv_heads * hd), d),
            "wo": dense(k[4], (L, cfg.n_heads * hd, d),
                        cfg.n_heads * hd),
            "mlp_norm": jnp.zeros((L, d), dtype=dt),
            "w_gate": dense(k[5], (L, d, cfg.mlp_dim), d),
            "w_up": dense(k[6], (L, d, cfg.mlp_dim), d),
            "w_down": dense(k[7], (L, cfg.mlp_dim, d), cfg.mlp_dim),
        },
        "final_norm": jnp.zeros((d,), dtype=dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k[8], (d, cfg.vocab_size), d)
    return params


# The forward/decode machinery is llama's, driven by this config's
# knobs — one shared implementation of attention, cache masking, remat,
# and the serving loop across the dense families.

def forward(cfg: GemmaConfig, params: Params, tokens: jax.Array,
            positions: Optional[jax.Array] = None,
            constrain=lambda x, spec: x) -> jax.Array:
    """Token ids (B, S) -> fp32 logits (B, S, vocab)."""
    return llama.forward(cfg, params, tokens, positions, constrain)


def forward_trunk(cfg: GemmaConfig, params: Params, tokens: jax.Array,
                  positions: Optional[jax.Array] = None,
                  constrain=lambda x, spec: x) -> jax.Array:
    return llama.forward_trunk(cfg, params, tokens, positions, constrain)


def head_weights(params: Params) -> jax.Array:
    return llama.head_weights(params)


def init_cache(cfg: GemmaConfig, batch: int, max_seq: int):
    return llama.init_cache(cfg, batch, max_seq)


cache_specs = llama.cache_specs

# int8 weight serving: gemma's param tree uses llama's layer keys, so
# the quantization transform (and its tied-head embed_scale handling)
# is llama's shared machinery.
quantize_params = llama.quantize_params
params_quantized = llama.params_quantized

# Paged KV block pool (decode-engine paged mode): layout and block-
# table attention are llama's shared machinery.
init_paged_cache = llama.init_paged_cache
paged_cache_specs = llama.paged_cache_specs
forward_with_paged_cache = llama.forward_with_paged_cache

# Speculative decoding (decode-engine verify path): the multi-token
# verify window is llama's shared machinery driven by this config's
# knobs (norm offset, GeGLU, scaled embeddings, MQA cache layout).
verify_step = llama.verify_step
verify_step_paged = llama.verify_step_paged


def forward_with_cache(cfg: GemmaConfig, params: Params,
                       tokens: jax.Array, cache, start_pos,
                       valid_len=None, logits_at=None, *,
                       block: Optional[int] = None):
    return llama.forward_with_cache(cfg, params, tokens, cache,
                                    start_pos, valid_len=valid_len,
                                    logits_at=logits_at, block=block)


def decode(cfg: GemmaConfig, params: Params, prompt: jax.Array,
           true_len, max_tokens: int, max_seq: int,
           temperature: float = 0.0, key=None, *,
           cache=None, return_cache: bool = False) -> jax.Array:
    """Prefill + KV-cached decode through the shared serving loop
    (scalar or ragged (B,) true_len; optional donated cache)."""
    return llama.decode(cfg, params, prompt, true_len, max_tokens,
                        max_seq, temperature, key, cache=cache,
                        return_cache=return_cache)
