"""Mixtral-class sparse MoE decoder with expert parallelism.

Recipe-parity target: the reference serves Mixtral by handing vLLM a set of
GPUs (reference: llm/mixtral/serve.yaml — vLLM does the expert math). Here
the MoE layer is native and TPU-first: top-2 routing is computed as one-hot
capacity dispatch/combine einsums (all MXU matmuls, no gather/scatter), the
expert axis is a logical axis (`expert` -> `ep` mesh axis via the rule
table), and XLA inserts the all-to-alls when the mesh shards it.

Shares the attention stack with llama.py; only the MLP differs.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.02
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def mixtral_8x7b() -> "MixtralConfig":
        return MixtralConfig()

    @staticmethod
    def tiny(vocab_size: int = 256) -> "MixtralConfig":
        return MixtralConfig(vocab_size=vocab_size, dim=64, n_layers=2,
                             n_heads=4, n_kv_heads=2, mlp_dim=128,
                             n_experts=4, top_k=2, max_seq_len=256)

    def flops_per_token(self) -> float:
        attn = self.dim * (self.n_heads + 2 * self.n_kv_heads) * \
            self.head_dim + self.n_heads * self.head_dim * self.dim
        moe = self.top_k * 3 * self.dim * self.mlp_dim
        router = self.dim * self.n_experts
        p_active = self.n_layers * (attn + moe + router) + \
            2 * self.vocab_size * self.dim
        return 6.0 * p_active


def param_specs(cfg: MixtralConfig, *, quantized: bool = False) -> Params:
    specs = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "q_heads_x_dim"),
            "wk": ("layers", "embed", "kv_heads_x_dim"),
            "wv": ("layers", "embed", "kv_heads_x_dim"),
            "wo": ("layers", "q_heads_x_dim", "embed"),
            "mlp_norm": ("layers", "embed"),
            "router": ("layers", "embed", None),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }
    if quantized:
        # int8 serving tree (quantize_params): per-output-channel
        # scales keep the weight's trailing axes minus the reduced
        # in-features axis — expert weights keep their expert axis so
        # EP sharding places each expert's scales beside its codes.
        # The f32 router is NOT quantized (routing decisions are
        # discrete; a code flip would change which experts fire).
        specs["embed_scale"] = ("vocab",)
        for name in llama.QUANT_LAYER_WEIGHTS:
            spec = specs["layers"][name]
            specs["layers"][name + "_scale"] = (
                spec[:-2] + spec[-1:])
        specs["lm_head_scale"] = ("vocab",)
    return specs


def init(cfg: MixtralConfig, key: jax.Array) -> Params:
    k = jax.random.split(key, 10)
    d, hd, L, E = cfg.dim, cfg.head_dim, cfg.n_layers, cfg.n_experts
    dt = cfg.dtype

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32) *
                (fan_in ** -0.5)).astype(dt)

    return {
        "embed": dense(k[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype=dt),
            "wq": dense(k[1], (L, d, cfg.n_heads * hd), d),
            "wk": dense(k[2], (L, d, cfg.n_kv_heads * hd), d),
            "wv": dense(k[3], (L, d, cfg.n_kv_heads * hd), d),
            "wo": dense(k[4], (L, cfg.n_heads * hd, d), cfg.n_heads * hd),
            "mlp_norm": jnp.ones((L, d), dtype=dt),
            "router": dense(k[5], (L, d, E), d).astype(jnp.float32),
            "w_gate": dense(k[6], (L, E, d, cfg.mlp_dim), d),
            "w_up": dense(k[7], (L, E, d, cfg.mlp_dim), d),
            "w_down": dense(k[8], (L, E, cfg.mlp_dim, d), cfg.mlp_dim),
        },
        "final_norm": jnp.ones((d,), dtype=dt),
        "lm_head": dense(k[9], (d, cfg.vocab_size), d),
    }


def quantize_params(cfg: MixtralConfig, params: Params) -> Params:
    """int8 weight-serving transform, mirroring
    ``param_specs(cfg, quantized=True)``: llama's per-output-channel
    scheme over the shared attention weights plus the expert tensors
    (in-features axis is always axis -2, expert axes survive into the
    scale), with the f32 router left exact — routing is a discrete
    argmax and must not move under quantization noise."""
    out = dict(params)
    out["embed"], out["embed_scale"] = llama._quantize_weight(
        params["embed"], -1)
    layers = dict(params["layers"])
    for name in llama.QUANT_LAYER_WEIGHTS:
        layers[name], layers[name + "_scale"] = llama._quantize_weight(
            layers[name], -2)
    out["layers"] = layers
    out["lm_head"], out["lm_head_scale"] = llama._quantize_weight(
        params["lm_head"], -2)
    return out


params_quantized = llama.params_quantized


def _top2_dispatch(gates: jax.Array, capacity: int
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """GShard-style top-2 capacity routing, all one-hot matmul friendly.

    gates: (T, E) softmax probabilities.
    Returns (dispatch (T, E, C) bool, combine (T, E, C) f32, aux_loss ()).
    """
    t, e = gates.shape
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)
    gates_no1 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates_no1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=gates.dtype)

    # Load-balancing aux loss (Switch-style): fraction of tokens routed to
    # each expert * mean router prob per expert.
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux = jnp.sum(density * density_proxy) * (e ** 2) / 1.0

    # Positions within each expert's buffer; tokens past capacity dropped.
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1  # (T, E)
    keep1 = (pos1 < capacity) * mask1
    pos2 = (jnp.cumsum(mask2, axis=0) + jnp.sum(mask1, axis=0,
                                                keepdims=True)) * mask2 - \
        mask2
    keep2 = (pos2 < capacity) * mask2

    g1 = jnp.sum(gates * keep1, axis=-1)
    g2 = jnp.sum(gates * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    cap_iota = jnp.arange(capacity, dtype=pos1.dtype)
    # (T, E, C) one-hots of each token's slot in each expert buffer.
    slot1 = keep1[:, :, None] * (pos1[:, :, None] == cap_iota)
    slot2 = keep2[:, :, None] * (pos2[:, :, None] == cap_iota)
    combine = g1[:, None, None] * slot1 + g2[:, None, None] * slot2
    dispatch = (slot1 + slot2) > 0
    return dispatch, combine.astype(jnp.float32), aux


def _moe_mlp(cfg: MixtralConfig, y: jax.Array, lp: Params, constrain
             ) -> Tuple[jax.Array, jax.Array]:
    """y: (B, S, D) -> (B, S, D), aux loss."""
    b, s, d = y.shape
    t = b * s
    e = cfg.n_experts
    capacity = max(int(cfg.capacity_factor * cfg.top_k * t / e), cfg.top_k)
    yt = y.reshape(t, d)
    logits = yt.astype(jnp.float32) @ lp["router"]
    gates = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, aux = _top2_dispatch(gates, capacity)
    # Dispatch: (T,E,C) x (T,D) -> (E,C,D); sharded expert axis makes XLA
    # insert the all-to-all here.
    xs = jnp.einsum("tec,td->ecd", dispatch.astype(y.dtype), yt)
    xs = constrain(xs, ("expert", None, "act_embed"))
    gate = jax.nn.silu(jnp.einsum("ecd,edm->ecm", xs, lp["w_gate"]))
    up = jnp.einsum("ecd,edm->ecm", xs, lp["w_up"])
    out = jnp.einsum("ecm,emd->ecd", gate * up, lp["w_down"])
    out = constrain(out, ("expert", None, "act_embed"))
    yo = jnp.einsum("tec,ecd->td", combine.astype(y.dtype), out)
    return yo.reshape(b, s, d), aux


def _layer(cfg: MixtralConfig, x: jax.Array, lp: Params,
           positions: jax.Array, constrain) -> Tuple[jax.Array, jax.Array]:
    x = llama.attention_block(cfg, x, lp, positions, constrain)
    y = llama.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    moe_out, aux = _moe_mlp(cfg, y, lp, constrain)
    x = x + constrain(moe_out, ("batch", "act_seq", "act_embed"))
    return x, aux


def forward(cfg: MixtralConfig, params: Params, tokens: jax.Array,
            positions: Optional[jax.Array] = None,
            constrain=lambda x, spec: x,
            with_aux: bool = True):
    """Token ids (B, S) -> (logits (B, S, vocab), router aux loss).

    ``with_aux=True`` by default so the load-balancing loss can only be
    dropped deliberately — training without it collapses the router.
    """
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    x = llama.embed_tokens(params, tokens, constrain)

    def layer_fn(carry, lp):
        x, aux_sum = carry
        x, aux = _layer(cfg, x, lp, positions, constrain)
        return (x, aux_sum + aux), None

    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn, prevent_cse=False)
    (x, aux_total), _ = jax.lax.scan(
        layer_fn, (x, jnp.zeros((), jnp.float32)), params["layers"])

    logits = llama.lm_head(cfg, params, x, constrain)
    if with_aux:
        return logits, cfg.router_aux_weight * aux_total / cfg.n_layers
    return logits


# ----------------------------------------------------------- KV-cache decode
def _moe_mlp_dense(cfg: MixtralConfig, y: jax.Array,
                   lp: Params) -> jax.Array:
    """Inference-time MoE: every expert computed, top-2 combined.

    Capacity routing (training) makes a token's output depend on which
    OTHER tokens compete for expert slots — so incremental decode could
    never reproduce a full pass. Per-token dense routing is
    composition-independent (incremental == full by construction) and
    cheap at decode chunk sizes; it equals the capacity path exactly
    whenever capacity is not exceeded.
    """
    e = cfg.n_experts
    logits = y.astype(jnp.float32) @ lp["router"]        # (B,T,E)
    gates = jax.nn.softmax(logits, axis=-1)
    # Select via top_k INDICES (exactly two experts, matching training's
    # two argmax picks) — a value threshold would activate 3+ experts on
    # tied gates and diverge from the capacity path.
    _, idx = jax.lax.top_k(gates, 2)                     # (B,T,2)
    sel = jax.nn.one_hot(idx, e, dtype=gates.dtype).sum(axis=-2)
    w = gates * sel
    w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)

    def expert_mm(eq, x, name):
        # Expert matmul, dequantizing per-(expert, channel) scales
        # when the weight is int8 (quantize_params tree): the scale's
        # trailing (E, out) axes broadcast against the einsum's
        # (..., E, out) result.
        wt = lp[name]
        scale = lp.get(name + "_scale")
        if scale is None:
            return jnp.einsum(eq, x, wt)
        r = jnp.einsum(eq, x, wt.astype(x.dtype))
        return (r.astype(jnp.float32) * scale).astype(x.dtype)

    gate = jax.nn.silu(expert_mm("btd,edm->btem", y, "w_gate"))
    up = expert_mm("btd,edm->btem", y, "w_up")
    out = expert_mm("btem,emd->bted", gate * up, "w_down")
    return jnp.einsum("bte,bted->btd", w.astype(out.dtype), out)


def init_cache(cfg: MixtralConfig, batch: int, max_seq: int):
    """Layer-stacked KV cache — same layout as llama's (the attention
    blocks are shared); experts add no per-token state."""
    return llama.init_cache(cfg, batch, max_seq)


cache_specs = llama.cache_specs

# Paged KV block pool: llama's layout/specs, experts add no per-token
# cache state.
init_paged_cache = llama.init_paged_cache
paged_cache_specs = llama.paged_cache_specs


def _moe_block(cfg: MixtralConfig, x: jax.Array, lp: Params) -> jax.Array:
    """Pre-norm dense-routed MoE residual block (inference)."""
    y = llama.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    return x + _moe_mlp_dense(cfg, y, lp)


def forward_with_cache(cfg: MixtralConfig, params: Params,
                       tokens: jax.Array, cache, start_pos: jax.Array,
                       valid_len: Optional[jax.Array] = None,
                       logits_at: Optional[jax.Array] = None, *,
                       block: Optional[int] = None):
    """Incremental MoE forward: llama's cache loop (attention/mask
    contract lives there, in one place) with the dense-routed top-2
    expert MLP swapped in — the serving loop the reference delegates to
    vLLM for Mixtral (llm/mixtral/serve.yaml). Same scalar-or-(B,)
    start_pos/valid_len/logits_at contract as
    llama.forward_with_cache."""
    return llama.forward_with_cache(
        cfg, params, tokens, cache, start_pos, valid_len=valid_len,
        logits_at=logits_at, mlp_fn=_moe_block, block=block)


def forward_with_paged_cache(cfg: MixtralConfig, params: Params,
                             tokens: jax.Array, cache, table,
                             start_pos, valid_len=None,
                             logits_at=None, *, window: int,
                             write_block=None):
    """Paged incremental MoE forward: llama's block-table cache loop
    with the dense-routed top-2 expert MLP swapped in — same pattern
    as forward_with_cache."""
    return llama.forward_with_paged_cache(
        cfg, params, tokens, cache, table, start_pos,
        valid_len=valid_len, logits_at=logits_at, window=window,
        write_block=write_block, mlp_fn=_moe_block)


def verify_step(cfg: MixtralConfig, params: Params, tokens: jax.Array,
                cache, start_pos, spec_len, *,
                block: Optional[int] = None):
    """Multi-token speculative verification for Mixtral: llama's dense
    verify window with the dense-routed top-2 expert MLP swapped in —
    per-token dense routing is composition-independent, so a verify
    column's logits equal the 1-token step's by construction."""
    return llama.verify_step(cfg, params, tokens, cache, start_pos,
                             spec_len, mlp_fn=_moe_block, block=block)


def verify_step_paged(cfg: MixtralConfig, params: Params,
                      tokens: jax.Array, cache, table, start_pos,
                      spec_len, *, window: int):
    """Paged speculative verify window with the MoE MLP swapped in."""
    return llama.verify_step_paged(cfg, params, tokens, cache, table,
                                   start_pos, spec_len, window=window,
                                   mlp_fn=_moe_block)


def decode(cfg: MixtralConfig, params: Params, prompt: jax.Array,
           true_len: jax.Array, max_tokens: int, max_seq: int,
           temperature: float = 0.0,
           key: Optional[jax.Array] = None, *,
           cache=None, return_cache: bool = False) -> jax.Array:
    """Prefill + cached decode for Mixtral (llama.decode's loop with the
    MoE cache functions plugged in; scalar or ragged (B,) true_len)."""
    return llama.decode(cfg, params, prompt, true_len, max_tokens,
                        max_seq, temperature=temperature, key=key,
                        fwd_cache=forward_with_cache,
                        cache_init=init_cache, cache=cache,
                        return_cache=return_cache)
