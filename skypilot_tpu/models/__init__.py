"""Model families (llama / mixtral / gemma) sharing one attention,
KV-cache, and serving-decode stack (models/llama.py)."""
from __future__ import annotations


def model_api(cfg):
    """Config-type -> model module (init/forward/decode/cache fns).

    Static dispatch on the (static-argnum) config dataclass, shared by
    the serving recipe, the decode engine, and the benches so a fourth
    family plugs in at exactly one place.
    """
    from skypilot_tpu.models import gemma, llama, mixtral
    if isinstance(cfg, mixtral.MixtralConfig):
        return mixtral
    if isinstance(cfg, gemma.GemmaConfig):
        return gemma
    return llama


def family_name(cfg) -> str:
    """Config-type -> family string ("llama" / "mixtral" / "gemma").

    The stable identifier the tuning manifest keys engine constants
    by (skypilot_tpu/tune/) — the same dispatch as model_api, reduced
    to a name that can live in a JSON file."""
    return model_api(cfg).__name__.rsplit(".", 1)[-1]
