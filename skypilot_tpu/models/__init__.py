"""Model families (llama / mixtral / gemma) sharing one attention,
KV-cache, and serving-decode stack (models/llama.py)."""
from __future__ import annotations


def model_api(cfg):
    """Config-type -> model module (init/forward/decode/cache fns).

    Static dispatch on the (static-argnum) config dataclass, shared by
    the serving recipe, the decode engine, and the benches so a fourth
    family plugs in at exactly one place.
    """
    from skypilot_tpu.models import gemma, llama, mixtral
    if isinstance(cfg, mixtral.MixtralConfig):
        return mixtral
    if isinstance(cfg, gemma.GemmaConfig):
        return gemma
    return llama
