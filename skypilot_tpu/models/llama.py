"""Llama-3-class decoder transformer, pure-JAX functional style.

Flagship dense model of the recipe tree (reference analog:
llm/llama-3_1-finetuning -- the reference shells out to torchtune; here the
model is native). Design is TPU-first:

  * params are plain pytrees of arrays with a parallel pytree of *logical
    axis* tuples -> shardings come from `parallel.mesh.ShardingRules`;
  * layers are stacked on a leading axis and executed with `lax.scan`
    (one compiled layer body, fast XLA compiles, natural remat point);
  * attention dispatches to the Pallas flash kernel on TPU;
  * all matmuls run in bfloat16 on the MXU, softmax/norm stats in fp32.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.ops import attention as attention_ops

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    attention_impl: str = "auto"  # auto|pallas|reference|ring
    remat: bool = True
    # "full": classic layer remat (everything recomputed in bwd).
    # "save_flash": layer remat, but the flash kernel's outputs
    # (named flash_out/flash_lse in its vjp fwd) are pinned — the bwd
    # recomputes projections/norms/MLP yet never re-runs the quadratic
    # attention kernel. Costs ~(2*S*D + 4*S*H) bytes per layer; at long
    # context the kernel re-run it saves dominates.
    # "save_flash_qkv": save_flash plus the roped q/k/v — also skips
    # the qkv-projection recompute for another ~2*S*D*2 bytes/layer.
    # "save_flash_offload_qkv": save_flash's HBM budget with
    # save_flash_qkv's recompute savings — q/k/v park in pinned host
    # RAM and stream back for the bwd. Long-context default: measured
    # to match save_flash_qkv at 8k and beat save_flash by +1.5 MFU pts
    # at 16k+ where pinned qkv OOMs (docs/performance.md).
    remat_policy: str = "full"
    # full|save_flash|save_flash_qkv|save_flash_offload_qkv

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab_size: int = 512) -> "LlamaConfig":
        return LlamaConfig(vocab_size=vocab_size, dim=128, n_layers=4,
                           n_heads=8, n_kv_heads=4, mlp_dim=256,
                           max_seq_len=512)

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate fwd+bwd FLOPs per token for MFU accounting.

        Without ``seq_len``: the conservative 6N parameter-matmul count
        (PaLM's "model FLOPs" convention; understates real work). With
        ``seq_len``: adds the causal attention score/value matmuls
        (~6 * L * S * d per token), the attention-inclusive figure.
        """
        p_layer = (self.dim * (self.n_heads + 2 * self.n_kv_heads) *
                   self.head_dim + self.n_heads * self.head_dim * self.dim +
                   3 * self.dim * self.mlp_dim)
        p = self.n_layers * p_layer + self.vocab_size * self.dim * (
            1 if self.tie_embeddings else 2)
        flops = 6.0 * p
        if seq_len is not None:
            # QK^T + PV: 4*S*d fwd per layer, halved by causal masking,
            # tripled for fwd+bwd.
            flops += 6.0 * self.n_layers * seq_len * self.dim
        return flops

    def num_params(self) -> int:
        p_layer = (self.dim * (self.n_heads + 2 * self.n_kv_heads) *
                   self.head_dim + self.n_heads * self.head_dim * self.dim +
                   3 * self.dim * self.mlp_dim + 2 * self.dim)
        return (self.n_layers * p_layer + self.dim +
                self.vocab_size * self.dim * (1 if self.tie_embeddings else 2))


def param_specs(cfg: LlamaConfig, *, quantized: bool = False) -> Params:
    """Logical-axis names for every param, mirroring init()'s tree.

    ``quantized`` mirrors :func:`quantize_params`' tree instead: every
    int8 weight keeps its bf16 spec (codes shard exactly like the
    values they encode) and gains a ``<name>_scale`` entry whose spec
    is the weight's OUTPUT axis — per-channel scales live on the same
    device as the channel's matmul shard, so TP serving never gathers
    them."""
    specs = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "q_heads_x_dim"),
            "wk": ("layers", "embed", "kv_heads_x_dim"),
            "wv": ("layers", "embed", "kv_heads_x_dim"),
            "wo": ("layers", "q_heads_x_dim", "embed"),
            "mlp_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }
    if cfg.tie_embeddings:
        specs.pop("lm_head")
    if quantized:
        specs["embed_scale"] = ("vocab",)
        for name in QUANT_LAYER_WEIGHTS:
            out_axis = specs["layers"][name][-1]
            specs["layers"][name + "_scale"] = ("layers", out_axis)
        if "lm_head" in specs:
            specs["lm_head_scale"] = ("vocab",)
    return specs


def init(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialize params (stacked-layer layout)."""
    k = jax.random.split(key, 9)
    d, hd = cfg.dim, cfg.head_dim
    L = cfg.n_layers
    dt = cfg.dtype

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32) *
                (fan_in ** -0.5)).astype(dt)

    params: Params = {
        "embed": dense(k[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype=dt),
            "wq": dense(k[1], (L, d, cfg.n_heads * hd), d),
            "wk": dense(k[2], (L, d, cfg.n_kv_heads * hd), d),
            "wv": dense(k[3], (L, d, cfg.n_kv_heads * hd), d),
            "wo": dense(k[4], (L, cfg.n_heads * hd, d), cfg.n_heads * hd),
            "mlp_norm": jnp.ones((L, d), dtype=dt),
            "w_gate": dense(k[5], (L, d, cfg.mlp_dim), d),
            "w_up": dense(k[6], (L, d, cfg.mlp_dim), d),
            "w_down": dense(k[7], (L, cfg.mlp_dim, d), cfg.mlp_dim),
        },
        "final_norm": jnp.ones((d,), dtype=dt),
        "lm_head": dense(k[8], (d, cfg.vocab_size), d),
    }
    if cfg.tie_embeddings:
        params.pop("lm_head")
    return params


# Layer weights the int8 serving path quantizes (norms and LoRA
# adapters stay in their checkpoint dtype; mixtral extends this with
# its expert tensors and keeps the f32 router exact).
QUANT_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up",
                      "w_down")


def _quantize_weight(w: jax.Array, reduce_axis: int):
    """Symmetric per-channel int8: absmax over the in-features axis
    (``reduce_axis``), one f32 scale per output channel. Codes span
    [-127, 127] so the representation is sign-symmetric."""
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=reduce_axis) / 127.0,
                        1e-8)
    q = jnp.round(wf / jnp.expand_dims(scale, reduce_axis))
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def quantize_params(cfg: LlamaConfig, params: Params) -> Params:
    """int8 weight-serving transform: every matmul weight becomes int8
    codes plus a per-output-channel f32 ``<name>_scale`` (the embed
    table's scale is per vocab ROW, which is simultaneously the tied
    lm_head's per-output-channel scale). The tree shape mirrors
    ``param_specs(cfg, quantized=True)`` so TP sharding
    (gang_replica.shard_params) works unchanged; norms and LoRA
    adapters keep their dtype. The matmuls upcast codes in-register at
    use — the win is HBM: resident weight bytes halve, and decode is
    memory-bound."""
    out = dict(params)
    out["embed"], out["embed_scale"] = _quantize_weight(
        params["embed"], -1)
    layers = dict(params["layers"])
    for name in QUANT_LAYER_WEIGHTS:
        layers[name], layers[name + "_scale"] = _quantize_weight(
            layers[name], -2)
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"], out["lm_head_scale"] = _quantize_weight(
            params["lm_head"], -2)
    return out


def params_quantized(params: Params) -> bool:
    """True when ``params`` is a :func:`quantize_params` tree."""
    return "embed_scale" in params


def rms_norm(x: jax.Array, w: jax.Array, eps: float,
             offset: float = 0.0) -> jax.Array:
    """``offset`` generalizes the scale to (offset + w): llama/mixtral
    use offset 0 (scale = w, init ones); gemma uses offset 1 (scale =
    1 + w, init zeros — its checkpoint convention). Configs advertise it
    via ``norm_offset``."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed32 = x32 * jax.lax.rsqrt(var + eps)
    if offset:
        # Scale applied in fp32: in bf16, eps(1.0)=2^-8, so gemma
        # checkpoint norm deltas under ~0.002 would vanish into the
        # (offset + w) addition (and into the product) if done in the
        # weight dtype.
        return (normed32 *
                (w.astype(jnp.float32) + offset)).astype(x.dtype)
    return normed32.astype(x.dtype) * w


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: (B, S, H, D), positions: (B, S)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B,S,D/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def lora_dense(y: jax.Array, lp: Params, name: str) -> jax.Array:
    """y @ W, plus the low-rank LoRA path y @ A @ B when the layer params
    carry `<name>_lora_a`/`<name>_lora_b` adapters (recipes/llama_lora.py
    injects them; base checkpoints don't have the keys and skip it).

    When the layer carries a `<name>_scale` (quantize_params tree) the
    weight is int8: codes upcast to the activation dtype in-register,
    the matmul runs as usual, and the per-output-channel f32 scale
    multiplies the result — one extra VPU pass, half the HBM reads."""
    w = lp[name]
    scale = lp.get(name + "_scale")
    if scale is None:
        out = y @ w
    else:
        out = ((y @ w.astype(y.dtype)).astype(jnp.float32) *
               scale).astype(y.dtype)
    a = lp.get(name + "_lora_a")
    if a is not None:
        out = out + (y @ a) @ lp[name + "_lora_b"]
    return out


def qkv_proj(cfg, y: jax.Array, lp: Params, positions: jax.Array):
    """Projection + RoPE shared by the training forward and the KV-cache
    decode path (they must never diverge). Returns (q, k, v); v unroped.
    """
    b, t = y.shape[0], y.shape[1]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q = lora_dense(y, lp, "wq").reshape(b, t, h, hd)
    kk = lora_dense(y, lp, "wk").reshape(b, t, kvh, hd)
    vv = lora_dense(y, lp, "wv").reshape(b, t, kvh, hd)
    return (rope(q, positions, cfg.rope_theta),
            rope(kk, positions, cfg.rope_theta), vv)


def _mlp_activation(cfg):
    """Gated-MLP nonlinearity by config: SwiGLU (llama/mixtral, the
    default) or GeGLU with tanh-approx gelu (gemma)."""
    name = getattr(cfg, "mlp_activation", "silu")
    if name == "silu":
        return jax.nn.silu
    if name == "gelu_tanh":
        return lambda a: jax.nn.gelu(a, approximate=True)
    raise ValueError(f"unknown mlp_activation {name!r}")


def mlp_block(cfg, x: jax.Array, lp: Params,
              constrain=lambda a, _spec: a) -> jax.Array:
    """Pre-norm gated-MLP residual block (SwiGLU or GeGLU by config),
    shared by training and decode."""
    y = rms_norm(x, lp["mlp_norm"], cfg.norm_eps,
                 getattr(cfg, "norm_offset", 0.0))
    # Through lora_dense so the int8 weight-serving path (per-channel
    # `_scale` entries) covers the MLP projections too; without scales
    # or adapters it is exactly `y @ w`.
    gate = _mlp_activation(cfg)(lora_dense(y, lp, "w_gate"))
    up = lora_dense(y, lp, "w_up")
    mlp = constrain(gate * up, ("batch", "act_seq", "mlp"))
    return x + constrain(lora_dense(mlp, lp, "w_down"),
                         ("batch", "act_seq", "act_embed"))


def attention_block(cfg, x: jax.Array, lp: Params, positions: jax.Array,
                    constrain) -> jax.Array:
    """Pre-norm GQA attention residual block, shared by llama and mixtral.

    `cfg` needs: n_heads, n_kv_heads, head_dim, norm_eps, rope_theta,
    attention_impl.
    """
    b, s, d = x.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    y = rms_norm(x, lp["attn_norm"], cfg.norm_eps,
                 getattr(cfg, "norm_offset", 0.0))
    q, kk, vv = qkv_proj(cfg, y, lp, positions)
    q = constrain(q, ("batch", "act_seq", "heads", None))
    kk = constrain(kk, ("batch", "act_seq", "kv_heads", None))
    if cfg.attention_impl == "ring":
        from skypilot_tpu.parallel import ring_attention
        attn = ring_attention.ring_attention_from_context(q, kk, vv)
    else:
        attn = attention_ops.attention(q, kk, vv, causal=True,
                                       impl=cfg.attention_impl)
    attn = attn.reshape(b, s, h * hd)
    return x + constrain(lora_dense(attn, lp, "wo"),
                         ("batch", "act_seq", "act_embed"))


def embed_tokens(params: Params, tokens: jax.Array, constrain) -> jax.Array:
    """Token embedding lookup, SPMD-aware.

    Under a multi-device mesh the lookup is a one-hot matmul rather than
    a gather: a gather whose operand is sharded on the embed dim (the
    fsdp layout of the table) produces output sharded on that dim, and
    the SPMD partitioner cannot move that sharding to the batch dim
    without an "involuntary full rematerialization" (replicate + re-
    partition — the warning the multichip dryrun used to log). A dot is
    freely repartitionable: XLA all-gathers the table's fsdp shards
    (exactly FSDP's prefetch-before-use) and psums over a sharded vocab.
    Single-device paths (serving decode, CPU tests) keep the O(1) gather.
    """
    from skypilot_tpu.parallel import mesh as mesh_lib
    table = params["embed"]
    ctx = mesh_lib.current_mesh_rules()
    if ctx is not None and ctx[0].size > 1:
        one_hot = jax.nn.one_hot(tokens, table.shape[0],
                                 dtype=table.dtype)
        one_hot = constrain(one_hot, ("batch", "act_seq", "vocab"))
        x = one_hot @ table
    else:
        x = table[tokens]
    return constrain(x, ("batch", "act_seq", "act_embed"))


def _decode_embed(cfg, params: Params, tokens: jax.Array) -> jax.Array:
    """Token-embedding gather for the serving decode paths: O(1)
    single-device gather (decode never runs the one-hot SPMD matmul —
    the table is either replicated or vocab-sharded with a cheap (B, T)
    collective), dequantizing per-row embed scales when the table is
    int8 and applying gemma's sqrt(dim) embed multiplier."""
    x = params["embed"][tokens]
    row_scale = params.get("embed_scale")
    mult = getattr(cfg, "embed_multiplier", 1.0)
    if row_scale is not None:
        x = (x.astype(jnp.float32) *
             (row_scale[tokens][..., None] * mult)).astype(cfg.dtype)
    elif mult != 1.0:  # gemma: embeddings scaled by sqrt(dim)
        x = (x.astype(jnp.float32) * mult).astype(x.dtype)
    return x


def _vocab_proj(params: Params, x: jax.Array, constrain) -> jax.Array:
    """(B,S,D) hidden -> fp32 logits. bf16 INPUTS into the MXU with f32
    accumulation (preferred_element_type) — casting the operands to f32
    first runs the vocab matmul at the fp32 rate, ~4x below bf16 peak,
    and at vocab 32k this projection alone is ~1 TFLOP per 8k-token
    step."""
    logits = jax.lax.dot_general(
        x, head_weights(params).astype(x.dtype), (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # int8 serving: the head's per-vocab-channel scale (embed_scale for
    # a tied head — the embed table's per-ROW scale transposes into the
    # head's per-column scale) folds into the f32 logits.
    scale = (params.get("lm_head_scale") if "lm_head" in params
             else params.get("embed_scale"))
    if scale is not None:
        logits = logits * scale
    return constrain(logits, ("batch", "act_seq", "vocab"))


def lm_head(cfg, params: Params, x: jax.Array, constrain) -> jax.Array:
    """Final norm + (tied or untied) output projection, fp32 logits."""
    x = rms_norm(x, params["final_norm"], cfg.norm_eps,
                 getattr(cfg, "norm_offset", 0.0))
    return _vocab_proj(params, x, constrain)


def _remat_policy(cfg):
    """jax.checkpoint policy for the layer body (see
    LlamaConfig.remat_policy)."""
    name = getattr(cfg, "remat_policy", "full")
    if name == "save_flash":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")
    if name == "save_flash_qkv":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "flash_q", "flash_k", "flash_v")
    if name == "save_flash_offload_qkv":
        # save_flash's HBM budget, save_flash_qkv's recompute savings:
        # kernel outputs stay on-device, the roped q/k/v park in pinned
        # host RAM and stream back for the bwd. Whether the PCIe/ICI
        # round-trip beats the qkv-projection recompute is measured in
        # docs/performance.md (long-context offload experiment).
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=["flash_out", "flash_lse"],
            names_which_can_be_offloaded=["flash_q", "flash_k",
                                          "flash_v"],
            offload_src="device", offload_dst="pinned_host")
    if name != "full":
        # A typo silently degrading to full remat would re-run the
        # quadratic kernel every bwd — the exact cost the knob avoids.
        raise ValueError(
            f"Unknown remat_policy {name!r}; expected 'full', "
            "'save_flash', 'save_flash_qkv' or "
            "'save_flash_offload_qkv'.")
    return None


def _layer(cfg: LlamaConfig, x: jax.Array, layer_params: Params,
           positions: jax.Array, constrain) -> jax.Array:
    lp = layer_params
    x = attention_block(cfg, x, lp, positions, constrain)
    return mlp_block(cfg, x, lp, constrain)


def forward(cfg: LlamaConfig, params: Params, tokens: jax.Array,
            positions: Optional[jax.Array] = None,
            constrain=lambda x, spec: x) -> jax.Array:
    """Token ids (B, S) -> logits (B, S, vocab).

    `constrain` is an optional callback (x, logical_axes) -> x used by the
    trainer to inject with_sharding_constraint under a concrete mesh; the
    default is identity so the model runs un-meshed (single device).
    """
    x = forward_trunk(cfg, params, tokens, positions, constrain)
    return _vocab_proj(params, x, constrain)


def forward_trunk(cfg: LlamaConfig, params: Params, tokens: jax.Array,
                  positions: Optional[jax.Array] = None,
                  constrain=lambda x, spec: x) -> jax.Array:
    """Token ids (B, S) -> FINAL-NORMED hidden states (B, S, dim) — the
    trunk without the vocab projection. The chunked-CE training loss
    (train/trainer.py chunked_cross_entropy_loss) projects chunk-by-
    chunk so the (B, S, vocab) fp32 logits tensor never materializes in
    HBM (it is ~1GB at seq 8192 x vocab 32k, and the round-trips through
    it dominate the loss region's step time)."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    x = embed_tokens(params, tokens, constrain)
    scale = getattr(cfg, "embed_multiplier", 1.0)
    if scale != 1.0:  # gemma: embeddings scaled by sqrt(dim)
        x = (x.astype(jnp.float32) * scale).astype(x.dtype)
    layer_fn = lambda carry, lp: (_layer(cfg, carry, lp, positions,
                                         constrain), None)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn, prevent_cse=False,
                                  policy=_remat_policy(cfg))
    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.norm_eps,
                    getattr(cfg, "norm_offset", 0.0))


def head_weights(params: Params) -> jax.Array:
    """(dim, vocab) output projection — untied head or embed^T."""
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return head


# ----------------------------------------------------------- KV-cache decode

# KV rows read per split-KV block. 256 keeps tiny test caches (< 256
# rows) on a single block — bit-identical to the dense softmax — while
# bounding VMEM working set at serving cache sizes.
SPLIT_KV_BLOCK = 256


def init_cache(cfg: LlamaConfig, batch: int,
               max_seq: int) -> Dict[str, jax.Array]:
    """Per-layer KV cache, stacked on the layer axis like the params
    (so the decode step scans layers and caches together)."""
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype=cfg.dtype),
            "v": jnp.zeros(shape, dtype=cfg.dtype)}


def cache_specs(cfg: LlamaConfig) -> Dict[str, tuple]:
    """Logical-axis names for the KV cache, mirroring init_cache()'s
    (layers, batch, max_seq, kv_heads, head_dim) layout — the serving
    analog of param_specs. Under a TP mesh the kv_heads axis shards
    over ICI neighbors (each chip holds its heads' rows); batch and
    seq stay replicated because every decode step touches all slots.
    Callers that build concrete shardings must re-point the rule at
    the trailing head_dim axis when tp does not divide n_kv_heads
    (gemma's single KV head) — serve/gang_replica.cache_shardings is
    the one place that check lives."""
    spec = ("layers", None, None, "kv_heads", None)
    return {"k": spec, "v": spec}


def init_paged_cache(cfg: LlamaConfig, num_blocks: int,
                     block_tokens: int, *,
                     quantized: bool = False) -> Dict[str, jax.Array]:
    """ONE device-resident paged KV pool shared by every engine slot
    AND the shared-prefix cache: ``num_blocks`` blocks of
    ``block_tokens`` token rows each, stacked on the layer axis like
    the dense cache (the decode step scans layers and pool together).
    Slots map logical positions to blocks through per-slot block
    tables (serve/kv_pool.py owns the accounting); block 0 is the
    scratch block free slots write into.

    ``quantized`` stores the pool as int8 codes plus parallel
    per-(layer, block, kv_head) f32 scale arrays — sized off the same
    block count, so the block table indexes codes and scales alike.
    Bytes per block roughly halve against bf16 (codes are half, the
    scale adds 4 bytes per kv_head per block against block_tokens *
    head_dim rows), which is where the ~2x pool capacity at a fixed
    HBM budget comes from."""
    shape = (cfg.n_layers, num_blocks, block_tokens, cfg.n_kv_heads,
             cfg.head_dim)
    if not quantized:
        return {"k": jnp.zeros(shape, dtype=cfg.dtype),
                "v": jnp.zeros(shape, dtype=cfg.dtype)}
    sshape = (cfg.n_layers, num_blocks, cfg.n_kv_heads)
    return {"k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(sshape, dtype=jnp.float32),
            "v_scale": jnp.zeros(sshape, dtype=jnp.float32)}


def paged_cache_specs(cfg: LlamaConfig) -> Dict[str, tuple]:
    """Logical-axis names for the paged pool. Identical to
    :func:`cache_specs`: the (layers, num_blocks, block_tokens,
    kv_heads, head_dim) layout keeps kv_heads at the same axis index
    as the dense (layers, batch, max_seq, kv_heads, head_dim) cache,
    so the TP sharding rules — including gang_replica.cache_shardings'
    head_dim fallback — apply unchanged."""
    return cache_specs(cfg)


def _attn_tile(qf: jax.Array, scale: float, kb: jax.Array,
               vb: jax.Array, msk: jax.Array, m: jax.Array,
               el: jax.Array, acc: jax.Array):
    """One online-softmax tile (running max / normalizer / accumulator
    update) shared by the dense and paged split-KV loops — one
    implementation so the two paths are the same arithmetic term for
    term, which is what makes paged decode bit-identical to dense when
    their tile boundaries align.

    qf: (B, T, KVH, G, D) f32 queries; kb/vb: (B, W, KVH, D) f32 tile;
    msk: (B, T, W) bool. Returns (m, el, acc) updated.
    """
    s_blk = jnp.einsum("btkgd,bskd->bkgts", qf, kb) * scale
    s_blk = jnp.where(msk[:, None, None], s_blk, -1e30)
    m_new = jnp.maximum(m, jnp.max(s_blk, axis=-1))
    corr = jnp.exp(m - m_new)
    # Masked entries multiplied to exactly 0 (not just exp(-big)):
    # a fully-masked slot (free engine slot) must stay finite.
    p = jnp.exp(s_blk - m_new[..., None]) * msk[:, None, None]
    el = el * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bkgts,bskd->btkgd", p, vb)
    corr_t = corr.transpose(0, 3, 1, 2)[..., None]
    return m_new, el, acc * corr_t + pv


def _attn_carry(b: int, t: int, kvh: int, g: int, d: int):
    return (jnp.int32(0),
            jnp.full((b, kvh, g, t), -1e30, jnp.float32),
            jnp.zeros((b, kvh, g, t), jnp.float32),
            jnp.zeros((b, t, kvh, g, d), jnp.float32))


def _attn_normalize(el: jax.Array, acc: jax.Array) -> jax.Array:
    el_t = el.transpose(0, 3, 1, 2)[..., None]
    return jnp.where(el_t > 0, acc / jnp.maximum(el_t, 1e-30), 0.0)


def _split_kv_attention(qg: jax.Array, ck: jax.Array, cv: jax.Array,
                        positions: jax.Array, valid_len: jax.Array,
                        block: Optional[int] = None) -> jax.Array:
    """Flash-decode-style attention against the ragged KV cache.

    Instead of one dense (T, max_seq) score einsum that reads every
    cache row, the cache is consumed in key blocks with an online
    softmax (running max / normalizer / accumulator), and the block loop
    is a ``lax.while_loop`` bounded by the LONGEST valid prefix in the
    batch — cache rows past every slot's frontier are never read, so a
    batch of short sequences in a long-max_seq cache pays for its actual
    tokens, not the allocation.

    qg: (B, T, KVH, G, D) grouped queries; ck/cv: (B, max_seq, KVH, D).
    positions: (B, T) absolute query positions. valid_len: (B,) — rows
    >= valid_len[b] are masked even though they hold (stale) data; this
    is the padding-KV-never-attendable invariant slot reuse relies on.
    Returns f32 (B, T, KVH, G, D).
    """
    b, t, kvh, g, d = qg.shape
    max_seq = ck.shape[1]
    block = min(block or SPLIT_KV_BLOCK, max_seq)
    qf = qg.astype(jnp.float32)
    scale = d ** -0.5
    # Rows a query of slot b can ever attend stop at
    # min(its position + 1, valid_len[b]); the loop bound is the batch
    # max so every slot's frontier is covered.
    limit = jnp.max(jnp.minimum(positions[:, -1] + 1, valid_len))
    limit = jnp.minimum(limit, max_seq)

    def body(carry):
        s0, m, el, acc = carry
        # When block does not divide max_seq, the final window clamps
        # back to max_seq - block; rows before the nominal start s0
        # (already consumed by earlier blocks) are masked out below, so
        # the overlap never double-counts.
        start = jnp.minimum(s0, max_seq - block)
        kb = jax.lax.dynamic_slice_in_dim(ck, start, block,
                                          axis=1).astype(jnp.float32)
        vb = jax.lax.dynamic_slice_in_dim(cv, start, block,
                                          axis=1).astype(jnp.float32)
        kpos = start + jnp.arange(block)
        msk = ((kpos[None, None, :] >= s0) &
               (kpos[None, None, :] <= positions[..., None]) &
               (kpos[None, None, :] < valid_len[:, None, None]))
        m_new, el, acc = _attn_tile(qf, scale, kb, vb, msk, m, el, acc)
        return s0 + block, m_new, el, acc

    _, _, el, acc = jax.lax.while_loop(
        lambda c: c[0] < limit, body, _attn_carry(b, t, kvh, g, d))
    return _attn_normalize(el, acc)


def _paged_split_kv_attention(qg: jax.Array, pk: jax.Array,
                              pv: jax.Array, table: jax.Array,
                              positions: jax.Array,
                              valid_len: jax.Array,
                              window: int,
                              k_scale: Optional[jax.Array] = None,
                              v_scale: Optional[jax.Array] = None
                              ) -> jax.Array:
    """Split-KV attention reading K/V THROUGH a per-slot block table.

    The paged twin of :func:`_split_kv_attention`: instead of each slot
    owning a contiguous (max_seq, ...) cache row, K/V live in one
    shared pool of fixed-size blocks and ``table[b, j]`` names the
    physical block holding slot ``b``'s logical chunk ``j``. Each
    ``lax.while_loop`` iteration gathers ``window // block_tokens``
    blocks per slot (a batched dynamic-slice of the table + one gather
    into the pool), reassembles the same (B, W, KVH, D) tile the dense
    loop slices out, and runs the IDENTICAL online-softmax tile
    (:func:`_attn_tile`) — so when ``window`` matches the dense path's
    block and tile boundaries align (window | max_seq, true for every
    shipped config), paged output is bit-identical to dense.

    pk/pv: (num_blocks, block_tokens, KVH, D) — ONE layer's pool.
    table: (B, table_len) int32; entries past a slot's frontier may be
    stale/zero (the scratch block) — their rows are masked to exact 0
    like any invalid dense row, so garbage never contributes.

    ``k_scale``/``v_scale`` ((num_blocks, KVH) f32, one layer's slice)
    arm the int8 pool: the SAME ``phys`` gather that pulls a tile's
    code blocks pulls their per-(block, head) scales, and the dequant
    multiply folds into the tile's existing f32 upcast — so
    :func:`_attn_tile` below stays the ONE online-softmax kernel
    shared with the dense loop, fed f32 tiles either way.
    """
    b, t, kvh, g, d = qg.shape
    bt = pk.shape[1]
    nb_win = window // bt
    if nb_win * bt != window:
        raise ValueError(f"window {window} must be a multiple of the "
                         f"block size {bt}")
    qf = qg.astype(jnp.float32)
    scale = d ** -0.5
    limit = jnp.max(jnp.minimum(positions[:, -1] + 1, valid_len))
    limit = jnp.minimum(limit, table.shape[1] * bt)

    def body(carry):
        s0, m, el, acc = carry
        phys = jax.lax.dynamic_slice(
            table, (jnp.int32(0), s0 // bt), (b, nb_win))  # (B, nbw)
        kb = pk[phys].astype(jnp.float32)       # (B, nbw, bt, KVH, D)
        vb = pv[phys].astype(jnp.float32)
        if k_scale is not None:
            kb = kb * k_scale[phys][:, :, None, :, None]
            vb = vb * v_scale[phys][:, :, None, :, None]
        kb = kb.reshape(b, window, kvh, d)
        vb = vb.reshape(b, window, kvh, d)
        kpos = s0 + jnp.arange(window)
        msk = ((kpos[None, None, :] >= s0) &
               (kpos[None, None, :] <= positions[..., None]) &
               (kpos[None, None, :] < valid_len[:, None, None]))
        m_new, el, acc = _attn_tile(qf, scale, kb, vb, msk, m, el, acc)
        return s0 + window, m_new, el, acc

    _, _, el, acc = jax.lax.while_loop(
        lambda c: c[0] < limit, body, _attn_carry(b, t, kvh, g, d))
    return _attn_normalize(el, acc)


def cached_attention_block(cfg, x: jax.Array, lp: Params,
                           ck: jax.Array, cv: jax.Array,
                           positions: jax.Array, start_pos: jax.Array,
                           valid_len: jax.Array,
                           write_pos: Optional[jax.Array] = None,
                           block: Optional[int] = None):
    """One pre-norm GQA attention residual block against the KV cache
    (shared by llama's and mixtral's decode paths). ``start_pos`` and
    ``valid_len`` are per-slot (B,) vectors — every slot in the batch
    may sit at a different sequence position (continuous batching).
    ``write_pos`` (B, T), when given, replaces the contiguous
    dynamic-update-slice cache write with a per-token row scatter whose
    out-of-bounds rows are DROPPED — the speculative verify_step write
    path, where a slot's draft tail may be shorter than the batch's
    static T (junk columns carry a sentinel >= max_seq and write
    nothing, so a short-draft slot can never clobber valid rows the
    way a clamped dynamic_update_slice would).
    Returns (x + attn_out, updated ck, updated cv)."""
    b, t = x.shape[0], x.shape[1]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    y = rms_norm(x, lp["attn_norm"], cfg.norm_eps,
                 getattr(cfg, "norm_offset", 0.0))
    q, k_new, v_new = qkv_proj(cfg, y, lp, positions)
    if write_pos is None:
        upd = lambda c, u, s: jax.lax.dynamic_update_slice(c, u,
                                                           (s, 0, 0))
        ck = jax.vmap(upd)(ck, k_new.astype(ck.dtype), start_pos)
        cv = jax.vmap(upd)(cv, v_new.astype(cv.dtype), start_pos)
    else:
        b_iota = jnp.arange(b)[:, None]
        ck = ck.at[b_iota, write_pos].set(k_new.astype(ck.dtype),
                                          mode="drop")
        cv = cv.at[b_iota, write_pos].set(v_new.astype(cv.dtype),
                                          mode="drop")
    # GQA grouped attention against the UNEXPANDED cache (the head-
    # order convention of ops/attention.py): q regrouped per KV head
    # so no repeat()ed copy of the cache hits HBM on the hot path.
    groups = h // kvh
    qg = q.reshape(b, t, kvh, groups, hd)
    attn = _split_kv_attention(qg, ck, cv, positions, valid_len,
                               block)
    attn = attn.astype(x.dtype).reshape(b, t, h * hd)
    return x + lora_dense(attn, lp, "wo"), ck, cv


def forward_with_cache(cfg, params: Params,
                       tokens: jax.Array, cache: Dict[str, jax.Array],
                       start_pos: jax.Array,
                       valid_len: Optional[jax.Array] = None,
                       logits_at: Optional[jax.Array] = None, *,
                       write_pos: Optional[jax.Array] = None,
                       mlp_fn=None, block: Optional[int] = None
                       ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Incremental forward: process a chunk, reading/writing the cache.

    tokens (B, T) are positions [start_pos, start_pos+T); returns
    (logits (B, T, vocab), updated cache). T == prompt length for
    prefill, T == 1 for each decode step; per-token cost is
    O(longest valid prefix), not O(seq^2) — the property a serving
    endpoint needs (vLLM/JetStream analog; the reference delegates this
    entirely to vLLM).

    ``start_pos``, ``valid_len`` and ``logits_at`` each accept a scalar
    (whole batch at one position — the bucketed fixed-batch path) OR a
    per-slot (B,) vector: under continuous batching every slot sits at
    its own sequence position, so the cache write offset, the
    attendable prefix, and the read-out index are all per-example.

    ``valid_len`` (default start_pos + T): cache positions >= valid_len
    are masked out of attention. Right-padded prefill chunks pass their
    true length so padding K/V never becomes attendable (padding slots
    are overwritten by later decode steps before valid_len reaches
    them). ``logits_at`` (chunk-relative index) computes the lm_head at
    just that position, returning (B, 1, vocab). ``block`` (static)
    overrides the split-KV attention tile width — the autotuner's
    dense-path knob; None keeps the SPLIT_KV_BLOCK default. Any
    aligned tile width is bit-identical (the online softmax is
    exact), so this is a perf knob, not a numerics one — the tuner's
    parity gate proves it per winner anyway.
    """
    b, t = tokens.shape
    start_pos = jnp.asarray(start_pos, jnp.int32)
    if start_pos.ndim == 0:
        start_pos = jnp.broadcast_to(start_pos, (b,))
    if valid_len is None:
        valid_len = start_pos + t
    valid_len = jnp.asarray(valid_len, jnp.int32)
    if valid_len.ndim == 0:
        valid_len = jnp.broadcast_to(valid_len, (b,))
    positions = start_pos[:, None] + jnp.arange(t)[None, :]  # (B, T)
    x = _decode_embed(cfg, params, tokens)

    # Pluggable residual MLP half — mixtral swaps in its dense-routed
    # MoE (models/mixtral.py) while the attention/cache/mask contract
    # (padding K/V never attendable) stays in exactly one place.
    mlp_fn = mlp_fn or (lambda cfg, x2, lp: mlp_block(cfg, x2, lp))

    def layer_fn(x, scanned):
        lp, ck, cv = scanned                               # per-layer
        x2, ck, cv = cached_attention_block(cfg, x, lp, ck, cv,
                                            positions, start_pos,
                                            valid_len,
                                            write_pos=write_pos,
                                            block=block)
        return mlp_fn(cfg, x2, lp), (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer_fn, x, (params["layers"], cache["k"], cache["v"]))
    if logits_at is not None:
        # Serving prefill reads exactly one position — skip the
        # O(T x vocab) head on the padded chunk.
        logits_at = jnp.asarray(logits_at, jnp.int32)
        if logits_at.ndim == 0:
            x = jax.lax.dynamic_slice_in_dim(x, logits_at, 1, axis=1)
        else:  # per-slot read-out (ragged prompt lengths)
            x = x[jnp.arange(b), logits_at][:, None]
    logits = lm_head(cfg, params, x, lambda a, _spec: a)
    return logits, {"k": new_k, "v": new_v}


def _quant_scatter_row(pk: jax.Array, ks: jax.Array, blk: jax.Array,
                       off: jax.Array, row: jax.Array):
    """Scatter one new K/V row per slot into the int8 pool, keeping
    the one-scale-per-(block, head) invariant.

    Works in CODE space: the row's absmax can only grow the block's
    scale (never shrink it), and when it doesn't — the common decode
    step — the rescale ratio is exactly 1.0, so existing codes round
    back to themselves and repeated steps never random-walk. When the
    row does grow the scale, the block's prior codes rescale once by
    old/new. ``off == 0`` (first row of a freshly granted block)
    resets the inherited scale: pool blocks recycle without zeroing,
    and a dead block's stale scale must not inflate the new
    sequence's quantization step. Free slots ride along targeting the
    scratch block (possibly many per batch — last write wins, scratch
    contents are never attendable).

    pk: (NB, BT, KVH, D) int8; ks: (NB, KVH) f32; blk/off: (B,) int32;
    row: (B, KVH, D). Returns (pk, ks) updated.
    """
    b = blk.shape[0]
    cur = pk[blk].astype(jnp.float32)               # (B, BT, KVH, D)
    old_s = jnp.where((off == 0)[:, None], 0.0, ks[blk])     # (B, KVH)
    row_s = jnp.max(jnp.abs(row.astype(jnp.float32)),
                    axis=-1) / 127.0
    new_s = jnp.maximum(jnp.maximum(old_s, row_s), 1e-8)
    ratio = (old_s / new_s)[:, None, :, None]
    scaled = jnp.round(cur * ratio)
    q_row = jnp.round(row.astype(jnp.float32) / new_s[..., None])
    scaled = scaled.at[jnp.arange(b), off].set(q_row)
    q = jnp.clip(scaled, -127, 127).astype(jnp.int8)
    return pk.at[blk].set(q), ks.at[blk].set(new_s)


def _quant_block_write(pk: jax.Array, ks: jax.Array,
                       write_block: jax.Array, rows: jax.Array,
                       valid_rows: jax.Array):
    """Whole-block int8 overwrite (single-slot chunk prefill): a fresh
    per-(block, head) scale from the chunk's VALID rows — a
    right-padded final chunk's junk rows are excluded so padding can
    never inflate the quantization step — then every row quantized
    under it (junk rows too; they are masked at read like any invalid
    row). rows: (BT, KVH, D); valid_rows: (BT,) bool."""
    rf = rows.astype(jnp.float32)
    masked = jnp.where(valid_rows[:, None, None], jnp.abs(rf), 0.0)
    s = jnp.maximum(jnp.max(masked, axis=(0, 2)) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(rf / s[None, :, None]),
                 -127, 127).astype(jnp.int8)
    return pk.at[write_block].set(q), ks.at[write_block].set(s)


def paged_attention_block(cfg, x: jax.Array, lp: Params,
                          pk: jax.Array, pv: jax.Array,
                          table: jax.Array, positions: jax.Array,
                          start_pos: jax.Array, valid_len: jax.Array,
                          window: int,
                          write_block: Optional[jax.Array],
                          write_pos: Optional[jax.Array] = None,
                          ks: Optional[jax.Array] = None,
                          vs: Optional[jax.Array] = None):
    """One pre-norm GQA attention residual block against the PAGED KV
    pool (the block-table twin of :func:`cached_attention_block`).

    Writes route through the table: T == 1 (batched decode step)
    scatters each slot's new K/V row into block ``table[b, pos//bt]``
    at offset ``pos % bt`` — free slots ride along with table row 0
    (the scratch block), so their ignored writes can never clobber a
    live slot's block. T == block_tokens (single-slot chunk prefill,
    B == 1, chunk-aligned) overwrites the whole physical block
    ``write_block``. Aliased (shared-prefix) blocks are never write
    targets: admission aligns the cached prefix to whole blocks and
    prefill/decode only ever write from the first non-cached block on.
    ``ks``/``vs`` ((num_blocks, KVH) f32 per-layer scale slices) arm
    the int8 pool: every write path quantizes against the target
    block's one-scale-per-(block, head) entry (fresh scale on
    whole-block prefill, grow-only code-space rescale on row
    scatters) and the attention gather dequantizes with the same
    scales. Returns (x + attn_out, pk, pv, ks, vs) with the pool
    updated in place under donation."""
    b, t = x.shape[0], x.shape[1]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    bt = pk.shape[1]
    quant = ks is not None
    y = rms_norm(x, lp["attn_norm"], cfg.norm_eps,
                 getattr(cfg, "norm_offset", 0.0))
    q, k_new, v_new = qkv_proj(cfg, y, lp, positions)
    if write_pos is not None:
        # Speculative verify: per-(slot, token) scatter THROUGH the
        # table. Junk columns (a slot's draft tail shorter than the
        # batch's static T) carry a sentinel >= the table span and
        # route to the scratch block — like free slots' rides, their
        # garbage is masked to exact 0 by valid_len, never attendable.
        span = table.shape[1] * bt
        ok = write_pos < span
        blk_idx = jnp.clip(write_pos // bt, 0, table.shape[1] - 1)
        blk = jnp.where(ok, jnp.take_along_axis(table, blk_idx,
                                                axis=1), 0)
        off = jnp.where(ok, write_pos % bt, 0)
        if quant:
            # Columns in order: the verify window's positions are
            # consecutive per slot, so a block boundary (off == 0,
            # scale reset) is always crossed BEFORE that block's
            # later offsets are written.
            for j in range(t):
                pk, ks = _quant_scatter_row(pk, ks, blk[:, j],
                                            off[:, j], k_new[:, j])
                pv, vs = _quant_scatter_row(pv, vs, blk[:, j],
                                            off[:, j], v_new[:, j])
        else:
            pk = pk.at[blk, off].set(k_new.astype(pk.dtype))
            pv = pv.at[blk, off].set(v_new.astype(pv.dtype))
    elif t == 1:
        blk = jnp.take_along_axis(table, (start_pos // bt)[:, None],
                                  axis=1)[:, 0]
        off = start_pos % bt
        if quant:
            pk, ks = _quant_scatter_row(pk, ks, blk, off, k_new[:, 0])
            pv, vs = _quant_scatter_row(pv, vs, blk, off, v_new[:, 0])
        else:
            pk = pk.at[blk, off].set(k_new[:, 0].astype(pk.dtype))
            pv = pv.at[blk, off].set(v_new[:, 0].astype(pv.dtype))
    else:
        if b != 1 or t != bt or write_block is None:
            raise ValueError(
                "paged chunk prefill needs B == 1, T == block_tokens "
                "and a write_block (chunk-aligned whole-block write); "
                f"got B={b}, T={t}, block_tokens={bt}")
        if quant:
            valid_rows = positions[0] < valid_len[0]
            pk, ks = _quant_block_write(pk, ks, write_block,
                                        k_new[0], valid_rows)
            pv, vs = _quant_block_write(pv, vs, write_block,
                                        v_new[0], valid_rows)
        else:
            pk = pk.at[write_block].set(k_new[0].astype(pk.dtype))
            pv = pv.at[write_block].set(v_new[0].astype(pv.dtype))
    groups = h // kvh
    qg = q.reshape(b, t, kvh, groups, hd)
    attn = _paged_split_kv_attention(qg, pk, pv, table, positions,
                                     valid_len, window,
                                     k_scale=ks, v_scale=vs)
    attn = attn.astype(x.dtype).reshape(b, t, h * hd)
    return x + lora_dense(attn, lp, "wo"), pk, pv, ks, vs


def forward_with_paged_cache(cfg, params: Params, tokens: jax.Array,
                             cache: Dict[str, jax.Array],
                             table: jax.Array, start_pos: jax.Array,
                             valid_len: Optional[jax.Array] = None,
                             logits_at: Optional[jax.Array] = None, *,
                             window: int,
                             write_block: Optional[jax.Array] = None,
                             write_pos: Optional[jax.Array] = None,
                             mlp_fn=None
                             ) -> Tuple[jax.Array,
                                        Dict[str, jax.Array]]:
    """Incremental forward against the paged block pool — the same
    scalar-or-(B,) ``start_pos``/``valid_len``/``logits_at`` contract
    as :func:`forward_with_cache`, with the KV cache replaced by
    ``cache`` (init_paged_cache pool, DONATED by callers) plus
    ``table`` (B, table_len) int32 block tables. ``window`` (static)
    is the attention tile width; match it to the dense path's
    ``min(SPLIT_KV_BLOCK, max_seq)`` for bit-parity. ``write_block``
    is the single-slot prefill write target (see
    :func:`paged_attention_block`)."""
    b, t = tokens.shape
    start_pos = jnp.asarray(start_pos, jnp.int32)
    if start_pos.ndim == 0:
        start_pos = jnp.broadcast_to(start_pos, (b,))
    if valid_len is None:
        valid_len = start_pos + t
    valid_len = jnp.asarray(valid_len, jnp.int32)
    if valid_len.ndim == 0:
        valid_len = jnp.broadcast_to(valid_len, (b,))
    positions = start_pos[:, None] + jnp.arange(t)[None, :]  # (B, T)
    x = _decode_embed(cfg, params, tokens)

    # Pluggable residual MLP half, exactly as in forward_with_cache
    # (mixtral swaps in its dense-routed MoE).
    mlp_fn = mlp_fn or (lambda cfg, x2, lp: mlp_block(cfg, x2, lp))

    quantized = "k_scale" in cache

    def layer_fn(x, scanned):
        if quantized:
            lp, pk, pv, ks, vs = scanned                   # per-layer
        else:
            (lp, pk, pv), ks, vs = scanned, None, None
        x2, pk, pv, ks, vs = paged_attention_block(
            cfg, x, lp, pk, pv, table, positions, start_pos,
            valid_len, window, write_block, write_pos=write_pos,
            ks=ks, vs=vs)
        return mlp_fn(cfg, x2, lp), ((pk, pv, ks, vs) if quantized
                                     else (pk, pv))

    if quantized:
        # Scales ride the layer scan beside the code pools so the
        # whole cache tree stays donate-aliasable through the jitted
        # serving entry points (scales update in place like codes).
        x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
            layer_fn, x, (params["layers"], cache["k"], cache["v"],
                          cache["k_scale"], cache["v_scale"]))
        new_cache = {"k": new_k, "v": new_v,
                     "k_scale": new_ks, "v_scale": new_vs}
    else:
        x, (new_k, new_v) = jax.lax.scan(
            layer_fn, x, (params["layers"], cache["k"], cache["v"]))
        new_cache = {"k": new_k, "v": new_v}
    if logits_at is not None:
        logits_at = jnp.asarray(logits_at, jnp.int32)
        if logits_at.ndim == 0:
            x = jax.lax.dynamic_slice_in_dim(x, logits_at, 1, axis=1)
        else:  # per-slot read-out (ragged prompt lengths)
            x = x[jnp.arange(b), logits_at][:, None]
    logits = lm_head(cfg, params, x, lambda a, _spec: a)
    return logits, new_cache


def _verify_write_positions(t: int, start_pos: jax.Array,
                            spec_len: jax.Array,
                            span: int) -> jax.Array:
    """(B, T) cache-write positions for a speculative verify window:
    column j of slot b lands at start_pos[b] + j while j <= spec_len[b]
    (the slot's real token + its drafts) and at the out-of-range
    sentinel ``span`` past its draft tail — dense scatters DROP those
    rows, the paged scatter routes them to the scratch block. Either
    way a short-draft slot's junk columns write nothing attendable."""
    offs = jnp.arange(t)[None, :]
    wpos = start_pos[:, None] + offs
    return jnp.where(offs <= spec_len[:, None], wpos, span)


def verify_step(cfg, params: Params, tokens: jax.Array,
                cache: Dict[str, jax.Array], start_pos: jax.Array,
                spec_len: jax.Array, *, mlp_fn=None,
                block: Optional[int] = None
                ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Multi-token speculative verification against the dense cache.

    ``tokens`` (B, T) is, per slot, its last emitted token followed by
    up to T-1 drafted tokens (``spec_len`` (B,) real drafts; the tail
    is padding). One forward computes logits at ALL T positions —
    column j is the target distribution for the token at absolute
    position ``start_pos + j + 1``, conditioned on the draft prefix
    whose K/V this same pass wrote — which is what lets the engine
    accept k drafted tokens for the price of one memory-bound pass
    (the per-slot (B,) start_pos/valid_len contract generalized to a
    per-slot (B, T) logits-at-positions read-out).

    Writes scatter per token with out-of-bounds DROP semantics
    (:func:`_verify_write_positions`), so rejected/padded suffixes
    never land where a clamped dynamic_update_slice would corrupt
    valid rows; ``valid_len = start_pos + spec_len + 1`` masks each
    slot's junk columns out of every other query. The engine rolls a
    rejected suffix back host-side by simply not advancing ``pos``
    past the accepted frontier — rows beyond it are stale-masked, the
    exact invariant slot reuse already relies on.

    Returns (logits (B, T, vocab), cache).
    """
    b, t = tokens.shape
    start_pos = jnp.asarray(start_pos, jnp.int32)
    if start_pos.ndim == 0:
        start_pos = jnp.broadcast_to(start_pos, (b,))
    spec_len = jnp.asarray(spec_len, jnp.int32)
    if spec_len.ndim == 0:
        spec_len = jnp.broadcast_to(spec_len, (b,))
    max_seq = cache["k"].shape[2]
    wpos = _verify_write_positions(t, start_pos, spec_len, max_seq)
    return forward_with_cache(
        cfg, params, tokens, cache, start_pos,
        valid_len=start_pos + spec_len + 1, write_pos=wpos,
        mlp_fn=mlp_fn, block=block)


def verify_step_paged(cfg, params: Params, tokens: jax.Array,
                      cache: Dict[str, jax.Array], table: jax.Array,
                      start_pos: jax.Array, spec_len: jax.Array, *,
                      window: int, mlp_fn=None
                      ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """:func:`verify_step` against the paged block pool: the same
    (B, T) verify window with writes scattered THROUGH each slot's
    block table (junk columns route to the scratch block) and
    attention gathered by :func:`_paged_split_kv_attention`. The
    engine backs the window's blocks from the slot's admission
    reservation before the call and truncates the rejected suffix's
    blocks back afterwards."""
    b, t = tokens.shape
    start_pos = jnp.asarray(start_pos, jnp.int32)
    if start_pos.ndim == 0:
        start_pos = jnp.broadcast_to(start_pos, (b,))
    spec_len = jnp.asarray(spec_len, jnp.int32)
    if spec_len.ndim == 0:
        spec_len = jnp.broadcast_to(spec_len, (b,))
    span = table.shape[1] * cache["k"].shape[2]
    wpos = _verify_write_positions(t, start_pos, spec_len, span)
    return forward_with_paged_cache(
        cfg, params, tokens, cache, table, start_pos,
        valid_len=start_pos + spec_len + 1, window=window,
        write_pos=wpos, mlp_fn=mlp_fn)


def decode(cfg: LlamaConfig, params: Params, prompt: jax.Array,
           true_len: jax.Array, max_tokens: int, max_seq: int,
           temperature: float = 0.0,
           key: Optional[jax.Array] = None, *,
           fwd_cache=None, cache_init=None,
           cache=None, return_cache: bool = False) -> jax.Array:
    """Prefill + cached decode: prompt (B, S_pad) -> (B, max_tokens).

    ``true_len`` is the un-padded prompt length — a scalar shared by
    the whole batch, or a per-example (B,) vector: a RAGGED batch
    (heterogeneous prompt lengths right-padded to one bucket) decodes
    in a single batched call, each row masked to its own valid prefix
    and read out at its own last prompt token. One O(S) prefill pass,
    then max_tokens steps each bounded by the longest live prefix
    (split-KV attention). temperature == 0 is greedy; > 0 samples from
    softmax(logits/T) (key required).

    ``cache``: optional preallocated KV cache (init_cache layout).
    Callers that jit this function should allocate the cache outside,
    DONATE it (``donate_argnums``), and pass ``return_cache=True`` so
    the final cache is part of the jit output — XLA only aliases a
    donated input to an output, so without returning it the donation
    is inert and each call still materializes a second full-size cache
    in HBM. With it, the O(layers * batch * max_seq) buffer updates in
    place (the caller simply drops the returned cache).
    """
    true_len = jnp.asarray(true_len, jnp.int32)
    b, s_pad = prompt.shape
    if true_len.ndim == 0:
        true_len = jnp.broadcast_to(true_len, (b,))
    elif true_len.shape != (b,):
        raise ValueError(
            f"true_len must be a scalar or a (batch,) vector of "
            f"un-padded prompt lengths; got shape {true_len.shape} "
            f"for batch {b}.")
    if s_pad + max_tokens > max_seq:
        raise ValueError(
            f"prompt ({s_pad}) + max_tokens ({max_tokens}) exceeds the "
            f"cache (max_seq={max_seq}); dynamic_update_slice would "
            f"silently clamp and corrupt the tail.")
    if temperature > 0.0 and key is None:
        raise ValueError("temperature > 0 needs a PRNG key")
    if key is None:
        key = jax.random.key(0)  # unused on the greedy path

    def pick(logits_row, k):
        if temperature > 0.0:
            return jax.random.categorical(
                k, logits_row / temperature, axis=-1).astype(jnp.int32)
        return jnp.argmax(logits_row, axis=-1).astype(jnp.int32)

    # Pluggable cache fns: mixtral reuses this loop with its MoE layers
    # (models/mixtral.py decode).
    fwd_cache = fwd_cache or forward_with_cache
    cache_init = cache_init or init_cache
    if cache is None:
        cache = cache_init(cfg, b, max_seq)
    logits, cache = fwd_cache(
        cfg, params, prompt, cache, jnp.int32(0), valid_len=true_len,
        logits_at=true_len - 1)
    key, sub = jax.random.split(key)
    first = pick(logits[:, 0], sub)

    def step(carry, i):
        tok, cache, key = carry
        logits, cache = fwd_cache(
            cfg, params, tok[:, None], cache, true_len + i)
        key, sub = jax.random.split(key)
        nxt = pick(logits[:, -1], sub)
        return (nxt, cache, key), tok

    (_, cache, _), toks = jax.lax.scan(
        step, (first, cache, key),
        jnp.arange(max_tokens, dtype=jnp.int32))
    if return_cache:
        return toks.T, cache
    return toks.T                                          # (B, max_tokens)


def greedy_decode(cfg: LlamaConfig, params: Params, prompt: jax.Array,
                  true_len: jax.Array, max_tokens: int,
                  max_seq: int) -> jax.Array:
    return decode(cfg, params, prompt, true_len, max_tokens, max_seq)


def forward_pipelined(cfg: LlamaConfig, params: Params, tokens: jax.Array,
                      *, mesh, rules, num_microbatches: int,
                      positions: Optional[jax.Array] = None) -> jax.Array:
    """GPipe-pipelined forward: layer stack split into mesh.shape['pp']
    stages, batch split into microbatches. Use with PIPELINE_RULES so the
    stored layer stack is sharded over pp and the stage reshape is local.
    """
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import pipeline as pipeline_lib

    if cfg.attention_impl == "ring":
        raise NotImplementedError(
            "attention_impl='ring' is not supported under pipeline "
            "parallelism: ring attention's shard_map over 'sp' cannot nest "
            "inside the pipeline's shard_map over 'pp'. Use ring attention "
            "with a dp/sp/tp mesh, or pipeline with impl='auto'.")
    n_stages = mesh.shape.get(mesh_lib.PP, 1)
    if cfg.n_layers % max(n_stages, 1):
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"pp={n_stages}")
    b, s = tokens.shape
    m = num_microbatches
    if b % m:
        raise ValueError(f"batch={b} not divisible by microbatches={m}")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    def constrain(x, spec):
        return mesh_lib.constrain(x, mesh, rules, spec)

    x = embed_tokens(params, tokens, constrain)
    d = x.shape[-1]

    # (L, ...) -> (P, L/P, ...): local view change under PIPELINE_RULES.
    def to_stages(a):
        return a.reshape(n_stages, cfg.n_layers // n_stages, *a.shape[1:])
    stage_params = jax.tree.map(to_stages, params["layers"])
    stage_params = jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(
            a, rules.sharding(("stage", "layers") + (None,) * (a.ndim - 2),
                              mesh)),
        stage_params)

    x_mb = x.reshape(m, b // m, s, d)
    pos_mb = positions.reshape(m, b // m, s)

    def stage_fn(lp, x_in, pos_in):
        def layer_fn(carry, layer_p):
            return _layer(cfg, carry, layer_p, pos_in,
                          lambda a, _spec: a), None
        if cfg.remat:
            layer_fn = jax.checkpoint(layer_fn, prevent_cse=False)
        out, _ = jax.lax.scan(layer_fn, x_in, lp)
        return out

    x = pipeline_lib.gpipe(stage_fn, stage_params, x_mb, pos_mb,
                           mesh=mesh, num_microbatches=m)
    x = x.reshape(b, s, d)
    return lm_head(cfg, params, x, constrain)
