"""Shared append path for the observability JSONL logs.

The lifecycle event log (events.py) and the trace sink (tracing.py)
both append one JSON object per line to a log under
``~/.stpu/logs/``, rotate at a size cap keeping exactly ONE ``.1``
generation, and must never raise into the instrumented call. That
durability-critical write path lives HERE once, so a fix to it
(rotation policy, fsync discipline) cannot land in one log and
silently miss the other. The readers stay per-module: their access
patterns genuinely differ (events tails bounded byte windows and
filters by kind/name/time; tracing reads whole generations and groups
by trace id).
"""
from __future__ import annotations

import os


def rotate_if_needed(path, max_bytes: int) -> None:
    """current -> current.1 once the size cap is crossed (the previous
    ``.1`` is overwritten: one retained generation). Never raises."""
    try:
        if path.stat().st_size < max_bytes:
            return
        os.replace(path, str(path) + ".1")
    except OSError:
        pass


def append_line(path, line: str, max_bytes: int, lock) -> None:
    """Append one record line under ``lock`` (the caller's module
    lock), rotating first if needed. I/O failures are swallowed —
    telemetry must never break the instrumented call."""
    try:
        with lock:
            rotate_if_needed(path, max_bytes)
            with open(path, "a") as f:
                f.write(line + "\n")
    except OSError:
        pass
