"""Runtime observability: metrics registry + structured event log.

Two stdlib-only primitives every long-running stpu process shares:

* ``metrics`` — a thread-safe registry of Counter/Gauge/Histogram
  families with label support and Prometheus text exposition
  (``/metrics`` on the serve load balancer, ``stpu metrics`` locally,
  a textfile dump from the host agent).
* ``events`` — an append-only JSONL lifecycle log (cluster/job/replica
  state transitions) stamped with wall + monotonic time and a run ID
  that propagates CLI -> gang driver -> job environment.
* ``tracing`` — per-request/per-launch distributed spans (trace_id /
  span_id / parent) reassembled into causal trees by ``stpu trace``;
  context propagates LB -> replica via the ``X-STPU-Trace`` header and
  host-to-host via ``STPU_TRACE_CTX`` (the run-ID pattern). Off by
  default; hot paths guard on ``tracing.ENABLED``.
* ``promtext`` — the exposition PARSER dual to ``metrics.render()``,
  shared by the loadgen scraper, bench gates, and tests so ad-hoc
  string matching over scraped documents never reappears.
* ``stepstats`` — per-engine-step performance telemetry (fixed-size
  step ring recorded from the decode engine's supervisor loop, phase
  breakdown on ``GET /perf``, sampled dispatch-vs-device sync split)
  plus the crash flight recorder (``~/.stpu/logs/flightrec/``). Off
  by default; hot paths guard on ``stepstats.ENABLED``.

None may ever break the instrumented call: all I/O failures are
swallowed, and recording is lock-free on hot paths except for the
single child-update lock held for the increment itself.
"""
from skypilot_tpu.observability import events
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import promtext
from skypilot_tpu.observability import stepstats
from skypilot_tpu.observability import tracing

__all__ = ["events", "metrics", "promtext", "stepstats", "tracing"]
