"""Ring-buffered fleet time-series store with downsampling tiers.

The serve controller's fleet collector (serve/fleet.py) scrapes every
ready replica's /metrics + /perf each tick and needs somewhere to PUT
the history: the controller previously threw each scrape away, so an
operator diagnosing a p99 regression had no view older than the
current scrape and the autoscaler could only act on raw QPS. This
store is that somewhere — bounded, stdlib-only, controller-resident.

Two tiers per series (the Prometheus-recording-rule shape, collapsed
into one in-process structure):

* **raw** — one point per ``raw_seconds`` bucket, kept for
  ``raw_retention`` seconds (default 10s resolution for 15 min);
* **rollup** — raw points aging out of the raw window fold into
  ``rollup_seconds`` buckets carrying ``(count, sum, min, max)``,
  kept for ``rollup_retention`` seconds (default 1 min rollups for
  24 h). A rollup bucket's value is its mean; min/max survive so a
  spike is not averaged out of existence.

Downsampling math: a point stamped ``ts`` belongs to rollup bucket
``floor(ts / rollup_seconds) * rollup_seconds``; folding adds it to
the bucket's running ``(count, sum, min, max)``. Memory is therefore
bounded by ``raw_retention / raw_seconds + rollup_retention /
rollup_seconds`` buckets per series, independent of scrape rate.

Histograms are stored as CUMULATIVE bucket snapshots
(promtext.HistogramSnapshot — the existing parser's shape), not as
per-window deltas: cumulative counts are monotone, so the delta
between ANY two retained snapshots is a valid window distribution
(``HistogramSnapshot.delta``), and downsampling is just keeping fewer
snapshots — one per rollup bucket beyond the raw window — with no
re-aggregation. Quantiles over a window share
``metrics.quantile_from_cumulative`` with the loadgen scraper, so a
stored p99 and a client-side report can never disagree on the math.

Counters are recorded as their cumulative totals (what the scrape
returns); ``rate()``/``window_delta()`` difference them, clamping a
process-restart reset to zero rather than reporting a negative rate.
"""
from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.observability.promtext import HistogramSnapshot

DEFAULT_RAW_SECONDS = 10.0
DEFAULT_RAW_RETENTION = 900.0           # 15 min
DEFAULT_ROLLUP_SECONDS = 60.0
DEFAULT_ROLLUP_RETENTION = 86400.0      # 24 h

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _matches(key: _LabelKey, subset: Dict[str, Any]) -> bool:
    """Label-subset match, same contract as promtext.histogram: naming
    no labels matches every series of the name."""
    have = dict(key)
    return all(have.get(str(k)) == str(v) for k, v in subset.items())


class _RollupBucket:
    __slots__ = ("ts", "count", "sum", "min", "max")

    def __init__(self, ts: float, value: float):
        self.ts = ts
        self.count = 1
        self.sum = value
        self.min = value
        self.max = value

    def fold(self, value: float) -> None:
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)

    @property
    def mean(self) -> float:
        return self.sum / self.count


class _ScalarSeries:
    """Raw (ts, value) points + rollup buckets for one labeled series."""

    def __init__(self) -> None:
        self.raw: List[Tuple[float, float]] = []
        self.rollup: List[_RollupBucket] = []

    def record(self, ts: float, value: float, raw_seconds: float) -> None:
        # One point per raw bucket: a collector ticking faster than the
        # raw resolution overwrites in place instead of growing the ring.
        if self.raw and ts - self.raw[-1][0] < raw_seconds:
            self.raw[-1] = (self.raw[-1][0], value)
        else:
            self.raw.append((ts, value))

    def downsample(self, now: float, raw_retention: float,
                   rollup_seconds: float, rollup_retention: float) -> None:
        cutoff = now - raw_retention
        while self.raw and self.raw[0][0] < cutoff:
            ts, value = self.raw.pop(0)
            bucket_ts = math.floor(ts / rollup_seconds) * rollup_seconds
            if self.rollup and self.rollup[-1].ts == bucket_ts:
                self.rollup[-1].fold(value)
            else:
                self.rollup.append(_RollupBucket(bucket_ts, value))
        drop = now - rollup_retention
        while self.rollup and self.rollup[0].ts < drop:
            self.rollup.pop(0)


class _HistSeries:
    """Cumulative HistogramSnapshots, thinned to one per rollup bucket
    beyond the raw window (cumulative snapshots delta-compose, so
    keeping fewer IS the downsampling)."""

    def __init__(self) -> None:
        self.snaps: List[Tuple[float, HistogramSnapshot]] = []

    def record(self, ts: float, snap: HistogramSnapshot,
               raw_seconds: float) -> None:
        if self.snaps and ts - self.snaps[-1][0] < raw_seconds:
            self.snaps[-1] = (self.snaps[-1][0], snap)
        else:
            self.snaps.append((ts, snap))

    def downsample(self, now: float, raw_retention: float,
                   rollup_seconds: float, rollup_retention: float) -> None:
        cutoff = now - raw_retention
        kept: List[Tuple[float, HistogramSnapshot]] = []
        last_bucket: Optional[float] = None
        for ts, snap in self.snaps:
            if ts >= cutoff:
                kept.append((ts, snap))
                continue
            if ts < now - rollup_retention:
                continue
            bucket_ts = math.floor(ts / rollup_seconds) * rollup_seconds
            if bucket_ts != last_bucket:
                kept.append((ts, snap))
                last_bucket = bucket_ts
            else:
                # Newest snapshot wins within a bucket: cumulative
                # counts make the latest the most informative.
                kept[-1] = (kept[-1][0], snap)
        self.snaps = kept


class TimeSeriesStore:
    """Thread-safe store; all reads/writes take one lock (collector
    thread writes, the /fleet handler and SLO monitor read)."""

    def __init__(self, raw_seconds: float = DEFAULT_RAW_SECONDS,
                 raw_retention: float = DEFAULT_RAW_RETENTION,
                 rollup_seconds: float = DEFAULT_ROLLUP_SECONDS,
                 rollup_retention: float = DEFAULT_ROLLUP_RETENTION):
        self.raw_seconds = float(raw_seconds)
        self.raw_retention = float(raw_retention)
        self.rollup_seconds = float(rollup_seconds)
        self.rollup_retention = float(rollup_retention)
        self._lock = threading.Lock()
        self._scalars: Dict[Tuple[str, _LabelKey], _ScalarSeries] = {}
        self._hists: Dict[Tuple[str, _LabelKey], _HistSeries] = {}

    # --------------------------------------------------------- writes
    def record(self, name: str, value: float, ts: float,
               **labels: Any) -> None:
        """Record one scalar point (gauge reading or cumulative counter
        total). NaN points are dropped at the door: NaN in the store
        would poison every mean/rate computed over the window."""
        value = float(value)
        if math.isnan(value):
            return
        key = (name, _label_key(labels))
        with self._lock:
            series = self._scalars.get(key)
            if series is None:
                series = self._scalars[key] = _ScalarSeries()
            series.record(ts, value, self.raw_seconds)
            series.downsample(ts, self.raw_retention,
                              self.rollup_seconds, self.rollup_retention)

    def record_histogram(self, name: str, snap: HistogramSnapshot,
                         ts: float, **labels: Any) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            series = self._hists.get(key)
            if series is None:
                series = self._hists[key] = _HistSeries()
            series.record(ts, snap, self.raw_seconds)
            series.downsample(ts, self.raw_retention,
                              self.rollup_seconds, self.rollup_retention)

    # ---------------------------------------------------------- reads
    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({n for n, _ in self._scalars} |
                          {n for n, _ in self._hists})

    def labels_for(self, name: str) -> List[Dict[str, str]]:
        with self._lock:
            keys = [k for (n, k) in list(self._scalars) +
                    list(self._hists) if n == name]
        return [dict(k) for k in sorted(set(keys))]

    def latest(self, name: str, **labels: Any) -> Optional[float]:
        """Newest raw point across matching series (summed when more
        than one matches — the counter-family convention)."""
        with self._lock:
            vals = [s.raw[-1][1]
                    for (n, k), s in self._scalars.items()
                    if n == name and _matches(k, labels) and s.raw]
        if not vals:
            return None
        return sum(vals)

    def points(self, name: str, since: Optional[float] = None,
               **labels: Any) -> List[Tuple[float, float]]:
        """Merged (ts, value) points for one series, rollup tier first
        (rollup buckets surface their mean), bounded by ``since``.
        Matching multiple label-sets concatenates them — name enough
        labels to address one series when plotting."""
        out: List[Tuple[float, float]] = []
        with self._lock:
            for (n, k), s in self._scalars.items():
                if n != name or not _matches(k, labels):
                    continue
                out.extend((b.ts, b.mean) for b in s.rollup)
                out.extend(s.raw)
        out.sort()
        if since is not None:
            out = [(t, v) for t, v in out if t >= since]
        return out

    def window_delta(self, name: str, window: float, now: float,
                     **labels: Any) -> Optional[float]:
        """Cumulative-counter increase over the trailing window, summed
        across matching series. A reset (value dropped) clamps that
        series' contribution to the post-reset total — never negative.
        None when no matching series has any data."""
        found = False
        total = 0.0
        with self._lock:
            items = [(k, list(s.rollup), list(s.raw))
                     for (n, k), s in self._scalars.items()
                     if n == name and _matches(k, labels)]
        for _, rollup, raw in items:
            pts = [(b.ts, b.max) for b in rollup] + raw
            if not pts:
                continue
            found = True
            cutoff = now - window
            # Baseline = newest point at-or-before the window start
            # (the counter total as the window opened); fall back to
            # the oldest retained point for short histories.
            baseline = None
            for ts, v in pts:
                if ts <= cutoff:
                    baseline = v
                else:
                    break
            if baseline is None:
                baseline = pts[0][1]
            latest = pts[-1][1]
            total += latest - baseline if latest >= baseline else latest
        return total if found else None

    def rate(self, name: str, window: float, now: float,
             **labels: Any) -> Optional[float]:
        delta = self.window_delta(name, window, now, **labels)
        if delta is None or window <= 0:
            return None
        return delta / window

    def histogram_delta(self, name: str, window: float, now: float,
                        **labels: Any) -> Optional[HistogramSnapshot]:
        """The distribution observed over the trailing window: latest
        snapshot minus the snapshot at the window's start, summed
        bucket-wise across matching series (per-replica histograms
        compose into the fleet view). None when no series matches or
        bucket bounds changed mid-window."""
        with self._lock:
            series = [list(s.snaps)
                      for (n, k), s in self._hists.items()
                      if n == name and _matches(k, labels) and s.snaps]
        merged: Optional[HistogramSnapshot] = None
        for snaps in series:
            cutoff = now - window
            baseline = None
            for ts, snap in snaps:
                if ts <= cutoff:
                    baseline = snap
                else:
                    break
            latest = snaps[-1][1]
            if baseline is None:
                # Short history: the oldest snapshot is the baseline —
                # unless it IS the latest, in which case the window
                # holds zero observations by construction.
                baseline = snaps[0][1]
            try:
                delta = latest.delta(baseline)
            except ValueError:
                # Bucket bounds changed (replica restart with a new
                # layout): the delta is undefined — skip the series.
                continue
            if merged is None:
                merged = delta
            elif merged.bounds == delta.bounds:
                merged = HistogramSnapshot(
                    bounds=list(merged.bounds),
                    cumulative=[a + b for a, b in
                                zip(merged.cumulative, delta.cumulative)],
                    sum=merged.sum + delta.sum,
                    count=merged.count + delta.count)
            # Mismatched bounds across series: keep the first; summing
            # incompatible layouts would fabricate a distribution.
        return merged

    def to_doc(self, name: str, since: Optional[float] = None
               ) -> Dict[str, Any]:
        """JSON-ready series dump for GET /fleet?series=NAME."""
        series = []
        with self._lock:
            label_sets = sorted({k for (n, k) in self._scalars
                                 if n == name})
        for key in label_sets:
            series.append({"labels": dict(key),
                           "points": self.points(name, since=since,
                                                 **dict(key))})
        return {"series": name, "data": series}
