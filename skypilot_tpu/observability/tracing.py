"""End-to-end distributed tracing: request + launch spans.

PR-1 gave stpu aggregate metrics ("how many requests were slow") and
lifecycle events ("what state changed"); neither answers "why was THIS
request's TTFT 4s". This module adds the causal, per-request view: a
span is one timed hop (LB proxy attempt, replica generate, engine
prefill, gang launch), spans share a ``trace_id``, and parent links
reassemble them into a tree — LB root → replica → engine children for
a request, jobs controller → gang driver → hosts for a launch.

Reference analog: the reference leans on Ray's dashboard timeline for
this; a TPU-native stack needs its own. Deliberately NOT OpenTelemetry
(the container bakes no SDK): the same ids/parenting model, stdlib
only, with Chrome trace-event export (``stpu trace export --perfetto``)
so the result still loads in Perfetto / chrome://tracing alongside the
on-device XLA profiles ``callbacks.device_profile()`` captures.

Record shape (one JSON object per line in ``traces.jsonl``, written on
span END so every record is complete):

    {"trace_id": <32 hex>, "span_id": <16 hex>, "parent_id": ...|null,
     "name": "lb.request", "kind": "lb", "ts": <wall start seconds>,
     "dur": <monotonic-clock seconds>, "status": "ok",
     "pid": ..., "tid": ..., "run_id": ...,
     "attrs": {...}, "events": [{"name": "retry", "at": <sec offset>}]}

``ts`` is wall clock for cross-host alignment; ``dur`` (and event
offsets) come from ``time.perf_counter()`` so an NTP step mid-span
cannot produce a negative duration (the stpu-wallclock rule of `stpu check`).

Context propagation:

  * HTTP hop (LB → replica): the ``X-STPU-Trace`` header carries
    ``<trace_id>-<span_id>-<01|00>`` (last field: sampled flag);
    ``extract(headers)`` / ``format_ctx(span.context())`` are the two
    ends.
  * host-to-host (jobs controller → gang driver → job env): the
    ``STPU_TRACE_CTX`` env var carries the same string, the exact
    pattern ``STPU_RUN_ID`` uses (events.py) — set_env_context() on
    the parent side, from_env() on the child side, child_env() to
    stamp a subprocess environment.

Overhead discipline (mirror of utils/fault_injection.py): tracing is
OFF by default; hot call sites guard with the module attribute
``ENABLED`` (``if tracing.ENABLED: ...``) so the unarmed cost is one
global load and a falsy branch — no span objects, no clock reads, no
allocation. Arm with ``STPU_TRACE=1`` (every process in the stack picks
it up at import) or ``arm()`` in tests. ``STPU_TRACE_SAMPLE`` in [0, 1]
samples at ROOT-span granularity; a child follows its parent's sampled
decision (carried in the header/env flag — including the NEGATIVE
decision, via an unsampled carrier span), so a trace is always whole
or absent, never torn. Disabled paths get the ``NOOP`` span: every
method a no-op, usable as a context manager, ``context()`` is None.

Span emission must never break the instrumented call: all sink I/O
errors are swallowed, exactly like events.emit.
"""
from __future__ import annotations

import json
import os
import random
import re
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Mapping, Optional

ENABLE_ENV = "STPU_TRACE"
SAMPLE_ENV = "STPU_TRACE_SAMPLE"
ENV_CTX = "STPU_TRACE_CTX"
HEADER = "X-STPU-Trace"

# Hot-path guard (see module docstring). Call sites read this module
# attribute before paying for anything else.
ENABLED = False

# Traces are per-request (not per-transition like events), so the cap
# is larger; one generation (.1) kept, same policy as events.jsonl.
_MAX_BYTES = 16 * 1024 * 1024

_lock = threading.Lock()
_rng = random.Random()
_sample_rate = 1.0

_CTX_RE = re.compile(r"^([0-9a-f]{32})-([0-9a-f]{16})-(0[01])$")


def trace_path() -> "os.PathLike[str]":
    from skypilot_tpu.utils import paths
    return paths.logs_dir() / "traces.jsonl"


# ------------------------------------------------------------- arming
def arm(sample: Optional[float] = None) -> None:
    """Turn tracing on (idempotent). ``sample`` overrides the
    STPU_TRACE_SAMPLE root-span sampling rate for this process."""
    global ENABLED, _sample_rate
    if sample is None:
        try:
            sample = float(os.environ.get(SAMPLE_ENV, "1"))
        except ValueError:
            sample = 1.0
    _sample_rate = min(max(float(sample), 0.0), 1.0)
    ENABLED = True


def disarm() -> None:
    global ENABLED
    ENABLED = False


# ------------------------------------------------------------ context
class SpanContext:
    """The propagatable identity of a span: what a child (possibly in
    another process/host) needs to attach itself to the trace."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)


def format_ctx(ctx: Optional[SpanContext]) -> Optional[str]:
    """Wire form: ``<trace_id>-<span_id>-<01|00>`` (01 = sampled)."""
    if ctx is None:
        return None
    return (f"{ctx.trace_id}-{ctx.span_id}-"
            f"{'01' if ctx.sampled else '00'}")


def parse_ctx(value: Optional[str]) -> Optional[SpanContext]:
    if not value:
        return None
    m = _CTX_RE.match(value.strip())
    if m is None:
        return None
    return SpanContext(m.group(1), m.group(2), m.group(3) == "01")


def extract(headers: Mapping[str, str]) -> Optional[SpanContext]:
    """Parse the ``X-STPU-Trace`` header out of an incoming request
    (http.server's case-insensitive message mapping works directly)."""
    try:
        return parse_ctx(headers.get(HEADER))
    except (AttributeError, TypeError):
        return None


def from_env() -> Optional[SpanContext]:
    """Parent context carried host-to-host through the environment
    (STPU_TRACE_CTX — the STPU_RUN_ID pattern)."""
    return parse_ctx(os.environ.get(ENV_CTX))


def set_env_context(ctx: Optional[SpanContext]) -> None:
    """Export ``ctx`` to this process's environment so every child
    process (launch subprocess, gang driver, job) inherits it."""
    if ctx is None:
        return
    os.environ[ENV_CTX] = format_ctx(ctx)


def env_context() -> Optional[str]:
    """The serialized context children should inherit, or None when
    tracing is off (a stale env var must not smuggle trace ids into an
    untraced launch)."""
    if not ENABLED:
        return None
    return os.environ.get(ENV_CTX) or None


def child_env() -> Dict[str, str]:
    """Env-var stamp for a subprocess/remote-host environment: the
    current context plus the arming flag, so job-side telemetry both
    CAN trace and knows WHERE to attach."""
    ctx = env_context()
    if not ctx:
        return {}
    return {ENV_CTX: ctx, ENABLE_ENV: "1"}


def adopt_ctx(serialized: Optional[str]) -> Optional[SpanContext]:
    """Child-process side of a spec-carried context (gang driver): a
    valid context both sets the env (for OUR children) and arms
    tracing — the submitting client only stamps a context when it is
    tracing, so the carrier doubles as the arming signal."""
    ctx = parse_ctx(serialized)
    if ctx is None:
        return None
    os.environ[ENV_CTX] = format_ctx(ctx)
    if not ENABLED:
        arm()
    return ctx


# --------------------------------------------------------------- spans
class Span:
    """One timed hop. Created by start_span(); emitted by end().

    Not thread-safe by design: a span belongs to the one logical
    operation it times (event/attr appends from its owning thread).
    """

    __slots__ = ("name", "kind", "trace_id", "span_id", "parent_id",
                 "ts", "_mono", "attrs", "events", "_ended")

    def __init__(self, name: str, kind: str, trace_id: str,
                 parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.ts = time.time()
        self._mono = time.perf_counter()
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.events: List[Dict[str, Any]] = []
        self._ended = False

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, True)

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def event(self, name: str, **fields: Any) -> None:
        """A timestamped annotation WITHIN the span (retry, breaker
        ejection, policy decision); ``at`` is the offset from span
        start in monotonic seconds."""
        rec = {"name": name,
               "at": round(time.perf_counter() - self._mono, 6)}
        rec.update(fields)
        self.events.append(rec)

    def end(self, status: str = "ok", **attrs: Any) -> None:
        """Close the span and write its record. Idempotent — the
        second end() is a no-op, so an error path and a finally block
        can both call it safely."""
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        _write(_record(self.name, self.kind, self.trace_id,
                       self.span_id, self.parent_id, self.ts,
                       time.perf_counter() - self._mono, status,
                       self.attrs, self.events))

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end(status="error" if exc_type is not None else "ok",
                 **({"error": f"{exc_type.__name__}: {exc}"}
                    if exc_type is not None else {}))


class _NoopSpan:
    """The zero-cost stand-in when tracing is disabled. Every method is
    a no-op; context() is None so children naturally no-op too."""

    __slots__ = ()

    def context(self) -> Optional[SpanContext]:
        return None

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def event(self, name: str, **fields: Any) -> None:
        pass

    def end(self, status: str = "ok", **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


class _UnsampledSpan(_NoopSpan):
    """Records nothing, but still CARRIES a context whose sampled flag
    is False: the root's not-sampled decision must propagate (header
    flag ``00``) or a downstream armed hop would open its own root and
    record a torn, rootless partial trace. Whole-or-absent means the
    negative decision travels too."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx: SpanContext):
        self._ctx = ctx

    def context(self) -> SpanContext:
        return self._ctx


NOOP = _NoopSpan()


def _parent_ids(parent):
    """(trace_id, parent_span_id, sampled) for a Span, a span-like
    (NOOP/unsampled), a SpanContext, or None parent."""
    if isinstance(parent, _NoopSpan):
        parent = parent.context()   # None for NOOP, ctx for unsampled
    if parent is None:
        return None, None, None
    if isinstance(parent, Span):
        return parent.trace_id, parent.span_id, True
    if isinstance(parent, SpanContext):
        return parent.trace_id, parent.span_id, parent.sampled
    return None, None, None


def start_span(name: str, kind: str = "span", parent=None,
               attrs: Optional[Dict[str, Any]] = None):
    """Open a span. ``parent`` is a Span, a SpanContext (extracted from
    a header / the env), or None for a root. Roots make the sampling
    decision; children inherit the parent's — a not-sampled root/parent
    yields an unsampled carrier span that records nothing but still
    propagates the decision, so traces are whole or absent, never torn.
    Returns NOOP when tracing is off. Callers never need to branch."""
    if not ENABLED:
        return NOOP
    trace_id, parent_id, sampled = _parent_ids(parent)
    if trace_id is None:
        if _sample_rate < 1.0 and _rng.random() >= _sample_rate:
            return _UnsampledSpan(SpanContext(
                uuid.uuid4().hex, uuid.uuid4().hex[:16], False))
        trace_id = uuid.uuid4().hex
    elif not sampled:
        return _UnsampledSpan(SpanContext(
            trace_id, uuid.uuid4().hex[:16], False))
    return Span(name, kind, trace_id, parent_id, attrs)


def record_span(name: str, kind: str, parent, start_mono: float,
                end_mono: Optional[float] = None, status: str = "ok",
                attrs: Optional[Dict[str, Any]] = None,
                events: Optional[List[Dict[str, Any]]] = None) -> None:
    """Emit a RETROACTIVE span from monotonic bounds — for phases whose
    boundaries are only known after the fact (engine queue wait:
    submit stamp → admission stamp) where holding an open Span object
    across scheduler iterations would be a leak hazard. The wall start
    is reconstructed from the current wall/monotonic pair, so the
    record aligns with live-span records on the timeline."""
    if not ENABLED:
        return
    trace_id, parent_id, sampled = _parent_ids(parent)
    if trace_id is None or not sampled:
        return
    now_wall = time.time()
    now_mono = time.perf_counter()
    if end_mono is None:
        end_mono = now_mono
    ts = now_wall - (now_mono - start_mono)
    _write(_record(name, kind, trace_id, uuid.uuid4().hex[:16],
                   parent_id, ts, end_mono - start_mono, status,
                   dict(attrs or {}), list(events or [])))


# ---------------------------------------------------------------- sink
def _record(name, kind, trace_id, span_id, parent_id, ts, dur, status,
            attrs, events) -> Dict[str, Any]:
    from skypilot_tpu.observability import events as events_lib
    return {
        "trace_id": trace_id, "span_id": span_id,
        "parent_id": parent_id, "name": name, "kind": kind,
        "ts": ts, "dur": max(dur, 0.0), "status": status,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
        "run_id": events_lib.run_id(),
        "attrs": attrs, "events": events,
    }


def _write(record: Dict[str, Any]) -> None:
    """Append one span record (shared rotate+append path with the
    event log: observability/jsonl_log.py). Never raises."""
    from skypilot_tpu.observability import jsonl_log
    try:
        line = json.dumps(record, default=str)
    except (TypeError, ValueError):
        return
    try:
        path = trace_path()
    except OSError:
        return
    jsonl_log.append_line(path, line, _MAX_BYTES, _lock)


# -------------------------------------------------------------- reading
def read(path: Optional[str] = None,
         trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """All span records (rotated generation included, oldest first);
    garbage lines skipped — a crash mid-append leaves at most one
    truncated line."""
    target = str(path or trace_path())
    out: List[Dict[str, Any]] = []
    for p in (target + ".1", target):
        try:
            with open(p, "r", errors="replace") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "span_id" not in rec:
                continue
            if trace_id is not None and rec.get("trace_id") != trace_id:
                continue
            out.append(rec)
    return out


def list_traces(limit: int = 20,
                path: Optional[str] = None) -> List[Dict[str, Any]]:
    """One summary row per trace, oldest first: root name, start,
    end-to-end duration (earliest start → latest end across spans),
    span count, worst status."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for rec in read(path=path):
        by_trace.setdefault(rec["trace_id"], []).append(rec)
    rows = []
    for tid, spans in by_trace.items():
        ids = {s["span_id"] for s in spans}
        roots = [s for s in spans
                 if not s.get("parent_id") or s["parent_id"] not in ids]
        root = min(roots or spans, key=lambda s: s.get("ts", 0))
        t0 = min(s.get("ts", 0) for s in spans)
        t1 = max(s.get("ts", 0) + s.get("dur", 0) for s in spans)
        rows.append({
            "trace_id": tid, "name": root.get("name", "?"),
            "kind": root.get("kind", "?"), "ts": t0,
            "dur": max(t1 - t0, 0.0), "spans": len(spans),
            "status": ("error" if any(s.get("status") == "error"
                                      for s in spans) else "ok"),
        })
    rows.sort(key=lambda r: r["ts"])
    return rows[-limit:] if limit else rows


def assemble(trace_id: str,
             path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Reassemble one trace into its span tree(s): a list of root
    nodes ``{"span": record, "children": [nodes...]}``, children
    sorted by start time. Spans whose parent record is missing (e.g.
    a host whose log was not collected) surface as extra roots rather
    than disappearing."""
    spans = read(path=path, trace_id=trace_id)
    nodes = {s["span_id"]: {"span": s, "children": []} for s in spans}
    roots = []
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in nodes:
            nodes[parent]["children"].append(nodes[s["span_id"]])
        else:
            roots.append(nodes[s["span_id"]])

    def sort_rec(node):
        node["children"].sort(key=lambda n: n["span"].get("ts", 0))
        for child in node["children"]:
            sort_rec(child)
    for root in roots:
        sort_rec(root)
    roots.sort(key=lambda n: n["span"].get("ts", 0))
    return roots


def critical_path(root: Dict[str, Any]) -> List[str]:
    """Span ids on the root's critical path: from each node, descend
    into the child whose END is latest (the child the parent was last
    waiting on). For the sequential pipelines stpu traces (queue →
    prefill → decode → stream) this is the chain that bounds
    end-to-end latency."""
    out = []
    node = root
    while node is not None:
        out.append(node["span"]["span_id"])
        children = node["children"]
        node = max(children, key=lambda n: (n["span"].get("ts", 0)
                                            + n["span"].get("dur", 0))
                   ) if children else None
    return out


# ------------------------------------------------------------- perfetto
def to_perfetto(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the Perfetto / chrome://tracing input
    format): one complete ("ph": "X") event per span with microsecond
    ts/dur and the originating pid/tid, one instant ("ph": "i") event
    per span annotation. Load via ui.perfetto.dev → Open trace file."""
    out: List[Dict[str, Any]] = []
    for rec in records:
        pid = int(rec.get("pid", 0))
        tid = int(rec.get("tid", 0))
        ts_us = float(rec.get("ts", 0)) * 1e6
        args = dict(rec.get("attrs") or {})
        args.update({"trace_id": rec.get("trace_id"),
                     "span_id": rec.get("span_id"),
                     "parent_id": rec.get("parent_id"),
                     "status": rec.get("status", "ok"),
                     "run_id": rec.get("run_id")})
        out.append({
            "name": rec.get("name", "?"),
            "cat": rec.get("kind", "span"),
            "ph": "X",
            "ts": ts_us,
            "dur": float(rec.get("dur", 0)) * 1e6,
            "pid": pid,
            "tid": tid,
            "args": args,
        })
        for ev in rec.get("events") or []:
            out.append({
                "name": f"{rec.get('name', '?')}.{ev.get('name', '?')}",
                "cat": rec.get("kind", "span"),
                "ph": "i",
                "s": "t",
                "ts": ts_us + float(ev.get("at", 0)) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": {k: v for k, v in ev.items()
                         if k not in ("name", "at")},
            })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# Arm from the environment at import: operators export STPU_TRACE=1 (or
# a launch carries it host-to-host via child_env) and every process in
# the stack picks it up.
if os.environ.get(ENABLE_ENV, "0") == "1":
    arm()
