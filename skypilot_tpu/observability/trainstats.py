"""Per-train-step goodput telemetry + gang straggler detection.

The serving path has four observability layers (metrics, tracing, the
stepstats flight recorder, the fleet/SLO store); training — the half
of the north star whose headline number is MFU — had none. This module
is the training twin of :mod:`stepstats`: a fixed-size ring of
per-train-step records plus derived gauges (live MFU, a goodput
breakdown), per-host JSONL sinks for multi-host gangs, host-0
straggler aggregation, and the same crash flight recorder.

Three layers:

* **Step ring** — one record per optimizer step, recorded from the
  recipe train loop:

      {"seq": N, "step": S, "ts": <wall s>, "mono": <perf_counter s>,
       "dur": <step seconds, exclusive of the stalls below>,
       "tokens": T,
       "data_wait_s": <input-pipeline wait>, "ckpt_s": <ckpt stall>,
       "dispatch_s": <host dispatch seconds>|None,
       "device_s": <sampled device-wait seconds>|None,
       "loss": L|None, "grad_norm": G|None}

  ``loss``/``grad_norm`` arrive ONE STEP LATE: the loop hands the
  previous step's device handle to ``jax.device_get`` only after the
  next step has been dispatched (``trainer.DelayedFetch``), so logging
  never syncs the hot loop. ``record_step(delayed=...)`` attaches the
  fetched values to the *previous* ring record.

* **Derived gauges** — live MFU from the model's ``flops_per_token()``
  against the ring's token rate and the configured peak FLOP/s, and a
  goodput breakdown: productive / data-wait / ckpt-stall /
  restart-downtime fractions of the observed window. ``snapshot()``
  renders one JSON document; armed multi-host runs also append every
  record to ``<out_dir>/host-{rank}.jsonl`` and host 0 writes an
  aggregate ``snapshot.json`` the jobs controller scrapes each watch
  tick into its ``TimeSeriesStore``.

* **Straggler detection** — host 0 tails the peer JSONL files: a host
  whose newest step completion lags the gang median by more than
  ``STPU_TRAIN_STRAGGLER_SECONDS`` raises an edge-triggered
  ``train_straggler`` event and sets ``stpu_train_host_skew_seconds``.

Flight recorder: ``dump_flight(reason, error=...)`` writes this
process's ring atomically (stepstats naming + retention);
``dump_dir_flight`` synthesizes a gang-wide dump from the host JSONL
tails — the jobs controller calls it on preemption/recovery so
post-mortems show the last N steps of every host even though the
training processes are already dead.

Overhead discipline (mirror of stepstats): OFF by default; hot call
sites guard with ``if trainstats.ENABLED:`` so the disarmed cost is
one global load and a falsy branch (pinned by the monkeypatch-bomb
test). Arm with ``STPU_TRAINSTATS=1`` (ring ``STPU_TRAINSTATS_RING``)
or ``arm()`` in tests. The sampled dispatch-vs-device split reuses the
stepstats contract: :func:`sampled_sync` is the ONLY sanctioned sync
in the train hot loops (``stpu-host-sync`` blesses exactly it and the
delayed ``jax.device_get``).

Stdlib-only on the hot path: no jax import (``sampled_sync``
duck-types; ``detect_peak_flops`` imports jax lazily at configure
time). Recording must never break training: all sink I/O errors are
swallowed, exactly like events/tracing.
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.observability import metrics

ENABLE_ENV = "STPU_TRAINSTATS"
RING_ENV = "STPU_TRAINSTATS_RING"
SYNC_ENV = "STPU_TRAINSTATS_SYNC_EVERY"
DIR_ENV = "STPU_TRAINSTATS_DIR"
STRAGGLER_ENV = "STPU_TRAIN_STRAGGLER_SECONDS"

DEFAULT_RING = 512
DEFAULT_STRAGGLER_S = 2.0
KEEP_DUMPS = 32
# Host-0 aggregate snapshot.json cadence (steps) and the minimum gap
# between straggler scans — both bound the armed steady-state I/O.
SNAPSHOT_EVERY = 5
STRAGGLER_SCAN_MIN_S = 0.5

# Hot-path guard (module docstring): call sites read this module
# attribute before paying for anything else.
ENABLED = False

# Peak dense FLOP/s per chip (bf16), by TPU generation. Lives here —
# not in bench.py — because live MFU is a first-class gauge now;
# bench.py imports :func:`peak_flops_for_device` for its report.
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

# ------------------------------------------------------------- metrics
_STEP_SECONDS = metrics.histogram(
    "stpu_train_step_seconds",
    "Optimizer step duration (dispatch + sampled device wait; input "
    "wait and ckpt stalls are recorded separately). Recorded only "
    "while STPU_TRAINSTATS=1.",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 15.0, 60.0))
_MFU = metrics.gauge(
    "stpu_train_mfu",
    "Live model FLOPs utilization over the step-ring window: "
    "tokens/s x flops_per_token / configured peak FLOP/s.")
_TOK_S = metrics.gauge(
    "stpu_train_tokens_per_sec",
    "Training token throughput over the step-ring window.")
_GOODPUT = metrics.gauge(
    "stpu_train_goodput_fraction",
    "Goodput breakdown over the step-ring window + recorded restart "
    "downtime: productive / data_wait / ckpt / restart fractions.",
    ("component",))
_HOST_SKEW = metrics.gauge(
    "stpu_train_host_skew_seconds",
    "Worst host step-completion lag behind the gang median (host-0 "
    "aggregation over the per-host JSONL sinks).")
_DISPATCH_SECONDS = metrics.histogram(
    "stpu_train_step_dispatch_seconds",
    "Host time to dispatch one train step (jitted call returning, "
    "device still executing).",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.5, 2.0))
_DEVICE_SECONDS = metrics.histogram(
    "stpu_train_step_device_seconds",
    "Sampled device-execution wait per train step (timed "
    "block_until_ready every STPU_TRAINSTATS_SYNC_EVERY steps).",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 10.0))
_DUMPS = metrics.counter(
    "stpu_train_flightrec_dumps_total",
    "Training flight-recorder dumps written, by trigger.", ("reason",))


def peak_flops_for_device(device: Any) -> float:
    """Per-chip peak dense FLOP/s for a jax device (0.0 = unknown,
    e.g. CPU). Matches on ``device_kind`` substrings; 'v5 lite' is
    v5e, bare 'v5' defaults to v5p."""
    kind = str(getattr(device, "device_kind", device) or "").lower()
    for name, flops in PEAK_FLOPS.items():
        if name in kind:
            return flops
    if "v5 lite" in kind or "v5lite" in kind:
        return PEAK_FLOPS["v5e"]
    if "v5" in kind:
        return PEAK_FLOPS["v5p"]
    return 0.0


def detect_peak_flops() -> float:
    """This process's aggregate peak FLOP/s: per-chip peak x local
    device count. Lazy jax import (configure time, not hot path);
    0.0 when the platform is unknown (CPU smoke runs → MFU=None)."""
    try:
        import jax
        devs = jax.local_devices()
    except Exception:
        return 0.0
    if not devs:
        return 0.0
    return peak_flops_for_device(devs[0]) * len(devs)


class _Ring:
    """Fixed-size step ring with running aggregates so the per-record
    cost is O(1): evicted records subtract their contribution, the
    gauges re-render from the sums."""

    def __init__(self, size: int):
        self.size = max(int(size), 1)
        self.buf: List[Optional[Dict[str, Any]]] = [None] * self.size
        self.idx = 0
        self.count = 0
        self.seq = 0
        self.dur_sum = 0.0
        self.tok_sum = 0
        self.data_wait_sum = 0.0
        self.ckpt_sum = 0.0
        self.dispatch_sum = 0.0
        self.dispatch_n = 0
        self.device_sum = 0.0
        self.device_n = 0

    def _account(self, rec: Dict[str, Any], sign: int) -> None:
        self.dur_sum += sign * rec["dur"]
        self.tok_sum += sign * rec["tokens"]
        self.data_wait_sum += sign * rec["data_wait_s"]
        self.ckpt_sum += sign * rec["ckpt_s"]
        if rec.get("dispatch_s") is not None:
            self.dispatch_sum += sign * rec["dispatch_s"]
            self.dispatch_n += sign
        if rec.get("device_s") is not None:
            self.device_sum += sign * rec["device_s"]
            self.device_n += sign

    def append(self, rec: Dict[str, Any]) -> None:
        evicted = self.buf[self.idx]
        if evicted is not None:
            self._account(evicted, -1)
        self.buf[self.idx] = rec
        self.idx = (self.idx + 1) % self.size
        self.count = min(self.count + 1, self.size)
        self.seq += 1
        self._account(rec, +1)

    def newest(self) -> Optional[Dict[str, Any]]:
        if self.count == 0:
            return None
        return self.buf[(self.idx - 1) % self.size]

    def ordered(self) -> List[Dict[str, Any]]:
        """Oldest → newest."""
        if self.count < self.size:
            return [r for r in self.buf[:self.count] if r is not None]
        return [r for r in (self.buf[self.idx:] + self.buf[:self.idx])
                if r is not None]

    def window_s(self) -> float:
        """Wall window covered by the ring, monotonic-clock based:
        oldest record's start → newest record's end."""
        if self.count == 0:
            return 0.0
        oldest = (self.buf[self.idx] if self.count == self.size
                  else self.buf[0])
        newest = self.buf[(self.idx - 1) % self.size]
        return max(newest["mono"] - (oldest["mono"] - oldest["dur"]),
                   1e-9)


_lock = threading.Lock()
_ring = _Ring(DEFAULT_RING)
_sync_every = 0
_sync_count = 0
_dump_seq = 0

# Run context set by configure(): identity in a gang, the MFU inputs,
# and the shared output directory (``$STPU_JOB_CKPT_DIR/trainstats``
# under a managed job, so controller + all hosts agree on it).
_host = 0
_hosts = 1
_job: Optional[str] = None
_flops_per_token: Optional[float] = None
_peak_flops: float = 0.0
_out_dir: Optional[str] = None
_straggler_s = DEFAULT_STRAGGLER_S
_downtime_s = 0.0
_straggling: set = set()
_last_scan_mono = 0.0
_host_skew_s = 0.0


# -------------------------------------------------------------- arming
def arm(ring: Optional[int] = None,
        sync_every: Optional[int] = None) -> None:
    """Turn train-step recording on (idempotent). ``ring`` overrides
    STPU_TRAINSTATS_RING, ``sync_every`` overrides
    STPU_TRAINSTATS_SYNC_EVERY for this process."""
    global ENABLED, _ring, _sync_every
    with _lock:
        if ring is None:
            try:
                ring = int(os.environ.get(RING_ENV, "512"))
            except ValueError:
                ring = DEFAULT_RING
        if sync_every is None:
            try:
                sync_every = int(os.environ.get(SYNC_ENV, "0"))
            except ValueError:
                sync_every = 0
        if _ring.size != int(ring):
            _ring = _Ring(int(ring))
        _sync_every = max(int(sync_every), 0)
        ENABLED = True


def disarm() -> None:
    global ENABLED
    ENABLED = False


def reset() -> None:
    """Drop all recorded state and run context (tests)."""
    global _ring, _sync_count, _host, _hosts, _job
    global _flops_per_token, _peak_flops, _out_dir, _straggler_s
    global _downtime_s, _straggling, _last_scan_mono, _host_skew_s
    with _lock:
        _ring = _Ring(_ring.size)
        _sync_count = 0
        _host = 0
        _hosts = 1
        _job = None
        _flops_per_token = None
        _peak_flops = 0.0
        _out_dir = None
        _straggler_s = DEFAULT_STRAGGLER_S
        _downtime_s = 0.0
        _straggling = set()
        _last_scan_mono = 0.0
        _host_skew_s = 0.0


def configure(*, flops_per_token: Optional[float] = None,
              peak_flops: Optional[float] = None,
              host: int = 0, hosts: int = 1,
              out_dir: Optional[str] = None,
              job: Optional[str] = None,
              straggler_s: Optional[float] = None) -> None:
    """Set the run context: MFU inputs (model flops/token + this
    process's peak FLOP/s), gang identity, and the shared output
    directory for the per-host JSONL sinks. Recipes call it once
    after building the model, guarded on ``ENABLED``.

    ``out_dir`` default: ``STPU_TRAINSTATS_DIR``, else
    ``$STPU_JOB_CKPT_DIR/trainstats`` under a managed job (the one
    directory the gang driver and the controller both know), else no
    sink (ring-only, single-process mode)."""
    global _flops_per_token, _peak_flops, _host, _hosts, _out_dir
    global _job, _straggler_s
    with _lock:
        if flops_per_token is not None:
            _flops_per_token = float(flops_per_token)
        if peak_flops is not None:
            _peak_flops = float(peak_flops)
        _host = int(host)
        _hosts = max(int(hosts), 1)
        if job is not None:
            _job = str(job)
        if straggler_s is None:
            try:
                straggler_s = float(
                    os.environ.get(STRAGGLER_ENV, "2.0"))
            except ValueError:
                straggler_s = DEFAULT_STRAGGLER_S
        _straggler_s = max(float(straggler_s), 0.0)
        if out_dir is None:
            out_dir = os.environ.get(DIR_ENV)
        if out_dir is None:
            ckpt_dir = os.environ.get("STPU_JOB_CKPT_DIR")
            if ckpt_dir:
                out_dir = os.path.join(ckpt_dir, "trainstats")
        if out_dir:
            _out_dir = str(out_dir)
            try:
                os.makedirs(_out_dir, exist_ok=True)
            except OSError:
                _out_dir = None


def note_downtime(seconds: float) -> None:
    """Account restart/startup downtime against goodput — recipes call
    it after a checkpoint restore with the wall seconds the process
    spent getting back to the training loop."""
    global _downtime_s
    with _lock:
        _downtime_s += max(float(seconds), 0.0)


# ----------------------------------------------------------- recording
def record_step(*, step: int, dur: float, tokens: int,
                data_wait_s: float = 0.0, ckpt_s: float = 0.0,
                dispatch_s: Optional[float] = None,
                device_s: Optional[float] = None,
                delayed: Optional[Dict[str, Any]] = None) -> None:
    """Append one train-step record and refresh the derived gauges.
    Callers guard on ``ENABLED``.

    ``delayed`` carries the PREVIOUS step's host-fetched values
    (``{"loss": ..., "grad_norm": ...}`` from the DelayedFetch
    rotation) — they attach to the previous ring record, keeping the
    record's timing fields and its loss about the same step."""
    rec = {
        "ts": time.time(),
        "mono": time.perf_counter(),
        "step": int(step),
        "dur": float(dur),
        "tokens": int(tokens),
        "data_wait_s": float(data_wait_s),
        "ckpt_s": float(ckpt_s),
        "dispatch_s": dispatch_s,
        "device_s": device_s,
        "loss": None,
        "grad_norm": None,
    }
    with _lock:
        if delayed:
            prev = _ring.newest()
            if prev is not None:
                for key in ("loss", "grad_norm"):
                    if delayed.get(key) is not None:
                        prev[key] = float(delayed[key])
        rec["seq"] = _ring.seq
        _ring.append(rec)
        window = _ring.window_s()
        tok_s = _ring.tok_sum / window if window else 0.0
        mfu = None
        if _flops_per_token and _peak_flops > 0:
            mfu = tok_s * _flops_per_token / _peak_flops
        denom = window + _downtime_s
        goodput = _goodput_locked(window, denom)
        write_snapshot = (_out_dir is not None
                          and _ring.seq % SNAPSHOT_EVERY == 0)
    _STEP_SECONDS.observe(rec["dur"])
    _TOK_S.set(tok_s)
    if mfu is not None:
        _MFU.set(mfu)
    for component, frac in goodput.items():
        _GOODPUT.labels(component=component).set(frac)
    if dispatch_s is not None:
        _DISPATCH_SECONDS.observe(dispatch_s)
    if device_s is not None:
        _DEVICE_SECONDS.observe(device_s)
    _append_jsonl(rec)
    if write_snapshot and _host == 0:
        _write_snapshot()
        check_stragglers()


def _goodput_locked(window: float, denom: float) -> Dict[str, float]:
    """Goodput fractions over window + downtime. Caller holds _lock.
    ``dur`` is pure step work (the loops time it EXCLUSIVE of the
    input wait and the checkpoint stall), so the components partition
    the window without double-counting; the remainder is untracked
    loop overhead."""
    if denom <= 0:
        return {"productive": 0.0, "data_wait": 0.0, "ckpt": 0.0,
                "restart": 0.0}
    productive = max(_ring.dur_sum, 0.0)
    return {
        "productive": round(min(productive / denom, 1.0), 4),
        "data_wait": round(min(_ring.data_wait_sum / denom, 1.0), 4),
        "ckpt": round(min(_ring.ckpt_sum / denom, 1.0), 4),
        "restart": round(min(_downtime_s / denom, 1.0), 4),
    }


def _host_jsonl(host: Optional[int] = None) -> Optional[str]:
    if _out_dir is None:
        return None
    return os.path.join(_out_dir,
                        f"host-{_host if host is None else host}.jsonl")


def _append_jsonl(rec: Dict[str, Any]) -> None:
    """Append one step record to this host's JSONL sink. The line is
    written at step boundary WITHOUT the delayed loss (timing is what
    straggler detection and crash forensics need; the loss lands in
    the next snapshot). Best-effort: OSError swallowed."""
    path = _host_jsonl()
    if path is None:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(
                {k: rec[k] for k in ("seq", "step", "ts", "mono",
                                     "dur", "tokens", "data_wait_s",
                                     "ckpt_s")}) + "\n")
    except OSError:
        pass


def _write_snapshot() -> None:
    """Atomically write host 0's aggregate ``snapshot.json`` next to
    the JSONL sinks — the document the jobs controller scrapes."""
    if _out_dir is None:
        return
    path = os.path.join(_out_dir, "snapshot.json")
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snapshot(), f, default=str)
        os.replace(tmp, path)
    except OSError:
        pass


def flush() -> None:
    """Force-write the aggregate snapshot (end of run / tests)."""
    if _host == 0:
        _write_snapshot()


# ------------------------------------------------------- straggler scan
def _tail_record(path: str) -> Optional[Dict[str, Any]]:
    """Newest JSONL record of one host sink: seek to the last ~4KB and
    parse the final complete line. Best-effort."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(size - 4096, 0))
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(chunk.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "ts" in rec:
            return rec
    return None


def check_stragglers(now: Optional[float] = None) -> Dict[int, float]:
    """Host-0 aggregation: tail every ``host-*.jsonl``, compare each
    host's newest step-completion wall time against the gang median,
    and flag hosts lagging by more than the straggler threshold —
    edge-triggered ``train_straggler`` event + the worst lag on
    ``stpu_train_host_skew_seconds``. Returns {host: lag_s} for hosts
    currently over threshold. Rate-limited to one scan per
    ``STRAGGLER_SCAN_MIN_S`` when called from the hot recorder."""
    global _last_scan_mono, _host_skew_s, _straggling
    with _lock:
        out_dir = _out_dir
        threshold = _straggler_s
        hosts = _hosts
        job = _job
        mono = time.perf_counter()
        if now is None and mono - _last_scan_mono < STRAGGLER_SCAN_MIN_S:
            return {}
        _last_scan_mono = mono
    if out_dir is None or hosts < 2 or threshold <= 0:
        return {}
    latest: Dict[int, Dict[str, Any]] = {}
    try:
        names = os.listdir(out_dir)
    except OSError:
        return {}
    for name in names:
        if not (name.startswith("host-") and name.endswith(".jsonl")):
            continue
        try:
            rank = int(name[len("host-"):-len(".jsonl")])
        except ValueError:
            continue
        rec = _tail_record(os.path.join(out_dir, name))
        if rec is not None:
            latest[rank] = rec
    if len(latest) < 2:
        return {}
    median_ts = statistics.median(r["ts"] for r in latest.values())
    lagging: Dict[int, float] = {}
    worst = 0.0
    for rank, rec in latest.items():
        lag = median_ts - float(rec["ts"])
        worst = max(worst, lag)
        if lag > threshold:
            lagging[rank] = round(lag, 3)
    with _lock:
        _host_skew_s = max(worst, 0.0)
        fresh = set(lagging) - _straggling
        _straggling = set(lagging)
    _HOST_SKEW.set(max(worst, 0.0))
    if fresh:
        from skypilot_tpu.observability import events
        for rank in sorted(fresh):
            events.emit("train", job or "train", "train_straggler",
                        host=rank, lag_s=lagging[rank],
                        step=latest[rank].get("step"))
    return lagging


# -------------------------------------------------------- sampled sync
def sync_due() -> bool:
    """True on every STPU_TRAINSTATS_SYNC_EVERY-th call (0 = never).
    The train loop asks once per step; the module owns the counter so
    restarted loops keep the cadence."""
    global _sync_count
    if _sync_every <= 0:
        return False
    _sync_count += 1
    if _sync_count >= _sync_every:
        _sync_count = 0
        return True
    return False


def sampled_sync(value: Any) -> float:
    """THE sanctioned device sync of the train hot loop: one timed
    ``block_until_ready`` on a step's output, returning the wait in
    seconds. The ``stpu-host-sync`` analyzer blesses exactly this
    helper (and the one-step-delayed ``jax.device_get``) — every other
    sync in the train loops is a finding."""
    t0 = time.perf_counter()
    try:
        value.block_until_ready()
    except AttributeError:  # non-array (tests, exotic backends)
        pass
    return time.perf_counter() - t0


# ------------------------------------------------------------ snapshot
def snapshot() -> Dict[str, Any]:
    """One JSON-ready document over the current ring: step/token
    rates, live MFU, the goodput breakdown, gang skew. Written as
    ``snapshot.json`` for the jobs controller and embedded in flight
    dumps."""
    with _lock:
        window = _ring.window_s()
        steps = _ring.count
        last = _ring.newest()
        tok_s = _ring.tok_sum / window if window else 0.0
        mfu = None
        if _flops_per_token and _peak_flops > 0:
            mfu = round(tok_s * _flops_per_token / _peak_flops, 4)
        denom = window + _downtime_s
        doc: Dict[str, Any] = {
            "armed": ENABLED,
            "ring_size": _ring.size,
            "steps": steps,
            "total_steps": _ring.seq,
            "window_s": round(window, 6),
            "step_seconds_mean": round(_ring.dur_sum / steps, 6)
            if steps else 0.0,
            "steps_per_sec": round(steps / window, 3) if window
            else 0.0,
            "tokens_per_sec": round(tok_s, 1),
            "mfu": mfu,
            "goodput": _goodput_locked(window, denom),
            "downtime_s": round(_downtime_s, 3),
            "host": _host,
            "hosts": _hosts,
            "job": _job,
            "host_skew_s": round(_host_skew_s, 3),
            "stragglers": sorted(_straggling),
        }
        if last is not None:
            # The delayed fetch attaches loss/grad_norm one step late,
            # so the NEWEST record never has them yet — surface the
            # newest record that does (normally the one before last).
            lossy = next((r for r in reversed(_ring.ordered())
                          if r["loss"] is not None
                          or r["grad_norm"] is not None), None)
            doc["last"] = {
                "step": last["step"],
                "loss": lossy["loss"] if lossy else None,
                "grad_norm": lossy["grad_norm"] if lossy else None,
            }
            if lossy is not None:
                doc["last"]["loss_step"] = lossy["step"]
        if _ring.dispatch_n:
            doc["dispatch_ms_mean"] = round(
                _ring.dispatch_sum / _ring.dispatch_n * 1e3, 3)
        if _ring.device_n:
            doc["sync"] = {
                "samples": _ring.device_n,
                "device_ms_mean": round(
                    _ring.device_sum / _ring.device_n * 1e3, 3),
                "every": _sync_every,
            }
        return doc


def steps_tail(n: int = 0) -> List[Dict[str, Any]]:
    """The last ``n`` step records, oldest first (0 = whole ring)."""
    with _lock:
        recs = _ring.ordered()
    return recs[-n:] if n else recs


# ------------------------------------------------------ flight recorder
def flightrec_dir(dir_path: Optional[str] = None) -> str:
    """Dump directory: inside the configured out_dir when the run has
    one (so a managed job's dumps survive under its ckpt dir for the
    controller and CLI), else ``~/.stpu/logs/flightrec_train/``."""
    if dir_path is None:
        dir_path = (os.path.join(_out_dir, "flightrec") if _out_dir
                    else None)
    if dir_path is None:
        from skypilot_tpu.utils import paths
        dir_path = str(paths.logs_dir() / "flightrec_train")
    os.makedirs(dir_path, exist_ok=True)
    return str(dir_path)


def _dump_doc(doc: Dict[str, Any], reason: str,
              dir_path: Optional[str]) -> Optional[str]:
    global _dump_seq
    with _lock:
        _dump_seq += 1
        seq = _dump_seq
    now = doc["ts"]
    stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
    micros = int(now % 1.0 * 1e6)
    name = (f"{stamp}.{micros:06d}-{reason}-{os.getpid()}"
            f"-{seq:06d}.json")
    try:
        root = flightrec_dir(dir_path)
        path = os.path.join(root, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
    except OSError:
        return None
    _DUMPS.labels(reason=reason).inc()
    _prune_dumps(dir_path=root)
    return path


def dump_flight(reason: str, error: Optional[str] = None,
                extra: Optional[Dict[str, Any]] = None
                ) -> Optional[str]:
    """Write this process's ring + aggregate snapshot + terminal
    exception atomically (temp + ``os.replace``). The recipe crash
    paths and SIGTERM handlers call it; returns the path, or None on
    any I/O failure — a post-mortem artifact must never crash the
    crash path it documents."""
    from skypilot_tpu.observability import events
    doc = {
        "version": 1,
        "reason": reason,
        "ts": time.time(),
        "run_id": events.run_id(),
        "pid": os.getpid(),
        "host": _host,
        "error": error,
        "snapshot": snapshot(),
        "steps": steps_tail(),
    }
    if extra:
        doc.update(extra)
    return _dump_doc(doc, reason, None)


def dump_dir_flight(reason: str, dir_path: str,
                    tail: int = 64) -> Optional[str]:
    """Synthesize a gang-wide flight dump from a trainstats directory
    (``host-*.jsonl`` tails + the last ``snapshot.json``) — the jobs
    controller's post-mortem path when a task is preempted/killed and
    the training processes can no longer dump themselves. Written to
    ``<dir_path>/flightrec/``."""
    hosts: Dict[str, List[Dict[str, Any]]] = {}
    try:
        names = os.listdir(dir_path)
    except OSError:
        return None
    for name in sorted(names):
        if not (name.startswith("host-") and name.endswith(".jsonl")):
            continue
        rank = name[len("host-"):-len(".jsonl")]
        recs: List[Dict[str, Any]] = []
        try:
            with open(os.path.join(dir_path, name)) as f:
                for line in f:
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
        hosts[rank] = recs[-tail:] if tail else recs
    snap = None
    try:
        with open(os.path.join(dir_path, "snapshot.json")) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        pass
    if not hosts and snap is None:
        return None
    doc = {
        "version": 1,
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "synthesized": True,
        "snapshot": snap,
        "hosts": hosts,
    }
    return _dump_doc(doc, reason,
                     os.path.join(dir_path, "flightrec"))


def _prune_dumps(keep: Optional[int] = None,
                 dir_path: Optional[str] = None) -> None:
    """Drop the oldest dumps past the retention cap (stamped names
    sort chronologically). Best-effort, like every sink here."""
    if keep is None:
        keep = KEEP_DUMPS
    if keep <= 0:
        return
    try:
        root = flightrec_dir(dir_path)
        names = sorted(n for n in os.listdir(root)
                       if n.endswith(".json"))
        for name in names[:-keep]:
            os.unlink(os.path.join(root, name))
    except OSError:
        pass


def list_dumps(dir_path: Optional[str] = None) -> List[str]:
    """Recorded training flight dumps, oldest first (file names)."""
    try:
        names = sorted(os.listdir(flightrec_dir(dir_path)))
    except OSError:
        return []
    return [n for n in names if n.endswith(".json")]


def read_dump(name: Optional[str] = None,
              dir_path: Optional[str] = None) -> Dict[str, Any]:
    """Load one dump by file name, path, or unique prefix; ``None`` =
    the newest. Raises FileNotFoundError/ValueError on no/ambiguous
    match (the CLI turns these into clean errors)."""
    if name and os.path.sep in str(name) and os.path.exists(name):
        path = str(name)
    else:
        dumps = list_dumps(dir_path)
        if not dumps:
            raise FileNotFoundError(
                "no training flight dumps recorded (arm "
                f"{ENABLE_ENV}=1 and crash/restart a train loop)")
        if name is None:
            target = dumps[-1]
        else:
            matches = [d for d in dumps if d.startswith(str(name))]
            if not matches:
                raise FileNotFoundError(f"no dump matches {name!r}")
            if len(matches) > 1:
                raise ValueError(
                    f"{name!r} is ambiguous ({len(matches)} dumps)")
            target = matches[0]
        path = os.path.join(flightrec_dir(dir_path), target)
    with open(path) as f:
        doc = json.load(f)
    doc.setdefault("path", path)
    return doc


# Arm from the environment at import: operators export
# STPU_TRAINSTATS=1 and every host in the gang picks it up.
if os.environ.get(ENABLE_ENV, "0") == "1":
    arm()
