"""Thread-safe metrics registry with Prometheus text exposition.

Reference analog: prometheus_client's Counter/Gauge/Histogram — but
stdlib-only (the container bakes no client library) and deliberately
small: label children are plain dicts keyed by the label-value tuple,
every update takes only that child's lock for the duration of the
arithmetic, and exposition renders the whole registry under the
registry lock. No background threads, no process collectors.

Usage:

    from skypilot_tpu.observability import metrics
    REQS = metrics.counter("stpu_lb_requests_total",
                           "Proxied requests.", ("method", "code"))
    REQS.labels(method="GET", code="200").inc()
    text = metrics.render()          # Prometheus text format 0.0.4

Families are created once per (registry, name): calling a factory again
with the same name returns the existing family, so module-level
declarations stay idempotent across re-imports and tests.
"""
from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Latency-in-seconds oriented defaults: sub-5ms local proxying through
# multi-minute cold model compiles (serve upstream timeout is 120s+).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

# Tail-latency-SLO bucket set for TTFT-class histograms. DEFAULT_BUCKETS
# jumps 1.0 -> 2.5 -> 5 -> 10 -> 30: a p99 TTFT anywhere past ~1s lands
# in a bucket 2.5-20s wide and interpolated quantiles are mush — useless
# for a "p99 TTFT < 2s" SLO verdict. This set keeps ~1.5x spacing
# through the 0.1s-20s band where serving TTFT tails actually live,
# while still covering cold-compile outliers at the top.
LATENCY_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.15, 0.25, 0.4, 0.6, 0.9,
                   1.3, 2.0, 3.0, 4.5, 6.5, 10.0, 15.0, 22.5, 35.0,
                   50.0, 75.0, 120.0, 300.0)


def quantile_from_cumulative(bounds: Sequence[float],
                             cumulative: Sequence[float],
                             q: float) -> float:
    """Quantile ``q`` in [0, 1] from cumulative bucket counts, linearly
    interpolated within the winning bucket (PromQL histogram_quantile
    semantics): the first bucket interpolates from 0, and a quantile
    landing in the +Inf bucket returns the highest finite bound — the
    histogram cannot resolve beyond it. ``cumulative`` has one more
    entry than ``bounds`` (the +Inf bucket). NaN when empty.

    Shared by Histogram.quantile (live registry) and
    promtext.HistogramSnapshot.quantile (scraped exposition), so the
    two can never diverge on what a percentile means."""
    if not cumulative:
        return math.nan
    total = cumulative[-1]
    if total <= 0:
        return math.nan
    q = min(max(float(q), 0.0), 1.0)
    rank = q * total
    for i, bound in enumerate(bounds):
        if cumulative[i] >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            below = cumulative[i - 1] if i > 0 else 0.0
            in_bucket = cumulative[i] - below
            if in_bucket <= 0:
                return bound
            return lo + (bound - lo) * (rank - below) / in_bucket
    return bounds[-1] if bounds else math.nan


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Child:
    """One (label-values) series of a Counter/Gauge."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def get(self) -> float:
        with self._lock:
            return self.value


class _HistogramChild:
    """One (label-values) series of a Histogram.

    Bucket counts are stored NON-cumulative (observe = one bisect + one
    increment under the child lock); cumulation happens at render time,
    off the hot path.
    """

    __slots__ = ("_lock", "_bounds", "_counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # +1 for +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self.sum += value
            self.count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count)."""
        with self._lock:
            counts = list(self._counts)
            total, cumulative = 0, []
            for c in counts:
                total += c
                cumulative.append(total)
            return cumulative, self.sum, self.count


class _MetricFamily:
    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # Label-less family IS its single child.
            self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *values, **kwvalues):
        if kwvalues:
            if values:
                raise ValueError("pass label values either positionally "
                                 "or by keyword, not both")
            try:
                values = tuple(kwvalues[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(expects {self.labelnames})") from e
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(values)} label values, "
                f"expects {len(self.labelnames)} {self.labelnames}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values,
                                                  self._new_child())
        return child

    def _samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._children.items())
        for values, child in items:
            yield (f"{self.name}"
                   f"{_format_labels(self.labelnames, values)} "
                   f"{_format_value(child.get())}")

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        lines.extend(self._samples())
        return "\n".join(lines)


class Counter(_MetricFamily):
    kind = "counter"

    def _new_child(self) -> _Child:
        return _Child()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def get(self) -> float:
        return self.labels().get()


class Gauge(_MetricFamily):
    kind = "gauge"

    def _new_child(self) -> _Child:
        return _Child()

    def set(self, value: float) -> None:
        self.labels().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def get(self) -> float:
        return self.labels().get()


class Histogram(_MetricFamily):
    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        super().__init__(name, help_text, labelnames)

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def quantile(self, q: float, **labelkw) -> float:
        """Interpolated quantile of one series' observations so far
        (label-less family by default). NaN while empty. SLO-grade
        accuracy depends on the bucket layout — see LATENCY_BUCKETS."""
        cumulative, _, _ = self.labels(**labelkw).snapshot()
        return quantile_from_cumulative(self.buckets, cumulative, q)

    def _samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._children.items())
        for values, child in items:
            cumulative, total, count = child.snapshot()
            bounds = list(self.buckets) + [math.inf]
            for bound, cum in zip(bounds, cumulative):
                names = self.labelnames + ("le",)
                vals = values + (_format_value(bound),)
                yield (f"{self.name}_bucket"
                       f"{_format_labels(names, vals)} {cum}")
            labels = _format_labels(self.labelnames, values)
            yield f"{self.name}_sum{labels} {_format_value(total)}"
            yield f"{self.name}_count{labels} {count}"


class Registry:
    """Named metric families; renders them in one exposition document."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _MetricFamily] = {}

    def _get_or_create(self, cls, name: str, help_text: str,
                       labelnames: Sequence[str], **kwargs):
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or \
                        existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type or label set")
                return existing
            family = cls(name, help_text, labelnames, **kwargs)
            self._families[name] = family
            return family

    def counter(self, name: str, help_text: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name: str, help_text: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_text,
                                   labelnames, buckets=buckets)

    def render(self) -> str:
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        out = [f.render() for f in families]
        return "\n".join(out) + "\n" if out else ""


# Default process-wide registry: module-level instrumentation in the
# LB/controller/daemon all lands here, so one render() is the whole
# process's exposition.
REGISTRY = Registry()


def counter(name: str, help_text: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help_text, labelnames)


def gauge(name: str, help_text: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help_text, labelnames)


def histogram(name: str, help_text: str = "",
              labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help_text, labelnames,
                              buckets=buckets)


def render(registry: Optional[Registry] = None) -> str:
    return (registry or REGISTRY).render()


def merge_text(primary: str, extra: str) -> str:
    """Concatenate two exposition documents, dropping ``extra``'s
    families whose name already appears in ``primary`` — duplicate
    HELP/TYPE blocks make the whole scrape invalid to Prometheus.
    Needed because two processes can both import a module that
    registers a family (e.g. the controller imports the LB module for
    RequestRecorder): the live process's series win, the other side's
    zero-valued copies are dropped."""
    seen = {line.split()[2] for line in primary.splitlines()
            if line.startswith("# TYPE ")}
    out_lines: List[str] = []
    keep = True
    for line in extra.splitlines():
        if line.startswith("# HELP "):
            keep = line.split()[2] not in seen
        if keep:
            out_lines.append(line)
    merged_extra = "\n".join(out_lines)
    if not merged_extra.strip():
        return primary
    return primary + merged_extra + "\n"


def dump_to_file(path, registry: Optional[Registry] = None) -> None:
    """Atomically write the registry's exposition to ``path`` (textfile
    collector contract: a concurrent reader must never see a truncated
    file). Failures are swallowed — metrics must never break the host
    process."""
    import os as os_lib
    tmp = str(path) + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.write(render(registry))
        os_lib.replace(tmp, str(path))
    except OSError:
        try:
            os_lib.unlink(tmp)
        except OSError:
            pass
