"""Per-engine-step performance telemetry + crash flight recorder.

PR-1 gave aggregate request metrics, PR-5 per-request spans. Neither
answers the questions the next performance levers ask: *where does one
engine step's time actually go* (queue vs prefill chunk vs decode
dispatch vs device execution vs host work), and *what did the
slots/pool look like at that instant*? The attention-constant
autotuner needs a per-(batch-band) step-time objective; disaggregated
prefill/decode autoscaling needs the prefill-vs-decode time split;
speculative decoding needs a steady-state decode baseline to beat.
This module is that measurement substrate.

Three layers:

* **Step ring** — a fixed-size ring buffer of per-engine-step records,
  recorded from the decode engine's supervisor loop (one record per
  iteration that did work). Each record:

      {"seq": N, "ts": <wall s>, "mono": <perf_counter s>,
       "dur": <step seconds>, "phase": "prefill"|"decode"|"mixed",
       "live_slots": L, "queue_depth": Q,
       "prefill_tokens": P,   # prompt tokens processed this step
       "decode_tokens": D,    # tokens emitted by the batched step
       "paged": 0|1, "kv_free": F|None, "kv_usable": U|None,
       "dispatch_s": <host dispatch seconds>|None,
       "device_s": <sampled device-wait seconds>|None,
       "spec_drafted": D,     # speculative tokens drafted this step
       "spec_accepted": A}    # ... and accepted by verification

  ``ts`` is wall clock (cross-host alignment); ``dur`` and ``mono``
  come from ``time.perf_counter()`` so an NTP step cannot corrupt a
  window (the stpu-wallclock rule). A small companion ring keeps the
  last admissions (prompt/budget/cached tokens, queue wait) — the
  workload context a post-mortem needs next to the step timings.

* **Derived metrics** — while armed, each record feeds the process
  registry (rides the replica ``/metrics`` → LB merge):
  ``stpu_engine_step_seconds{phase}``, ``stpu_engine_busy_fraction``,
  ``stpu_engine_slot_occupancy``,
  ``stpu_engine_phase_tokens_per_sec{phase}``, and the sampled
  dispatch/device split histograms. ``snapshot()`` renders the same
  ring as one JSON document — the replica's ``GET /perf``.

* **Flight recorder** — ``dump_flight(reason, error=...)`` writes the
  ring (steps + admissions + aggregate snapshot + the terminal
  exception) atomically to ``~/.stpu/logs/flightrec/``; the engine
  crash path, supervisor/gang restart paths and SIGTERM handlers call
  it, and the resulting path is stamped into the matching ``engine_*``
  lifecycle event. ``stpu perf dump|show`` read the dumps back.

Overhead discipline (mirror of ``tracing``/``fault_injection``): OFF
by default; hot call sites guard with the module attribute ``ENABLED``
(``if stepstats.ENABLED: ...``) so the disarmed cost is one global
load and a falsy branch — no records, no clock reads, no allocation
(pinned by the monkeypatch-bomb test). Arm with ``STPU_STEPSTATS=1``
(ring size ``STPU_STEPSTATS_RING``) or ``arm()`` in tests.

Dispatch-vs-device split: jitted calls return host-side as soon as the
computation is *dispatched*; the gap to the result being *ready* is
device execution. Forcing that boundary costs a sync, so it is
SAMPLED: every ``STPU_STEPSTATS_SYNC_EVERY``-th step (default 0 = off)
the engine calls :func:`sampled_sync` — one timed
``block_until_ready`` on that step's output — and the steady-state
path stays sync-free. ``sampled_sync`` is the ONLY sanctioned sync
seam in ``serve/`` (the ``stpu-host-sync`` analyzer blesses exactly
this helper and flags every other ``block_until_ready``).

Stdlib-only: no jax import (``sampled_sync`` duck-types the array).
Recording must never break the engine: all sink I/O errors are
swallowed, exactly like events/tracing.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.observability import metrics

ENABLE_ENV = "STPU_STEPSTATS"
RING_ENV = "STPU_STEPSTATS_RING"
SYNC_ENV = "STPU_STEPSTATS_SYNC_EVERY"

DEFAULT_RING = 1024
# Admission companion ring: fixed (no knob) — post-mortems want the
# recent workload shape, not an unbounded history.
ADMIT_RING = 256
# Retention: newest dumps kept on disk. Crash/restart paths dump
# unconditionally (the terminal exception matters even disarmed), so
# without a cap weeks of replica churn would fill the disk.
KEEP_DUMPS = 32

# Hot-path guard (module docstring): call sites read this module
# attribute before paying for anything else.
ENABLED = False

_PHASES = ("prefill", "decode", "mixed")

# ------------------------------------------------------------- metrics
_STEP_SECONDS = metrics.histogram(
    "stpu_engine_step_seconds",
    "Engine supervisor-loop step duration by phase (prefill = chunk "
    "prefill only, decode = batched decode only, mixed = both in one "
    "iteration). Recorded only while STPU_STEPSTATS=1.",
    ("phase",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0))
_BUSY_FRACTION = metrics.gauge(
    "stpu_engine_busy_fraction",
    "Fraction of wall time the engine spent doing prefill/decode work "
    "over the step-ring window (1.0 = fully busy).")
_OCCUPANCY = metrics.histogram(
    "stpu_engine_slot_occupancy",
    "Live slots observed per working engine step.",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
_PHASE_TOK_S = metrics.gauge(
    "stpu_engine_phase_tokens_per_sec",
    "Token throughput by phase over the step-ring window (prefill = "
    "prompt tokens processed, decode = tokens emitted).",
    ("phase",))
_DISPATCH_SECONDS = metrics.histogram(
    "stpu_engine_step_dispatch_seconds",
    "Host time to dispatch one batched step (jitted call returning, "
    "device still executing).",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.5, 2.0))
_DEVICE_SECONDS = metrics.histogram(
    "stpu_engine_step_device_seconds",
    "Sampled device-execution wait per batched step (timed "
    "block_until_ready every STPU_STEPSTATS_SYNC_EVERY steps).",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 10.0))
_DUMPS = metrics.counter(
    "stpu_engine_flightrec_dumps_total",
    "Flight-recorder dumps written, by trigger.", ("reason",))


class _Ring:
    """Fixed-size step ring with running aggregates, so the per-record
    cost is O(1): evicted records subtract their contribution, the
    gauges re-render from the sums."""

    def __init__(self, size: int):
        self.size = max(int(size), 1)
        self.buf: List[Optional[Dict[str, Any]]] = [None] * self.size
        self.idx = 0
        self.count = 0
        self.seq = 0
        self.dur_sum = 0.0
        self.occ_sum = 0
        self.phase_dur = {p: 0.0 for p in _PHASES}
        self.phase_steps = {p: 0 for p in _PHASES}
        self.tok_sum = {"prefill": 0, "decode": 0}
        self.spec_sum = {"drafted": 0, "accepted": 0}
        self.dispatch_sum = 0.0
        self.dispatch_n = 0
        self.device_sum = 0.0
        self.device_n = 0

    def _account(self, rec: Dict[str, Any], sign: int) -> None:
        self.dur_sum += sign * rec["dur"]
        self.occ_sum += sign * rec["live_slots"]
        phase = rec["phase"]
        self.phase_dur[phase] += sign * rec["dur"]
        self.phase_steps[phase] += sign
        self.tok_sum["prefill"] += sign * rec["prefill_tokens"]
        self.tok_sum["decode"] += sign * rec["decode_tokens"]
        self.spec_sum["drafted"] += sign * rec.get("spec_drafted", 0)
        self.spec_sum["accepted"] += sign * rec.get("spec_accepted", 0)
        if rec.get("dispatch_s") is not None:
            self.dispatch_sum += sign * rec["dispatch_s"]
            self.dispatch_n += sign
        if rec.get("device_s") is not None:
            self.device_sum += sign * rec["device_s"]
            self.device_n += sign

    def append(self, rec: Dict[str, Any]) -> None:
        evicted = self.buf[self.idx]
        if evicted is not None:
            self._account(evicted, -1)
        self.buf[self.idx] = rec
        self.idx = (self.idx + 1) % self.size
        self.count = min(self.count + 1, self.size)
        self.seq += 1
        self._account(rec, +1)

    def ordered(self) -> List[Dict[str, Any]]:
        """Oldest → newest."""
        if self.count < self.size:
            return [r for r in self.buf[:self.count] if r is not None]
        return [r for r in (self.buf[self.idx:] + self.buf[:self.idx])
                if r is not None]

    def window_s(self) -> float:
        """Wall window covered by the ring, monotonic-clock based:
        oldest record's start → newest record's end. O(1) — called on
        every armed record."""
        if self.count == 0:
            return 0.0
        oldest = (self.buf[self.idx] if self.count == self.size
                  else self.buf[0])
        newest = self.buf[(self.idx - 1) % self.size]
        return max(newest["mono"] - (oldest["mono"] - oldest["dur"]),
                   1e-9)


_lock = threading.Lock()
_ring = _Ring(DEFAULT_RING)
_admits: List[Dict[str, Any]] = []
_sync_every = 0
_sync_count = 0
_dump_seq = 0


# -------------------------------------------------------------- arming
def arm(ring: Optional[int] = None,
        sync_every: Optional[int] = None) -> None:
    """Turn step recording on (idempotent). ``ring`` overrides
    STPU_STEPSTATS_RING, ``sync_every`` overrides
    STPU_STEPSTATS_SYNC_EVERY for this process."""
    global ENABLED, _ring, _sync_every
    with _lock:
        if ring is None:
            try:
                ring = int(os.environ.get(RING_ENV, "1024"))
            except ValueError:
                ring = DEFAULT_RING
        if sync_every is None:
            try:
                sync_every = int(os.environ.get(SYNC_ENV, "0"))
            except ValueError:
                sync_every = 0
        if _ring.size != int(ring):
            _ring = _Ring(int(ring))
        _sync_every = max(int(sync_every), 0)
        ENABLED = True


def disarm() -> None:
    global ENABLED
    ENABLED = False


def reset() -> None:
    """Drop all recorded state (tests)."""
    global _ring, _admits, _sync_count
    with _lock:
        _ring = _Ring(_ring.size)
        _admits = []
        _sync_count = 0


# ----------------------------------------------------------- recording
def record(*, dur: float, phase: str, live_slots: int,
           queue_depth: int, prefill_tokens: int = 0,
           decode_tokens: int = 0, paged: bool = False,
           kv_free: Optional[int] = None,
           kv_usable: Optional[int] = None,
           dispatch_s: Optional[float] = None,
           device_s: Optional[float] = None,
           spec_drafted: int = 0, spec_accepted: int = 0) -> None:
    """Append one engine-step record (engine compute thread only) and
    refresh the derived metrics. Callers guard on ``ENABLED``.
    ``spec_drafted``/``spec_accepted`` are the speculative-decoding
    draft/accept token counts of a verify step (0 on plain steps)."""
    if phase not in _PHASES:
        phase = "mixed"
    rec = {
        "ts": time.time(),
        "mono": time.perf_counter(),
        "dur": float(dur),
        "phase": phase,
        "live_slots": int(live_slots),
        "queue_depth": int(queue_depth),
        "prefill_tokens": int(prefill_tokens),
        "decode_tokens": int(decode_tokens),
        "paged": int(bool(paged)),
        "kv_free": kv_free if kv_free is None else int(kv_free),
        "kv_usable": (kv_usable if kv_usable is None
                      else int(kv_usable)),
        "dispatch_s": dispatch_s,
        "device_s": device_s,
        "spec_drafted": int(spec_drafted),
        "spec_accepted": int(spec_accepted),
    }
    with _lock:
        rec["seq"] = _ring.seq
        _ring.append(rec)
        window = _ring.window_s()
        busy = min(_ring.dur_sum / window, 1.0) if window else 0.0
        tok_rates = {p: _ring.tok_sum[p] / window if window else 0.0
                     for p in ("prefill", "decode")}
    _STEP_SECONDS.labels(phase=phase).observe(rec["dur"])
    _OCCUPANCY.observe(rec["live_slots"])
    _BUSY_FRACTION.set(busy)
    for p, rate in tok_rates.items():
        _PHASE_TOK_S.labels(phase=p).set(rate)
    if dispatch_s is not None:
        _DISPATCH_SECONDS.observe(dispatch_s)
    if device_s is not None:
        _DEVICE_SECONDS.observe(device_s)


def record_admission(*, slot: int, prompt_tokens: int, max_tokens: int,
                     cached_tokens: int = 0,
                     queue_wait_s: float = 0.0) -> None:
    """Append one admission record (workload context for post-mortems).
    Callers guard on ``ENABLED``."""
    rec = {
        "ts": time.time(),
        "mono": time.perf_counter(),
        "slot": int(slot),
        "prompt_tokens": int(prompt_tokens),
        "max_tokens": int(max_tokens),
        "cached_tokens": int(cached_tokens),
        "queue_wait_s": round(float(queue_wait_s), 6),
    }
    with _lock:
        _admits.append(rec)
        if len(_admits) > ADMIT_RING:
            del _admits[:len(_admits) - ADMIT_RING]


# -------------------------------------------------------- sampled sync
def sync_due() -> bool:
    """True on every STPU_STEPSTATS_SYNC_EVERY-th call (0 = never).
    The engine asks once per decode step; the module owns the counter
    so restarted engines keep the cadence."""
    global _sync_count
    if _sync_every <= 0:
        return False
    _sync_count += 1
    if _sync_count >= _sync_every:
        _sync_count = 0
        return True
    return False


def sampled_sync(value: Any) -> float:
    """THE sanctioned device sync of the serve hot path: one timed
    ``block_until_ready`` on a step's output, returning the wait in
    seconds (device execution still outstanding at dispatch return).
    The ``stpu-host-sync`` analyzer blesses exactly this helper —
    every other sync in ``serve/`` is a finding."""
    t0 = time.perf_counter()
    try:
        value.block_until_ready()
    except AttributeError:  # non-array (tests, exotic backends)
        pass
    return time.perf_counter() - t0


# ------------------------------------------------------------ snapshot
def snapshot() -> Dict[str, Any]:
    """One JSON-ready document over the current ring: phase breakdown,
    occupancy, throughput, sampled dispatch/device split. Served as
    the replica's ``GET /perf`` and embedded in flight dumps."""
    with _lock:
        window = _ring.window_s()
        steps = _ring.count
        last = _ring.ordered()[-1] if steps else None
        phases = {}
        for p in _PHASES:
            n = _ring.phase_steps[p]
            if not n:
                continue
            phases[p] = {
                "steps": n,
                "seconds": round(_ring.phase_dur[p], 6),
            }
        if window:
            for p in phases:
                phases[p]["share"] = round(
                    _ring.phase_dur[p] / max(_ring.dur_sum, 1e-12), 4)
        doc: Dict[str, Any] = {
            "armed": ENABLED,
            "ring_size": _ring.size,
            "steps": steps,
            "total_steps": _ring.seq,
            "window_s": round(window, 6),
            "busy_fraction": round(
                min(_ring.dur_sum / window, 1.0) if window else 0.0,
                4),
            "phases": phases,
            "tokens_per_sec": {
                "prefill": round(_ring.tok_sum["prefill"] / window, 1)
                if window else 0.0,
                "decode": round(_ring.tok_sum["decode"] / window, 1)
                if window else 0.0,
            },
            "occupancy": {
                "mean": round(_ring.occ_sum / steps, 2) if steps
                else 0.0,
                "last": last["live_slots"] if last else 0,
            },
            "queue_depth": last["queue_depth"] if last else 0,
            "admissions": len(_admits),
        }
        if _ring.spec_sum["drafted"]:
            drafted = _ring.spec_sum["drafted"]
            accepted = _ring.spec_sum["accepted"]
            doc["spec"] = {
                "drafted": drafted,
                "accepted": accepted,
                "accept_rate": round(accepted / drafted, 4),
            }
        if _ring.dispatch_n:
            doc["dispatch_ms_mean"] = round(
                _ring.dispatch_sum / _ring.dispatch_n * 1e3, 3)
        if _ring.device_n:
            doc["sync"] = {
                "samples": _ring.device_n,
                "device_ms_mean": round(
                    _ring.device_sum / _ring.device_n * 1e3, 3),
                "every": _sync_every,
            }
        if last is not None:
            doc["paged"] = bool(last["paged"])
            if last["kv_usable"] is not None:
                doc["kv_pool"] = {"free": last["kv_free"],
                                  "usable": last["kv_usable"]}
        return doc


def steps_tail(n: int = 0) -> List[Dict[str, Any]]:
    """The last ``n`` step records, oldest first (0 = whole ring)."""
    with _lock:
        recs = _ring.ordered()
    return recs[-n:] if n else recs


def admissions_tail(n: int = 0) -> List[Dict[str, Any]]:
    with _lock:
        recs = list(_admits)
    return recs[-n:] if n else recs


# ------------------------------------------------------ flight recorder
def flightrec_dir() -> "os.PathLike[str]":
    from skypilot_tpu.utils import paths
    d = paths.logs_dir() / "flightrec"
    d.mkdir(parents=True, exist_ok=True)
    return d


def profiles_dir() -> "os.PathLike[str]":
    from skypilot_tpu.utils import paths
    d = paths.logs_dir() / "profiles"
    d.mkdir(parents=True, exist_ok=True)
    return d


def dump_flight(reason: str, error: Optional[str] = None,
                extra: Optional[Dict[str, Any]] = None
                ) -> Optional[str]:
    """Write the ring + admissions + terminal exception atomically to
    ``~/.stpu/logs/flightrec/`` (temp + ``os.replace`` so a concurrent
    reader never sees a torn dump). Returns the path, or None on any
    I/O failure — a post-mortem artifact must never crash the crash
    path it documents."""
    global _dump_seq
    from skypilot_tpu.observability import events
    with _lock:
        _dump_seq += 1
        seq = _dump_seq
    doc = {
        "version": 1,
        "reason": reason,
        "ts": time.time(),
        "run_id": events.run_id(),
        "pid": os.getpid(),
        "error": error,
        "snapshot": snapshot(),
        "steps": steps_tail(),
        "admissions": admissions_tail(),
    }
    if extra:
        doc.update(extra)
    # Names must sort chronologically (the retention prune and
    # read_dump's "newest" pick both rely on it), so the time prefix
    # carries microseconds — a second-granularity stamp would fall
    # back to comparing reason/pid for same-second dumps (e.g. a
    # gang_restart dump and the replacement engine's crash dump).
    now = doc["ts"]
    stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
    micros = int(now % 1.0 * 1e6)
    name = (f"{stamp}.{micros:06d}-{reason}-{os.getpid()}"
            f"-{seq:06d}.json")
    try:
        path = os.path.join(str(flightrec_dir()), name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
    except OSError:
        return None
    _DUMPS.labels(reason=reason).inc()
    _prune_dumps()
    return path


def _prune_dumps(keep: Optional[int] = None) -> None:
    """Drop the oldest dumps past the retention cap (stamped names
    sort chronologically). Best-effort, like every sink here."""
    if keep is None:
        keep = KEEP_DUMPS
    if keep <= 0:
        return
    try:
        root = str(flightrec_dir())
        names = sorted(n for n in os.listdir(root)
                       if n.endswith(".json"))
        for name in names[:-keep]:
            os.unlink(os.path.join(root, name))
    except OSError:
        pass


def list_dumps() -> List[str]:
    """Recorded flight dumps, oldest first (file names)."""
    try:
        names = sorted(os.listdir(str(flightrec_dir())))
    except OSError:
        return []
    return [n for n in names if n.endswith(".json")]


def read_dump(name: Optional[str] = None) -> Dict[str, Any]:
    """Load one dump by file name, path, or unique prefix; ``None`` =
    the newest. Raises FileNotFoundError/ValueError on no/ambiguous
    match (the CLI turns these into clean errors)."""
    if name and os.path.sep in str(name) and os.path.exists(name):
        path = str(name)
    else:
        dumps = list_dumps()
        if not dumps:
            raise FileNotFoundError(
                "no flight-recorder dumps recorded (arm "
                f"{ENABLE_ENV}=1 and crash/restart an engine)")
        if name is None:
            target = dumps[-1]
        else:
            matches = [d for d in dumps if d.startswith(str(name))]
            if not matches:
                raise FileNotFoundError(f"no dump matches {name!r}")
            if len(matches) > 1:
                raise ValueError(
                    f"{name!r} is ambiguous ({len(matches)} dumps)")
            target = matches[0]
        path = os.path.join(str(flightrec_dir()), target)
    with open(path) as f:
        doc = json.load(f)
    doc.setdefault("path", path)
    return doc


# ------------------------------------------------------------ profiler
_profile_lock = threading.Lock()
_profile_active = False


def begin_profile() -> bool:
    """Atomically claim the one-capture-at-a-time slot. The replica's
    POST /profile handler claims BEFORE answering 202 — two concurrent
    requests racing an unlocked flag would both be told a capture
    started while one silently did nothing."""
    global _profile_active
    with _profile_lock:
        if _profile_active:
            return False
        _profile_active = True
        return True


def capture_profile(seconds: float, out_dir: Optional[str] = None,
                    claimed: bool = False) -> Dict[str, Any]:
    """On-demand ``jax.profiler`` trace capture (the replica's ``POST
    /profile`` seam). Starts the trace, sleeps ``seconds`` (clamped to
    [0.05, 120]), stops it. One capture at a time per process —
    ``claimed=True`` means the caller already holds the slot via
    :func:`begin_profile`; otherwise it is claimed here and a
    concurrent capture raises cleanly. Blocking: callers run it on
    their own thread. The slot is released on every exit path."""
    seconds = min(max(float(seconds), 0.05), 120.0)
    if not claimed and not begin_profile():
        raise RuntimeError("a profile capture is already running")
    if out_dir is None:
        out_dir = os.path.join(str(profiles_dir()),
                               time.strftime("%Y%m%d-%H%M%S"))
    try:
        import jax
        jax.profiler.start_trace(str(out_dir))
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    finally:
        global _profile_active
        with _profile_lock:
            _profile_active = False
    from skypilot_tpu.observability import events
    events.emit("engine", "profiler", "profile_captured",
                seconds=seconds, out_dir=str(out_dir))
    return {"profile_dir": str(out_dir), "seconds": seconds}


# Arm from the environment at import: operators export STPU_STEPSTATS=1
# and every process in the serving stack picks it up.
if os.environ.get(ENABLE_ENV, "0") == "1":
    arm()
