"""Wide-event request analytics: ONE joined record per served request.

Every observability layer so far is aggregate (metrics), sampled
(tracing), or per-step (stepstats/trainstats); none answers "what
happened to THIS request" or "what does our real traffic look like".
This module is the per-request substrate: the LB and the engine each
assemble their half of a wide event, the engine ships its half to the
LB as a trailing ``stats`` SSE frame (stripped before the client sees
the stream), and the LB writes ONE joined JSONL record to
``~/.stpu/logs/requests.jsonl`` keyed by the trace id that already
rides ``X-STPU-Trace`` (reqlog mints ids itself when tracing is
disarmed, so the two layers arm independently).

Record shape (one JSON object per line; engine-half fields absent when
the replica predates the stats frame — LB-only degradation):

    {"request_id": <32 hex>, "ts": <wall arrival>, "status": "200",
     "error": null, "method": "POST", "path": "/generate",
     "replica": "http://...", "policy": "...", "attempts": 1,
     "retries": 0, "resumed": false, "resume_outcome": null,
     "ttft_s": ..., "e2e_s": ..., "bytes_streamed": ...,
     "prompt_tokens": ..., "max_tokens": ..., "prefix_hash": <16 hex>,
     "trace_sampled": false, "run_id": ...,
     "engine": {"queue_wait_s": ..., "prompt_tokens": ...,
                "cached_prompt_tokens": ..., "generated_tokens": ...,
                "kv_tier": "hbm|host|miss", "spec_drafted": ...,
                "spec_accepted": ..., "ttft_s": ...,
                "device_time_s": ..., "kv_quant": ...,
                "weight_quant": ..., "restarts": ...}}

``prefix_hash`` is a hash of the request's LEADING prompt chunk — the
log never stores prompt text/tokens, yet ``loadgen.derive_spec`` can
still recover the prefix-reuse structure (how many distinct prefixes,
how shared) for replay. ``engine.device_time_s`` is the request's
device-time share, accumulated host-side as ``step_dur/live_slots``
per decode step — the cost-attribution number multi-tenant billing
needs.

Tail-biased sampling (the write-time contract): ``STPU_REQLOG_SAMPLE``
in [0, 1] thins SUCCESSFUL requests only. Errors, resumed streams, and
requests whose TTFT/e2e exceed ``STPU_REQLOG_SLOW_TTFT`` /
``STPU_REQLOG_SLOW_E2E`` seconds are ALWAYS written — the tail is the
point of a request log, so it is never sampled away. A kept-for-cause
record carries ``keep`` ("error" | "resumed" | "slow_ttft" |
"slow_e2e") so readers can distinguish biased keeps from the uniform
sample.

Overhead discipline (mirror of tracing.py / fault_injection.py):
reqlog is OFF by default; hot call sites guard with the module
attribute ``ENABLED`` (``if reqlog.ENABLED: ...``) so the unarmed cost
is one global load and a falsy branch — no record dicts, no clock
reads, no hashing. Arm with ``STPU_REQLOG=1`` (every process picks it
up at import) or ``arm()`` in tests. Sink I/O failures are swallowed,
exactly like events.emit — analytics must never break the request.
"""
from __future__ import annotations

import json
import os
import random
import threading
import uuid
from typing import Any, Dict, List, Optional

ENABLE_ENV = "STPU_REQLOG"
SAMPLE_ENV = "STPU_REQLOG_SAMPLE"
SLOW_TTFT_ENV = "STPU_REQLOG_SLOW_TTFT"
SLOW_E2E_ENV = "STPU_REQLOG_SLOW_E2E"

# Hot-path guard (see module docstring). Call sites read this module
# attribute before paying for anything else.
ENABLED = False

# Requests are higher-volume than events but each record is small; same
# cap + one-generation policy as traces.jsonl.
_MAX_BYTES = 16 * 1024 * 1024

_lock = threading.Lock()
_rng = random.Random()
_sample_rate = 1.0
_slow_ttft_s = 1.0
_slow_e2e_s = 10.0


def requests_path() -> "os.PathLike[str]":
    from skypilot_tpu.utils import paths
    return paths.logs_dir() / "requests.jsonl"


def _env_float(env: str, default: str) -> float:
    try:
        return float(os.environ.get(env, default))
    except ValueError:
        return float(default)


# ------------------------------------------------------------- arming
def arm(sample: Optional[float] = None,
        slow_ttft: Optional[float] = None,
        slow_e2e: Optional[float] = None) -> None:
    """Turn the request log on (idempotent). Keyword overrides beat the
    STPU_REQLOG_SAMPLE / _SLOW_TTFT / _SLOW_E2E env knobs for this
    process (tests)."""
    global ENABLED, _sample_rate, _slow_ttft_s, _slow_e2e_s
    if sample is None:
        sample = _env_float(SAMPLE_ENV, "1")
    _sample_rate = min(max(float(sample), 0.0), 1.0)
    _slow_ttft_s = (float(slow_ttft) if slow_ttft is not None
                    else _env_float(SLOW_TTFT_ENV, "1.0"))
    _slow_e2e_s = (float(slow_e2e) if slow_e2e is not None
                   else _env_float(SLOW_E2E_ENV, "10.0"))
    ENABLED = True


def disarm() -> None:
    global ENABLED
    ENABLED = False


def slow_thresholds() -> "tuple[float, float]":
    """(slow_ttft_s, slow_e2e_s) currently in force — the CLI's
    ``--slow`` filter uses the same line the writer drew."""
    return _slow_ttft_s, _slow_e2e_s


# ---------------------------------------------------------------- ids
def mint_id() -> str:
    """A fresh request id, same shape as a trace id (32 hex) so the two
    key spaces interchange: when tracing is armed the trace id IS the
    request id; when only reqlog is armed the LB mints one here and
    still rides it on X-STPU-Trace (sampled flag 00) so the engine half
    joins by the same key."""
    return uuid.uuid4().hex


# ------------------------------------------------------------ sampling
def keep_reason(record: Dict[str, Any]) -> Optional[str]:
    """Why this record bypasses sampling, or None for a plain success
    (which is subject to the uniform sample). Pure — decided from the
    record alone, so the contract is testable without I/O."""
    status = str(record.get("status", ""))
    if record.get("error") or status not in ("ok", "200"):
        return "error"
    if record.get("resumed"):
        return "resumed"
    ttft = record.get("ttft_s")
    if isinstance(ttft, (int, float)) and ttft >= _slow_ttft_s:
        return "slow_ttft"
    e2e = record.get("e2e_s")
    if isinstance(e2e, (int, float)) and e2e >= _slow_e2e_s:
        return "slow_e2e"
    return None


def write_record(record: Dict[str, Any]) -> bool:
    """Append one joined request record, applying the tail-biased
    sampling contract at this single write point. Returns whether the
    record was written (tests pin the always-keep classes on this).
    Never raises."""
    if not ENABLED:
        return False
    reason = keep_reason(record)
    if reason is not None:
        record["keep"] = reason
    elif _sample_rate < 1.0 and _rng.random() >= _sample_rate:
        return False
    _write(record)
    return True


# ---------------------------------------------------------------- sink
def _write(record: Dict[str, Any]) -> None:
    """Shared rotate+append path with the event/trace logs
    (observability/jsonl_log.py). Never raises."""
    from skypilot_tpu.observability import jsonl_log
    try:
        line = json.dumps(record, default=str)
    except (TypeError, ValueError):
        return
    try:
        path = requests_path()
    except OSError:
        return
    jsonl_log.append_line(path, line, _MAX_BYTES, _lock)


# -------------------------------------------------------------- reading
def read(path: Optional[str] = None,
         request_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """All request records (rotated generation included, oldest first);
    garbage lines skipped — a crash mid-append leaves at most one
    truncated line. ``request_id`` accepts an unambiguous prefix (the
    trace-id abbreviation convention)."""
    target = str(path or requests_path())
    out: List[Dict[str, Any]] = []
    for p in (target + ".1", target):
        try:
            with open(p, "r", errors="replace") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "request_id" not in rec:
                continue
            if (request_id is not None
                    and not str(rec["request_id"]).startswith(request_id)):
                continue
            out.append(rec)
    return out


def is_slow(record: Dict[str, Any]) -> bool:
    """The CLI ``--slow`` predicate: over either slow threshold."""
    ttft = record.get("ttft_s")
    e2e = record.get("e2e_s")
    return ((isinstance(ttft, (int, float)) and ttft >= _slow_ttft_s)
            or (isinstance(e2e, (int, float)) and e2e >= _slow_e2e_s))


# Arm from the environment at import: operators export STPU_REQLOG=1
# and every process in the serving stack picks it up.
if os.environ.get(ENABLE_ENV, "0") == "1":
    arm()
