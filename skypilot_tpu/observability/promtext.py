"""Prometheus text-exposition (format 0.0.4) PARSER — the inverse of
``metrics.render()``.

Three consumers read exposition documents today and each grew its own
string handling: the loadgen scraper (benchmark/loadgen.py) snapshots a
live LB's /metrics into a time series, ``tools/bench_compare.py``-style
gates diff counter values between runs, and tests assert on scraped
families. Ad-hoc ``"name 5" in text`` checks break the moment a label
is added or a float renders differently, so the parsing lives HERE
once, exactly dual to the renderer: ``parse(render(reg))`` recovers
every sample bit-for-bit and ``render_families(parse(text)) == text``
for any renderer-produced document (the round-trip the golden tests
pin).

Shapes:

    families = promtext.parse(text)   # name -> Family
    fam = families["stpu_lb_requests_total"]
    fam.kind                          # "counter" | "gauge" |
                                      # "histogram" | "untyped"
    fam.samples                       # [Sample(name, labels, value)]
    promtext.value(families, "stpu_engine_up")
    promtext.counter_total(families, "stpu_lb_requests_total",
                           code="200")
    snap = promtext.histogram(families, "stpu_engine_ttft_seconds")
    snap.quantile(0.99)               # interpolated, like PromQL's
                                      # histogram_quantile

Histogram samples (``_bucket``/``_sum``/``_count``) attach to their
declared family; ``HistogramSnapshot`` carries the cumulative bucket
counts and delegates quantile interpolation to
``metrics.quantile_from_cumulative`` so a quantile computed from a
scrape and one computed live from a ``Histogram`` child can never
disagree. ``delta()`` subtracts two snapshots of the same histogram —
the run-scoped distribution between two scrapes, which is what an SLO
report wants (the live histogram is cumulative since process start).

Stdlib-only, like everything else in observability/.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.observability import metrics as _metrics


@dataclasses.dataclass
class Sample:
    name: str
    labels: Tuple[Tuple[str, str], ...]   # sorted (name, value) pairs
    value: float

    def label(self, key: str, default: str = "") -> str:
        for k, v in self.labels:
            if k == key:
                return v
        return default


@dataclasses.dataclass
class Family:
    name: str
    kind: str = "untyped"
    help: str = ""
    samples: List[Sample] = dataclasses.field(default_factory=list)


class ParseError(ValueError):
    """Malformed exposition text."""


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text in ("NaN", "nan"):
        return math.nan
    return float(text)


def _unescape_label(value: str) -> str:
    """Inverse of metrics._escape_label: \\\\ -> \\, \\n -> newline,
    \\" -> "  (processed left to right, so an escaped backslash never
    re-triggers)."""
    out: List[str] = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == '"':
                out.append('"')
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_labels(body: str, line: str
                  ) -> Tuple[Tuple[str, str], ...]:
    """Parse the inside of a ``{...}`` label block. A hand-rolled
    scanner because label VALUES may contain commas, quotes, and
    escaped backslashes — splitting on "," corrupts exactly the inputs
    the escaping exists for."""
    labels: List[Tuple[str, str]] = []
    i = 0
    while i < len(body):
        eq = body.find("=", i)
        if eq < 0:
            raise ParseError(f"bad label block in line {line!r}")
        name = body[i:eq].strip()
        if not name or body[eq + 1:eq + 2] != '"':
            raise ParseError(f"bad label block in line {line!r}")
        j = eq + 2
        raw: List[str] = []
        while j < len(body):
            ch = body[j]
            if ch == "\\" and j + 1 < len(body):
                raw.append(body[j:j + 2])
                j += 2
                continue
            if ch == '"':
                break
            raw.append(ch)
            j += 1
        else:
            raise ParseError(f"unterminated label value in {line!r}")
        labels.append((name, _unescape_label("".join(raw))))
        i = j + 1
        if i < len(body) and body[i] == ",":
            i += 1
    return tuple(labels)


_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def parse(text: str) -> Dict[str, Family]:
    """Parse one exposition document into ``{name: Family}``. Sample
    order within a family and family order in the document are
    preserved (render_families round-trips). Unknown/extra text raises
    ParseError — a scraper must not silently misread a document."""
    families: Dict[str, Family] = {}

    def family(name: str) -> Family:
        fam = families.get(name)
        if fam is None:
            fam = families[name] = Family(name)
        return fam

    def owner(sample_name: str) -> Family:
        # _bucket/_sum/_count of a DECLARED histogram family attach to
        # it; otherwise the sample owns its literal name.
        for suffix in _HIST_SUFFIXES:
            if sample_name.endswith(suffix):
                base = sample_name[:-len(suffix)]
                fam = families.get(base)
                if fam is not None and fam.kind == "histogram":
                    return fam
        return family(sample_name)

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            family(name).help = help_text
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                raise ParseError(f"bad TYPE line {line!r}")
            family(parts[2]).kind = parts[3]
            continue
        if line.startswith("#"):
            continue                     # other comments are legal
        # Sample line: name[{labels}] value
        brace = line.find("{")
        labels: Tuple[Tuple[str, str], ...] = ()
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ParseError(f"bad sample line {line!r}")
            name = line[:brace]
            labels = _parse_labels(line[brace + 1:close], line)
            value_text = line[close + 1:].strip()
        else:
            try:
                name, value_text = line.split(None, 1)
            except ValueError as e:
                raise ParseError(f"bad sample line {line!r}") from e
        try:
            value = _parse_value(value_text.split()[0])
        except (ValueError, IndexError) as e:
            raise ParseError(f"bad sample value in {line!r}") from e
        owner(name).samples.append(Sample(name, labels, value))
    return families


def render_families(families: Dict[str, Family]) -> str:
    """Render parsed families back to exposition text — the golden
    round-trip partner of parse(); matches metrics.render()'s layout
    (HELP then TYPE then samples, one trailing newline)."""
    out: List[str] = []
    for fam in families.values():
        out.append(f"# HELP {fam.name} {fam.help}")
        out.append(f"# TYPE {fam.name} {fam.kind}")
        for s in fam.samples:
            out.append(
                f"{s.name}"
                f"{_metrics._format_labels([k for k, _ in s.labels], [v for _, v in s.labels])}"
                f" {_metrics._format_value(s.value)}")
    return "\n".join(out) + "\n" if out else ""


def _match(sample: Sample, want: Dict[str, str]) -> bool:
    have = dict(sample.labels)
    return all(have.get(k) == str(v) for k, v in want.items())


def value(families: Dict[str, Family], name: str,
          default: float = 0.0, **labels: str) -> float:
    """The first sample of ``name`` matching the given labels (subset
    match), or ``default``. For counters/gauges."""
    fam = families.get(name)
    if fam is None:
        return default
    for s in fam.samples:
        if s.name == name and _match(s, labels):
            return s.value
    return default


def counter_total(families: Dict[str, Family], name: str,
                  **labels: str) -> float:
    """Sum of every ``name`` sample matching the label subset — e.g.
    all codes of a requests counter, or one code across methods."""
    fam = families.get(name)
    if fam is None:
        return 0.0
    return sum(s.value for s in fam.samples
               if s.name == name and _match(s, labels))


@dataclasses.dataclass
class HistogramSnapshot:
    """One histogram series (or label-aggregated family) at scrape
    time: ``bounds`` are the finite upper bounds, ``cumulative`` the
    cumulative counts INCLUDING the trailing +Inf bucket."""
    bounds: List[float]
    cumulative: List[float]
    sum: float
    count: float

    def quantile(self, q: float) -> float:
        return _metrics.quantile_from_cumulative(
            self.bounds, self.cumulative, q)

    def delta(self, earlier: "HistogramSnapshot"
              ) -> "HistogramSnapshot":
        """This snapshot minus an ``earlier`` one of the SAME series —
        the distribution of observations made between the two scrapes
        (live histograms are cumulative since process start, so an SLO
        report over a run window needs the difference, not the
        total)."""
        if earlier.bounds != self.bounds:
            raise ValueError("histogram bucket bounds changed between "
                             "snapshots; delta undefined")
        return HistogramSnapshot(
            bounds=list(self.bounds),
            cumulative=[max(a - b, 0.0) for a, b in
                        zip(self.cumulative, earlier.cumulative)],
            sum=max(self.sum - earlier.sum, 0.0),
            count=max(self.count - earlier.count, 0.0))


def histogram(families: Dict[str, Family], name: str,
              **labels: str) -> Optional[HistogramSnapshot]:
    """Reassemble ``name``'s bucket/sum/count samples into one
    HistogramSnapshot. Label-subset matching; series sharing the same
    bucket layout are SUMMED bucket-wise (e.g. every ``code`` of the
    LB latency histogram when no code is named). None when the family
    has no matching samples."""
    fam = families.get(name)
    if fam is None or fam.kind != "histogram":
        return None
    # Group buckets by their non-le label set.
    series: Dict[Tuple[Tuple[str, str], ...], Dict[float, float]] = {}
    sums: Dict[Tuple[Tuple[str, str], ...], float] = {}
    counts: Dict[Tuple[Tuple[str, str], ...], float] = {}
    for s in fam.samples:
        ident = tuple(kv for kv in s.labels if kv[0] != "le")
        if not _match(Sample(s.name, ident, 0.0), labels):
            continue
        if s.name == name + "_bucket":
            le = s.label("le")
            series.setdefault(ident, {})[_parse_value(le)] = s.value
        elif s.name == name + "_sum":
            sums[ident] = s.value
        elif s.name == name + "_count":
            counts[ident] = s.value
    if not series:
        return None
    layouts = {tuple(sorted(b)) for b in series.values()}
    if len(layouts) > 1:
        raise ValueError(
            f"{name}: matched series disagree on bucket bounds; "
            "name more labels")
    all_bounds = sorted(next(iter(layouts)))
    merged = [sum(b[bound] for b in series.values())
              for bound in all_bounds]
    finite = [b for b in all_bounds if not math.isinf(b)]
    return HistogramSnapshot(
        bounds=finite,
        cumulative=merged,
        sum=sum(sums.get(i, 0.0) for i in series),
        count=sum(counts.get(i, 0.0) for i in series))
