"""Structured JSONL lifecycle-event log.

Reference analog: the reference scatters lifecycle breadcrumbs across
per-subsystem logs (skylet events log, serve controller prints,
jobs controller prints); here every state transition lands in ONE
append-only JSONL file so `stpu status --events` / `stpu serve status`
can answer "what just happened" without grepping five logs.

Record shape (one JSON object per line):

    {"ts": <wall seconds>, "mono": <perf_counter seconds>,
     "run_id": "abc123def456", "kind": "replica",
     "name": "svc/3", "event": "READY", ...free-form fields}

``ts`` is wall clock for cross-host alignment; ``mono`` is the
process-local monotonic stamp so in-process durations between two
events survive NTP steps. ``run_id`` identifies the originating CLI
invocation and propagates through ``STPU_RUN_ID`` (subprocess env) and
the gang job spec, CLI -> controller -> gang driver -> job env.

Emission must never break the instrumented call: all I/O errors are
swallowed. Disable entirely with ``STPU_DISABLE_EVENTS=1``.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

DISABLE_ENV = "STPU_DISABLE_EVENTS"
RUN_ID_ENV = "STPU_RUN_ID"

# Rotate past this size: events.jsonl -> events.jsonl.1 (one generation
# kept). Lifecycle transitions are low-rate; 4 MB is months of them.
_MAX_BYTES = 4 * 1024 * 1024

_lock = threading.Lock()


def _enabled() -> bool:
    return os.environ.get(DISABLE_ENV, "0") != "1"


def run_id() -> str:
    """This invocation's run ID. First call generates one and exports it
    via the environment so every child process (serve controller, LB,
    jobs controller, gang driver) inherits the same ID."""
    rid = os.environ.get(RUN_ID_ENV)
    if not rid:
        rid = uuid.uuid4().hex[:12]
        os.environ[RUN_ID_ENV] = rid
    return rid


def log_path() -> "os.PathLike[str]":
    from skypilot_tpu.utils import paths
    return paths.logs_dir() / "events.jsonl"


def emit(kind: str, name: str, event: str, **fields: Any) -> None:
    """Append one lifecycle record. Never raises."""
    if not _enabled():
        return
    record: Dict[str, Any] = {
        "ts": time.time(),
        "mono": time.perf_counter(),
        "run_id": run_id(),
        "kind": kind,
        "name": name,
        "event": event,
    }
    record.update(fields)
    try:
        line = json.dumps(record, default=str)
    except (TypeError, ValueError):
        return
    from skypilot_tpu.observability import jsonl_log
    try:
        path = log_path()
    except OSError:
        return
    jsonl_log.append_line(path, line, _MAX_BYTES, _lock)


_SINCE_RE = re.compile(r"(\d+(?:\.\d+)?)([smhd])")


def parse_since(value: str) -> float:
    """Parse a ``--since`` window into a wall-clock threshold (unix
    seconds). Accepts a relative duration (``30s``/``5m``/``2h``/
    ``1d`` ago), raw unix seconds, or a local timestamp
    (``YYYY-MM-DD[ HH:MM[:SS]]``, ``T`` separator accepted)."""
    value = str(value).strip()
    m = _SINCE_RE.fullmatch(value)
    if m:
        mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}[m.group(2)]
        # Threshold compared against persisted wall stamps.
        return time.time() - float(m.group(1)) * mult  # noqa: stpu-wallclock threshold against persisted wall stamps
    try:
        return float(value)
    except ValueError:
        pass
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%dT%H:%M:%S",
                "%Y-%m-%d %H:%M", "%Y-%m-%dT%H:%M", "%Y-%m-%d"):
        try:
            return time.mktime(time.strptime(value, fmt))
        except ValueError:
            continue
    raise ValueError(
        f"unparseable --since value {value!r}: want a duration "
        "(30s/5m/2h/1d), unix seconds, or YYYY-MM-DD[ HH:MM[:SS]]")


def read(kind: Optional[str] = None, name: Optional[str] = None,
         limit: Optional[int] = 50,
         path: Optional[str] = None,
         max_bytes: Optional[int] = None,
         since: Optional[float] = None) -> List[Dict[str, Any]]:
    """Most-recent-last matching records (garbage lines skipped — a
    crash mid-append leaves at most one truncated line).

    ``max_bytes`` tails only the newest that many bytes of the current
    generation (skipping the rotated one) — for hot callers that only
    want recent records and must not pay a full multi-MB parse.
    ``since`` keeps only records whose wall stamp is at or after that
    unix-seconds threshold (see parse_since for the CLI grammar)."""
    target = path or log_path()
    out: List[Dict[str, Any]] = []
    # Include the rotated generation so a read right after rotation
    # still sees recent history (unless a bounded tail was asked for).
    files = ([str(target)] if max_bytes is not None
             else [str(target) + ".1", str(target)])
    for p in files:
        try:
            with open(p, "rb") as f:
                if max_bytes is not None:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    if size > max_bytes:
                        f.seek(size - max_bytes)
                        f.readline()   # drop the partial first line
                    else:
                        f.seek(0)
                data = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        for line in data.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if kind is not None and rec.get("kind") != kind:
                continue
            if name is not None and rec.get("name") != name:
                continue
            if since is not None and rec.get("ts", 0) < since:
                continue
            out.append(rec)
    if limit is not None:
        out = out[-limit:] if limit > 0 else []
    return out


def last(kind: str, name: Optional[str] = None
         ) -> Optional[Dict[str, Any]]:
    """The most recent record of ``kind`` (optionally for ``name``)."""
    recs = read(kind=kind, name=name, limit=1)
    return recs[-1] if recs else None
