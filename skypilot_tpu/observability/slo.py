"""SLO burn-rate monitor over the fleet telemetry store.

Per-service objectives are declared in the service YAML
(``service.slo.objectives``, see serve/service_spec.py) and evaluated
against the controller-resident TimeSeriesStore each collector tick:

    slo:
      objectives:
        - kind: ttft          # ttft | tpot | error_rate
          threshold_seconds: 1.0
          target: 0.99

An objective says "``target`` of requests are good", where *good* is
kind-shaped: a ``ttft`` request whose service-edge first byte arrived
within ``threshold_seconds`` (the LB's ``stpu_lb_ttfb_seconds``
histogram — the client-observed TTFT including queueing, retries and
upstream delays); a ``tpot`` decode step under ``threshold_seconds``
(``stpu_engine_step_seconds{phase="decode"}``, present when replicas
run with STPU_STEPSTATS=1); an ``error_rate`` request that did not
fail (non-5xx/non-upstream_aborted ``stpu_lb_requests_total``; a
``client_closed`` hang-up is the client's doing, not an error).

**Burn rate** (the Google-SRE multiwindow definition): over a window
W, ``burn = bad_fraction / (1 - target)`` — the rate at which the
error budget is being consumed, normalized so burn == 1 means
consuming exactly the window's pro-rata budget. The monitor evaluates
a FAST window (detection latency) and a SLOW window (noise rejection);
an objective **breaches** when BOTH exceed the burn threshold, the
standard guard against paging on a single bad scrape.
``budget_remaining = max(0, 1 - burn_slow)`` — the fraction of the
slow window's error budget left.

An empty window (no traffic, or a family the fleet doesn't expose)
yields ``burn = None`` — never NaN: ``quantile_from_cumulative`` and
fraction math return NaN on all-zero deltas, and a NaN compared
against a threshold is silently False, which would read as "SLO
healthy" during an outage that stops all traffic. None is rendered as
``-`` by ``stpu top``/``stpu slo`` and is excluded from breach edges.

Emits ``slo_breach`` / ``slo_recovered`` lifecycle events (kind
``slo``) on edges and keeps ``stpu_slo_burn_rate`` /
``stpu_slo_budget_remaining`` gauges current. ``latency_signals()``
is the seam the latency-aware autoscaler consumes
(serve/autoscalers.py) — plain data, so the autoscaler stays
import-light and unit-testable with synthetic signals.

Stdlib-only, like everything else in observability/.
"""
from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.observability import events
from skypilot_tpu.observability import metrics

DEFAULT_FAST_WINDOW = 300.0      # 5 min: detection
DEFAULT_SLOW_WINDOW = 3600.0     # 1 h: noise rejection
DEFAULT_BURN_THRESHOLD = 1.0     # burn >= 1 consumes budget too fast

KINDS = ("ttft", "tpot", "error_rate")

# Metric family each kind evaluates, and the extra label filter.
_FAMILY = {
    "ttft": ("stpu_lb_ttfb_seconds", {}),
    "tpot": ("stpu_engine_step_seconds", {"phase": "decode"}),
}
_ERROR_FAMILY = "stpu_lb_requests_total"

_BURN_GAUGE = metrics.gauge(
    "stpu_slo_burn_rate",
    "Error-budget burn rate per objective and window (1.0 = consuming "
    "exactly the window's pro-rata budget; 0 when the window is "
    "empty).", ("service", "objective", "window"))
_BUDGET_GAUGE = metrics.gauge(
    "stpu_slo_budget_remaining",
    "Fraction of the slow window's error budget unconsumed, in "
    "[0, 1].", ("service", "objective"))


def fast_window_seconds() -> float:
    return float(os.environ.get("STPU_SLO_FAST_WINDOW", "300"))


def slow_window_seconds() -> float:
    return float(os.environ.get("STPU_SLO_SLOW_WINDOW", "3600"))


def burn_threshold() -> float:
    return float(os.environ.get("STPU_SLO_BURN_THRESHOLD", "1.0"))


@dataclasses.dataclass(frozen=True)
class Objective:
    kind: str                          # ttft | tpot | error_rate
    target: float                      # good-fraction target, e.g. 0.99
    threshold_s: Optional[float] = None  # latency kinds only

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "Objective":
        kind = config.get("kind")
        if kind not in KINDS:
            raise ValueError(
                f"slo objective kind must be one of {KINDS}, "
                f"got {kind!r}")
        target = float(config.get("target", 0.99))
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"slo target must be in (0, 1), got {target}")
        threshold = config.get("threshold_seconds")
        if kind in ("ttft", "tpot"):
            if threshold is None:
                raise ValueError(
                    f"slo objective {kind!r} needs threshold_seconds")
            threshold = float(threshold)
            if threshold <= 0:
                raise ValueError("threshold_seconds must be > 0")
        elif threshold is not None:
            raise ValueError(
                "error_rate objectives take no threshold_seconds")
        return cls(kind=kind, target=target, threshold_s=threshold)

    def to_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": self.kind, "target": self.target}
        if self.threshold_s is not None:
            out["threshold_seconds"] = self.threshold_s
        return out


def _good_count(snap, threshold: float) -> float:
    """Observations <= the bucket bound enclosing ``threshold`` (the
    cumulative count at the first bound >= threshold — a threshold
    between bounds resolves to the enclosing bucket, documented in
    docs/observability.md)."""
    for bound, cum in zip(snap.bounds, snap.cumulative):
        if bound >= threshold:
            return cum
    return snap.count


class SloMonitor:
    def __init__(self, service_name: str, objectives: List[Objective],
                 store, fast_window: Optional[float] = None,
                 slow_window: Optional[float] = None,
                 threshold: Optional[float] = None):
        self.service_name = service_name
        self.objectives = list(objectives)
        self.store = store
        self.fast_window = (fast_window_seconds()
                            if fast_window is None else float(fast_window))
        self.slow_window = (slow_window_seconds()
                            if slow_window is None else float(slow_window))
        self.threshold = (burn_threshold()
                          if threshold is None else float(threshold))
        self._breaching: Dict[str, bool] = {o.kind: False
                                            for o in self.objectives}
        self._last_state: Dict[str, Any] = {}

    @classmethod
    def from_spec(cls, service_name: str, spec,
                  store) -> Optional["SloMonitor"]:
        configs = getattr(spec, "slo_objectives", None)
        if not configs:
            return None
        return cls(service_name,
                   [Objective.from_config(c) for c in configs], store)

    # ---------------------------------------------------------- burn math
    def _bad_fraction(self, obj: Objective, window: float,
                      now: float) -> Optional[float]:
        if obj.kind == "error_rate":
            total = self.store.window_delta(_ERROR_FAMILY, window, now)
            if not total:
                return None
            bad = 0.0
            for labels in self.store.labels_for(_ERROR_FAMILY):
                code = labels.get("code", "")
                # upstream_aborted = a replica died mid-stream and the
                # resume ladder could not heal it — our failure.
                # client_closed = the CLIENT hung up mid-stream; not
                # charged (burning error budget on closed tabs would
                # page operators for user behavior). "aborted" is the
                # pre-split legacy code, kept bad for old stores.
                if code.startswith("5") or code in (
                        "0", "aborted", "upstream_aborted"):
                    bad += self.store.window_delta(
                        _ERROR_FAMILY, window, now, **labels) or 0.0
            frac = bad / total
        else:
            family, extra = _FAMILY[obj.kind]
            snap = self.store.histogram_delta(family, window, now,
                                              **extra)
            if snap is None or snap.count <= 0:
                return None
            frac = 1.0 - _good_count(snap, obj.threshold_s) / snap.count
        # The NaN guard: quantile/fraction math over a raced or
        # clamped-to-zero delta must surface as "no data", never as a
        # NaN that compares False against every threshold.
        if math.isnan(frac):
            return None
        return min(max(frac, 0.0), 1.0)

    def _burn(self, obj: Objective, window: float,
              now: float) -> Optional[float]:
        frac = self._bad_fraction(obj, window, now)
        if frac is None:
            return None
        return frac / max(1e-9, 1.0 - obj.target)

    # ---------------------------------------------------------- evaluate
    def evaluate(self, now: float) -> Dict[str, Any]:
        """One evaluation pass: refresh gauges, emit breach/recovery
        events on edges, return (and cache) the state document."""
        state: Dict[str, Any] = {
            "service": self.service_name,
            "fast_window_s": self.fast_window,
            "slow_window_s": self.slow_window,
            "burn_threshold": self.threshold,
            "objectives": [],
            "degraded": False,
        }
        for obj in self.objectives:
            fast = self._burn(obj, self.fast_window, now)
            slow = self._burn(obj, self.slow_window, now)
            for window, burn in (("fast", fast), ("slow", slow)):
                _BURN_GAUGE.labels(service=self.service_name,
                                   objective=obj.kind,
                                   window=window).set(burn or 0.0)
            budget = (max(0.0, 1.0 - slow)
                      if slow is not None else None)
            _BUDGET_GAUGE.labels(
                service=self.service_name, objective=obj.kind).set(
                    1.0 if budget is None else budget)
            breaching = (fast is not None and slow is not None and
                         fast >= self.threshold and
                         slow >= self.threshold)
            was = self._breaching.get(obj.kind, False)
            if breaching and not was:
                events.emit("slo", self.service_name, "slo_breach",
                            objective=obj.kind,
                            burn_fast=round(fast, 3),
                            burn_slow=round(slow, 3),
                            target=obj.target)
            elif was and not breaching:
                events.emit("slo", self.service_name, "slo_recovered",
                            objective=obj.kind,
                            burn_fast=(round(fast, 3)
                                       if fast is not None else None),
                            burn_slow=(round(slow, 3)
                                       if slow is not None else None))
            self._breaching[obj.kind] = breaching
            state["objectives"].append({
                "kind": obj.kind,
                "target": obj.target,
                "threshold_seconds": obj.threshold_s,
                "burn_fast": fast,
                "burn_slow": slow,
                "budget_remaining": budget,
                "breaching": breaching,
            })
            state["degraded"] = state["degraded"] or breaching
        self._last_state = state
        return state

    # ------------------------------------------------------------- views
    def state(self) -> Dict[str, Any]:
        """The last evaluation's document (for GET /fleet and
        ``stpu slo``)."""
        return dict(self._last_state)

    def degraded(self) -> bool:
        return any(self._breaching.values())

    def latency_signals(self) -> Dict[str, Any]:
        """The autoscaler seam: per-kind burn readings from the last
        evaluation, as plain data. ``burn_fast``/``burn_slow`` are
        None when the window held no observations — the latency policy
        treats that as "no pressure", not as zero burn."""
        signals: Dict[str, Any] = {"degraded": False}
        for entry in self._last_state.get("objectives", []):
            signals[entry["kind"]] = {
                "burn_fast": entry["burn_fast"],
                "burn_slow": entry["burn_slow"],
                "breaching": entry["breaching"],
            }
            signals["degraded"] = (signals["degraded"] or
                                   entry["breaching"])
        return signals
