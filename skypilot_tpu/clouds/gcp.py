"""GCP TPU capability object.

Reference analog: sky/clouds/gcp.py:558-610 (TPU-VM host sizing and the
unstoppable-pod special cases). The TPU-specific rules:

  * multi-host pod slices cannot be stopped, only terminated — the TPU
    API rejects `stop` on pods (provision/gcp.py stop_instances);
  * therefore autostop on a pod must use --down;
  * custom machine images don't apply to TPU VMs (runtime_version is the
    image knob);
  * firewall/port management: provision/gcp.py open_ports/cleanup_ports
    (per-cluster tagged VPC ingress rule).
"""
from __future__ import annotations

import shutil
import subprocess
from typing import Dict, Tuple

from skypilot_tpu.clouds.cloud import (Cloud, CloudImplementationFeatures,
                                       pod_stop_rules)


class GCP(Cloud):
    NAME = "gcp"

    _UNSUPPORTED = {
        CloudImplementationFeatures.IMAGE_ID:
            "TPU VMs take a runtime_version, not a machine image",
        # OPEN_PORTS is supported: provision/gcp.py open_ports manages a
        # per-cluster tagged VPC ingress rule (reference:
        # sky/provision/gcp/instance.py:571).
    }

    def unsupported_features_for_resources(
            self, resources) -> Dict[CloudImplementationFeatures, str]:
        return {**self._UNSUPPORTED,
                **pod_stop_rules(resources,
                                 "Use `down` / autostop --down "
                                 "(TPU API limitation).")}

    def check_credentials(self) -> Tuple[bool, str]:
        """Usable = gcloud exists + active credentials + a project set.

        The TPU API itself is only reachable with network access; like
        the reference we treat credential presence as 'enabled' and
        surface API errors at provision time with failover semantics."""
        if shutil.which("gcloud") is None:
            return False, "gcloud CLI not installed"
        try:
            proc = subprocess.run(
                ["gcloud", "auth", "list",
                 "--filter=status:ACTIVE", "--format=value(account)"],
                capture_output=True, text=True, timeout=20)
            if proc.returncode != 0 or not proc.stdout.strip():
                return False, ("no active gcloud credentials "
                               "(run `gcloud auth login`)")
            proc = subprocess.run(
                ["gcloud", "config", "get-value", "project"],
                capture_output=True, text=True, timeout=20)
            project = proc.stdout.strip()
            if proc.returncode != 0 or not project or project == "(unset)":
                return False, ("no GCP project configured "
                               "(run `gcloud config set project ...`)")
            return True, f"project {project}"
        except (subprocess.SubprocessError, OSError) as e:
            return False, f"gcloud probe failed: {e}"
