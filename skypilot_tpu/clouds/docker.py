"""Docker capability object (dev/debug provider).

Reference analog: the LocalDockerBackend path
(sky/backends/local_docker_backend.py). Containers CAN stop (disk
survives `docker stop`), there is no spot market, and accelerators are
not passed through — this provider exists for orchestration development
and containerized CPU tasks.
"""
from __future__ import annotations

import shutil
import subprocess
from typing import Dict, Tuple

from skypilot_tpu.clouds.cloud import Cloud, CloudImplementationFeatures


class Docker(Cloud):
    NAME = "docker"

    _UNSUPPORTED = {
        CloudImplementationFeatures.SPOT_INSTANCE:
            "no spot market on a local docker daemon",
        CloudImplementationFeatures.OPEN_PORTS:
            "publish ports via docker run -p out of band (not "
            "implemented yet)",
        CloudImplementationFeatures.MULTI_NODE:
            "docker is the single-container dev path (reference "
            "LocalDockerBackend semantics); use local/kubernetes/gcp "
            "for multi-host gangs",
    }

    def unsupported_features_for_resources(
            self, resources) -> Dict[CloudImplementationFeatures, str]:
        del resources
        return dict(self._UNSUPPORTED)

    def check_credentials(self) -> Tuple[bool, str]:
        if shutil.which("docker") is None:
            return False, "docker CLI not installed"
        try:
            proc = subprocess.run(["docker", "info", "--format",
                                   "{{.ServerVersion}}"],
                                  capture_output=True, text=True,
                                  timeout=20)
            if proc.returncode != 0:
                return False, ("docker daemon unreachable: "
                               f"{proc.stderr.strip()[:120]}")
            return True, f"daemon {proc.stdout.strip()}"
        except (subprocess.SubprocessError, OSError) as e:
            return False, f"docker probe failed: {e}"
