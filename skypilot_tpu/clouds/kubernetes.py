"""Kubernetes capability object.

Reference analog: sky/clouds/kubernetes.py. The rules that matter here:

  * pods cannot be stopped — deletion is the only lifecycle exit, so
    `stop` and autostop-to-STOPPED are unsupported for EVERY resource
    (autostop --down still works: the daemon terminates);
  * no spot market — preemption exists (node drain) but there is no
    discounted tier to request;
  * `image_id` IS supported: it is the pod image;
  * placement is the cluster itself — no regions/zones, cost 0
    (on-prem/pre-paid hardware, like the reference prices kubernetes).
"""
from __future__ import annotations

import shutil
import subprocess
from typing import Dict, Tuple

from skypilot_tpu.clouds.cloud import Cloud, CloudImplementationFeatures


class Kubernetes(Cloud):
    NAME = "kubernetes"

    _UNSUPPORTED = {
        CloudImplementationFeatures.STOP:
            "kubernetes pods cannot be stopped, only deleted; use "
            "`down`",
        CloudImplementationFeatures.AUTOSTOP:
            "pods cannot stop; use autostop --down (terminate on idle)",
        CloudImplementationFeatures.SPOT_INSTANCE:
            "no spot market on kubernetes; use node-level preemption "
            "policies out of band",
        # OPEN_PORTS is supported: provision/kubernetes.py open_ports
        # manages a per-cluster NodePort Service on the head pod.
    }

    def unsupported_features_for_resources(
            self, resources) -> Dict[CloudImplementationFeatures, str]:
        del resources  # table is resource-independent: pods never stop
        return dict(self._UNSUPPORTED)

    def check_credentials(self) -> Tuple[bool, str]:
        """Usable = kubectl exists + a reachable current context."""
        if shutil.which("kubectl") is None:
            return False, "kubectl not installed"
        try:
            proc = subprocess.run(
                ["kubectl", "config", "current-context"],
                capture_output=True, text=True, timeout=20)
            if proc.returncode != 0 or not proc.stdout.strip():
                return False, "no current kubectl context"
            ctx = proc.stdout.strip()
            probe = subprocess.run(
                ["kubectl", "get", "--raw", "/version"],
                capture_output=True, text=True, timeout=20)
            if probe.returncode != 0:
                return False, (f"context {ctx!r} unreachable: "
                               f"{probe.stderr.strip()[:120]}")
            return True, f"context {ctx}"
        except (subprocess.SubprocessError, OSError) as e:
            return False, f"kubectl probe failed: {e}"
