"""Cloud capability objects: what each provider can and cannot do.

Reference analog: sky/clouds/cloud.py (CloudImplementationFeatures:27,
Cloud:96, check_features_are_supported:524). The backend and optimizer ask
a Cloud object — never a provider module — whether an operation is
possible for a given Resources, so capability rules (TPU pods cannot
stop, a provider without spot, ports unimplemented) live in exactly one
place and produce one error shape.
"""
from __future__ import annotations

import enum
from typing import Dict, Iterable, Tuple

from skypilot_tpu import exceptions


class CloudImplementationFeatures(enum.Enum):
    """Operations a cloud may or may not support for given resources.

    Mirrors the reference enum (sky/clouds/cloud.py:27), trimmed to the
    features this framework exposes.
    """
    STOP = "stop"                    # stop (preserve disk) vs terminate
    AUTOSTOP = "autostop"            # daemon-driven stop when idle
    MULTI_NODE = "multi_node"        # num_nodes > 1 (multi-slice)
    SPOT_INSTANCE = "spot_instance"
    STORAGE_MOUNTING = "storage_mounting"
    OPEN_PORTS = "open_ports"
    IMAGE_ID = "image_id"


def pod_stop_rules(resources, hint: str
                   ) -> Dict["CloudImplementationFeatures", str]:
    """The shared TPU-semantics rule: multi-host pod slices cannot be
    stopped (and therefore cannot autostop-to-STOPPED); they are
    terminate-only. Clouds whose multi-host clusters behave like pods
    merge this into their per-resource table."""
    sinfo = resources.slice_info() if resources is not None else None
    if sinfo is None or not sinfo.is_pod:
        return {}
    why = (f"multi-host slice {sinfo.accelerator} cannot be stopped, "
           f"only terminated. {hint}")
    return {CloudImplementationFeatures.STOP: why,
            CloudImplementationFeatures.AUTOSTOP: why}


class Cloud:
    """Base capability object; subclasses override the tables/hooks."""

    NAME = "abstract"

    # Features this cloud never supports, with human-readable reasons.
    _UNSUPPORTED: Dict[CloudImplementationFeatures, str] = {}

    def unsupported_features_for_resources(
            self, resources) -> Dict[CloudImplementationFeatures, str]:
        """Per-resource refinement: base table plus rules that depend on
        the concrete resources (e.g. pod slices cannot stop)."""
        del resources
        return dict(self._UNSUPPORTED)

    def check_features_are_supported(
            self, resources,
            requested: Iterable[CloudImplementationFeatures]) -> None:
        """Raise NotSupportedError if any requested feature is
        unsupported for these resources (reference:
        check_features_are_supported, sky/clouds/cloud.py:524)."""
        unsupported = self.unsupported_features_for_resources(resources)
        bad = {f: unsupported[f] for f in requested if f in unsupported}
        if bad:
            reasons = "; ".join(
                f"{f.value}: {why}" for f, why in bad.items())
            raise exceptions.NotSupportedError(
                f"{self.NAME}: requested feature(s) not supported — "
                f"{reasons}")

    def supports(self, resources,
                 feature: CloudImplementationFeatures) -> bool:
        return feature not in self.unsupported_features_for_resources(
            resources)

    def check_credentials(self) -> Tuple[bool, str]:
        """(usable, reason) — the `stpu check --clouds` probe."""
        return True, ""

    def __repr__(self) -> str:
        return self.NAME
