"""Cloud registry: name -> capability object.

Reference analog: sky/clouds/cloud_registry.py. The backend, optimizer,
and `stpu check --clouds` resolve providers through here; adding a cloud
registering one Cloud subclass (plus its provision module).
"""
from __future__ import annotations

from typing import Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.clouds.cloud import (  # noqa: F401 — public API
    Cloud, CloudImplementationFeatures)
from skypilot_tpu.clouds.docker import Docker
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.local import Local

CLOUD_REGISTRY: Dict[str, Cloud] = {
    Docker.NAME: Docker(),
    GCP.NAME: GCP(),
    Kubernetes.NAME: Kubernetes(),
    Local.NAME: Local(),
}


def get_cloud(name: str) -> Cloud:
    try:
        return CLOUD_REGISTRY[name]
    except KeyError:
        raise exceptions.SkyTpuError(
            f"Unknown cloud {name!r}; registered: "
            f"{sorted(CLOUD_REGISTRY)}") from None


def registered_names() -> List[str]:
    return sorted(CLOUD_REGISTRY)


def cloud_manages_ports(resources) -> bool:
    """Whether ``resources``'s cloud implements OPEN_PORTS — the one
    capability check both the serve replica launcher (inject the
    replica's serving port) and the controller bring-up (inject the LB
    port range) must agree on, so it lives here rather than in either.
    Unknown clouds answer False: never inject ports a provisioner
    can't open."""
    try:
        cloud = get_cloud(resources.provider_name)
    except Exception:  # noqa: BLE001 — unknown cloud: don't inject
        return False
    return (CloudImplementationFeatures.OPEN_PORTS
            not in cloud.unsupported_features_for_resources(resources))
