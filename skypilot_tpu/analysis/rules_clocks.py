"""Clock + tracing-span discipline (ported from tools/check_clocks.py).

``stpu-wallclock`` — ``time.time()`` in duration arithmetic. An NTP
step (or a VM migration's clock slew) mid-interval yields negative or
wildly wrong durations; intervals must come from ``perf_counter`` /
``monotonic``. Sites where wall clock is genuinely right (arithmetic
against a timestamp persisted by another process/boot) annotate
``# noqa: stpu-wallclock <reason>`` — the bespoke ``# wallclock:
intentional`` marker and the script-resident allowlist are gone.

``stpu-span-leak`` — every ``tracing.start_span()`` is either a
``with`` context expression or assigned to a name ``.end()``ed in the
same function. Records are written on end; an open span that is never
ended silently vanishes from the trace.
"""
from __future__ import annotations

import ast
import re
from typing import Iterable

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule

_WALLCLOCK_RE = re.compile(r"time\.time\(\)\s*-|-\s*time\.time\(\)")


@core.register
class WallclockRule(Rule):
    id = "stpu-wallclock"
    title = "time.time() in duration arithmetic"
    rationale = ("Durations measured with time.time() break under NTP "
                 "steps/clock slew; use time.perf_counter() or "
                 "time.monotonic(). Wall clock is for stamps.")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for lineno, line in enumerate(ctx.lines, start=1):
            if line.strip().startswith("#"):
                continue
            if _WALLCLOCK_RE.search(line):
                yield Finding(
                    ctx.rel, lineno, self.id,
                    "time.time() used in duration arithmetic — use "
                    "time.perf_counter()/time.monotonic(), or annotate "
                    "'# noqa: stpu-wallclock <reason>' if arithmetic "
                    "against a persisted wall stamp is intentional")


def _is_start_span_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and core.call_name(node) == "start_span")


def _span_closed(call: ast.Call, ctx: FileContext) -> bool:
    """True iff the start_span() call cannot leak an open span: it is a
    with-statement context expression, or its result is assigned to a
    name with a matching ``<name>.end(...)`` in the enclosing function
    (nested helpers like a shared finish() closure count)."""
    stmt = call
    while not isinstance(stmt, ast.stmt):
        stmt = ctx.parents[stmt]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if call is item.context_expr or any(
                    n is call for n in ast.walk(item.context_expr)):
                return True
        return False
    target = None
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name):
        target = stmt.targets[0].id
    elif isinstance(stmt, ast.AnnAssign) \
            and isinstance(stmt.target, ast.Name):
        target = stmt.target.id
    if target is None:
        return False  # bare/returned span: nobody owns the .end()
    scope = stmt
    while not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Module)):
        scope = ctx.parents[scope]
    for node in ast.walk(scope):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "end"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == target):
            return True
    return False


@core.register
class SpanLeakRule(Rule):
    id = "stpu-span-leak"
    title = "tracing span opened but never ended"
    rationale = ("Span records are written on end(); an un-ended "
                 "start_span() silently drops the hop from the trace. "
                 "Known-after-the-fact phases use record_span.")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        for node in ctx.nodes:
            if _is_start_span_call(node) and not _span_closed(node, ctx):
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    "start_span() result is never ended (use `with`, "
                    "or assign it and call .end() in the same "
                    "function; for known-after-the-fact phases use "
                    "record_span)")
