"""``stpu-collective`` — no hand-rolled collectives in the serving
stack (ported from tools/check_collectives.py).

Serving code expresses parallelism through ``parallel/mesh.py``
(ShardingRules resolving logical axes onto a named mesh; XLA's SPMD
partitioner inserts the collectives). A raw ``lax.psum`` /
``all_gather`` / ``ppermute`` in ``skypilot_tpu/serve`` hard-codes a
mesh axis name into request-path code, breaks the moment the topology
block changes shape, and silently decouples the engine from the
single-process path the bit-parity tests compare against. A site that
genuinely must issue one annotates ``# noqa: stpu-collective
<reason>``.
"""
from __future__ import annotations

import ast
from typing import Iterable

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule

COLLECTIVES = frozenset({
    "psum", "psum_scatter", "pmean", "pmax", "pmin",
    "all_gather", "all_to_all", "ppermute", "pshuffle",
    "pbroadcast", "axis_index", "pdot",
})


@core.register
class CollectiveRule(Rule):
    id = "stpu-collective"
    title = "raw collective primitive in serve/"
    rationale = ("Collectives belong where the mesh is managed "
                 "(parallel/); in serve/ they hard-code axis names "
                 "into request-path code and break on topology "
                 "changes.")

    def targets(self, rel: str) -> bool:
        return (rel.startswith("skypilot_tpu/serve/")
                or rel.startswith("serve/"))

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        # A bare Name only counts when it was imported as a collective
        # (e.g. `from jax.lax import psum`); local variables that
        # happen to share a name are fine — attribute access (lax.psum)
        # is always flagged.
        imported = set()
        for node in ctx.nodes:
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    name = alias.asname or alias.name
                    if name in COLLECTIVES:
                        imported.add(name)
        for node in ctx.nodes:
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
                if name not in imported:
                    continue
            else:
                continue
            if name not in COLLECTIVES:
                continue
            yield Finding(
                ctx.rel, node.lineno, self.id,
                f"collective `{name}` in serve/ — express parallelism "
                "through parallel/mesh.py ShardingRules (XLA inserts "
                "the collectives); annotate '# noqa: stpu-collective "
                "<reason>' if a raw collective is truly unavoidable")
