"""`stpu check` — the unified static-analysis framework.

One AST parse per file feeds every registered rule (no more four
scripts re-walking the tree), one suppression grammar
(``# noqa: stpu-<rule> <mandatory reason>``), one report format
(``file:line:rule-id: message`` or ``--json``).

Rules live in ``rules_*.py`` modules and self-register on import:

  * ``stpu-wallclock``   — time.time() in duration arithmetic
  * ``stpu-span-leak``   — tracing.start_span() never ended
  * ``stpu-except``      — except Exception: pass in the control plane
  * ``stpu-atomic``      — bare durable writes in crash-critical files
  * ``stpu-collective``  — raw collectives in serve/
  * ``stpu-donation``    — use-after-donate on jitted entry points
  * ``stpu-host-sync``   — device syncs on the decode hot path
  * ``stpu-env``         — STPU_* env reads vs utils/env_contract.py
  * ``stpu-armed-guard`` — unguarded observability calls on hot paths

Entry points: ``stpu check`` (cli.py), ``python tools/check_*.py``
(thin shims), and ``tests/test_static_analysis.py`` (tier-1).
See docs/static-analysis.md for the rule catalog and how to add one.
"""
from skypilot_tpu.analysis.core import (Finding, Rule, all_rules,
                                        get_rule, register, run_check)

# Importing the rule modules registers them (order = report order).
from skypilot_tpu.analysis import rules_clocks  # noqa: F401,E402
from skypilot_tpu.analysis import rules_excepts  # noqa: F401,E402
from skypilot_tpu.analysis import rules_atomic  # noqa: F401,E402
from skypilot_tpu.analysis import rules_collectives  # noqa: F401,E402
from skypilot_tpu.analysis import rules_donation  # noqa: F401,E402
from skypilot_tpu.analysis import rules_host_sync  # noqa: F401,E402
from skypilot_tpu.analysis import rules_env  # noqa: F401,E402
from skypilot_tpu.analysis import rules_armed  # noqa: F401,E402

__all__ = ["Finding", "Rule", "all_rules", "get_rule", "register",
           "run_check"]
