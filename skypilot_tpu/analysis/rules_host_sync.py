"""``stpu-host-sync`` — no implicit device syncs on the decode or
train hot paths.

Every ``.item()``, ``float(arr)``, ``np.asarray(arr)``, ``print(arr)``
or ``.block_until_ready()`` on a device array forces a device→host
round-trip that stalls EVERY slot in the continuous-batching engine,
not just the request that issued it — the decode loop is one thread
driving one shared cache, so one stray sync is a whole-replica
latency cliff. The engine's one sanctioned sync is the explicit
``jax.device_get`` on the sampled tokens (the tokens must reach the
host to be emitted); everything else stays on device.

Scope: ``serve/decode_engine.py``, ``serve/gang_replica.py``, and the
training loop — ``train/trainer.py`` plus the recipe loops
(``recipes/llama_lora.py``, ``recipes/mixtral_ep.py``,
``recipes/resnet_ddp.py``). A train loop that ``float()``s its loss
every step serializes host and device exactly like the decode engine
would; the sanctioned pattern there is the ONE-STEP-DELAYED fetch
(``trainer.DelayedFetch``): hold the device handle one iteration and
``jax.device_get`` it only after the next step is dispatched.

  * ``.item()`` and ``.block_until_ready()`` are flagged ANYWHERE in
    those files — they only exist on arrays and are never right on
    the serving path (benches that want a sync point live elsewhere).
  * ``float(...)``, ``np.asarray(...)`` / ``np.array(...)``, and
    ``print(...)`` are flagged inside HOT functions — the transitive
    same-module callers of the jitted entry points plus the gang
    mirror loops — and only when the argument is DEVICE-TAINTED: a
    value (transitively) produced by a jitted entry point or a
    ``jnp.``/``jax.`` call in the same function. ``jax.device_get``
    UN-taints (its result is a host array), so post-fetch host math
    never trips the rule, and neither do host scalars like an HTTP
    request's ``temperature``.
  * The function form ``jax.block_until_ready(...)`` is flagged like
    the method form — same sync, different spelling.

Two calls ARE sanctioned: ``stepstats.sampled_sync(...)``
(observability/stepstats.py) and its training twin
``trainstats.sampled_sync(...)`` (observability/trainstats.py) — the
step-telemetry subsystems' timed block_until_ready, fired every
STPU_STEPSTATS_SYNC_EVERY-th / STPU_TRAINSTATS_SYNC_EVERY-th step to
split dispatch vs device time. They are rate-limited by design and
the only approved way to put a sync on a hot path; anything else
must either use them or carry a noqa.

Training loops usually build their jitted step through a factory
(``step = trainer.make_train_step(...)``) rather than a local
``@jax.jit`` — those factory results are treated as jitted entry
points too (``_JIT_FACTORIES``), so the loop that calls them is hot.

Annotate a genuinely-required sync with
``# noqa: stpu-host-sync <reason>``.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule

TARGET_FILES = ("serve/decode_engine.py", "serve/gang_replica.py",
                "train/trainer.py", "recipes/llama_lora.py",
                "recipes/mixtral_ep.py", "recipes/resnet_ddp.py")

# Per-token mirror/broadcast loops that never call a jitted name
# directly (the engine is driven through objects), but sit on the
# admission path of every gang request.
EXTRA_HOT_ROOTS = {"follower_serve", "broadcast_generate",
                   "_serve_member", "_drain_request"}

# Flagged anywhere in the target files.
_ALWAYS_SYNC_ATTRS = {"item", "block_until_ready"}
# BARE-name function-form sync (`from jax import block_until_ready`);
# the dotted `jax.block_until_ready(...)` spelling is already caught
# by the attribute branch below (_ALWAYS_SYNC_ATTRS).
_ALWAYS_SYNC_CALLS = {"block_until_ready"}
# THE sanctioned sync seams (module docstring): the step-telemetry
# sampled dispatch/device splits. Never flagged.
_SANCTIONED_CALLS = {"stepstats.sampled_sync",
                     "trainstats.sampled_sync", "sampled_sync"}
_NP_MODULES = {"np", "numpy", "onp"}
_NP_FUNCS = {"asarray", "array"}
_DEVICE_MODULES = ("jnp.", "jax.")
_UNTAINT_CALLS = {"jax.device_get", "device_get"}
# Factories whose RESULT is a jitted callable: `step =
# trainer.make_train_step(...)` makes `step(...)` a jitted entry
# point even though no local def carries @jax.jit.
_JIT_FACTORIES = {"trainer.make_train_step", "make_train_step"}


def _jitted_names(ctx: FileContext) -> Set[str]:
    """Module-level names bound to jitted callables."""
    names: Set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    dec_name = core.dotted_path(dec.func)
                    if dec_name in ("functools.partial", "partial") \
                            and dec.args and core.dotted_path(
                                dec.args[0]) in ("jax.jit", "jit"):
                        names.add(node.name)
                    elif dec_name in ("jax.jit", "jit"):
                        names.add(node.name)
                elif core.dotted_path(dec) in ("jax.jit", "jit"):
                    names.add(node.name)
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and core.dotted_path(node.value.func) in (
                    "jax.jit", "jit", *_JIT_FACTORIES):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _function_index(ctx: FileContext) -> Dict[str, ast.AST]:
    """name -> def node, for module functions AND methods (methods are
    keyed by bare name: the call graph treats `self.f()` and `f()`
    alike, which is exact enough for a two-file rule)."""
    index: Dict[str, ast.AST] = {}
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            index.setdefault(node.name, node)
    return index


def _callees(fn: ast.AST) -> Set[str]:
    """Bare names this function calls (f(), self.f(), obj.f())."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = core.call_name(node)
            if name:
                out.add(name)
    return out


def _hot_functions(ctx: FileContext) -> Set[str]:
    """Transitive closure of functions that reach a jitted call, plus
    the configured mirror-loop roots and everything THEY call."""
    jitted = _jitted_names(ctx)
    index = _function_index(ctx)
    callees = {name: _callees(fn) for name, fn in index.items()}

    # Upward closure: anything that (transitively) calls a jitted name.
    hot: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, called in callees.items():
            if name in hot:
                continue
            if called & jitted or called & hot:
                hot.add(name)
                changed = True

    # Downward closure from the hot set + extra roots: a helper CALLED
    # from the per-token path stalls it just the same.
    hot |= EXTRA_HOT_ROOTS & set(index)
    frontier = list(hot)
    while frontier:
        name = frontier.pop()
        for callee in callees.get(name, ()):
            if callee in index and callee not in hot:
                hot.add(callee)
                frontier.append(callee)
    return hot


def _is_device_producer(call: ast.Call, jitted: Set[str]) -> bool:
    """Call whose result lives on device: a jitted entry point or a
    jnp./jax. API call (minus the explicit D2H fetch)."""
    path = core.dotted_path(call.func)
    if path is None:
        return False
    if path in _UNTAINT_CALLS:
        return False
    if path in jitted:
        return True
    return path.startswith(_DEVICE_MODULES)


def _references_taint(node: ast.AST, taint: Set[str],
                      jitted: Set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in taint:
            return True
        if isinstance(n, ast.Call) and _is_device_producer(n, jitted):
            return True
    return False


def _ordered_statements(fn: ast.AST) -> List[ast.stmt]:
    """All statements under fn in source order (nested defs included —
    closures run on the same thread)."""
    out: List[ast.stmt] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                out.append(child)
            visit(child)

    visit(fn)
    return out


class _FnScan:
    """One ordered pass over a hot function: track device taint
    through assignments, collect sync findings."""

    def __init__(self, rule: "HostSyncRule", ctx: FileContext,
                 fn: ast.AST, jitted: Set[str]):
        self.rule = rule
        self.ctx = ctx
        self.jitted = jitted
        self.taint: Set[str] = set()
        self.findings: List[Finding] = []
        for stmt in _ordered_statements(fn):
            self._scan_stmt(stmt)

    def _scan_stmt(self, stmt: ast.stmt) -> None:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets, value = [stmt.target], stmt.value
        if value is not None:
            # device_get at the top of the RHS is the sanctioned
            # fetch: its result is HOST memory.
            untaints = (isinstance(value, ast.Call)
                        and core.dotted_path(value.func)
                        in _UNTAINT_CALLS)
            tainted = (not untaints and
                       _references_taint(value, self.taint,
                                         self.jitted))
            for t in targets:
                stack = [t]
                while stack:
                    n = stack.pop()
                    if isinstance(n, (ast.Tuple, ast.List)):
                        stack.extend(n.elts)
                    elif isinstance(n, ast.Name):
                        if tainted:
                            self.taint.add(n.id)
                        else:
                            self.taint.discard(n.id)
        # Sync patterns in THIS statement's expressions (nested
        # statements get their own visit from the ordered walk).
        stack = [c for c in ast.iter_child_nodes(stmt)
                 if not isinstance(c, ast.stmt)]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                self._scan_call(node)
            stack.extend(c for c in ast.iter_child_nodes(node)
                         if not isinstance(c, ast.stmt))

    def _scan_call(self, node: ast.Call) -> None:
        func_path = core.dotted_path(node.func)
        hit = None
        if isinstance(node.func, ast.Name) and node.func.id == "float":
            hit = ("float(...)", "concretizes its argument (a D2H "
                   "sync for a device array)")
        elif func_path is not None and "." in func_path \
                and func_path.split(".", 1)[0] in _NP_MODULES \
                and func_path.rsplit(".", 1)[-1] in _NP_FUNCS:
            hit = (f"{func_path}(...)", "copies device memory to host")
        elif isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            hit = ("print(...)", "blocks on its arguments (a D2H sync "
                   "for device arrays) and serializes the loop on "
                   "stdout")
        if hit is None:
            return
        if not any(_references_taint(a, self.taint, self.jitted)
                   for a in (*node.args,
                             *(kw.value for kw in node.keywords))):
            return
        self.findings.append(Finding(
            self.ctx.rel, node.lineno, self.rule.id,
            f"{hit[0]} of a device value on the decode hot path "
            f"{hit[1]} — every slot on the replica stalls; keep it on "
            "device or hoist it off the per-token loop (annotate "
            "'# noqa: stpu-host-sync <reason>' for a sanctioned "
            "sync)"))


@core.register
class HostSyncRule(Rule):
    id = "stpu-host-sync"
    title = "implicit device sync on the decode hot path"
    rationale = ("One D2H sync in the engine loop stalls every slot "
                 "on the replica; the decode path's only sanctioned "
                 "sync is the explicit device_get on sampled tokens.")

    def targets(self, rel: str) -> bool:
        # '/'-bounded: observe/decode_engine.py is not the engine.
        return any(rel == t or rel.endswith("/" + t)
                   for t in TARGET_FILES)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        jitted = _jitted_names(ctx)
        hot = _hot_functions(ctx)
        index = _function_index(ctx)

        # .item() / .block_until_ready(): wrong anywhere in these files
        # (method form), plus the jax.block_until_ready(...) function
        # form. stepstats.sampled_sync is the ONE sanctioned seam.
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            path = core.dotted_path(node.func)
            if path in _SANCTIONED_CALLS:
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _ALWAYS_SYNC_ATTRS:
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    f".{node.func.attr}() forces a device sync — on "
                    "the serving path it stalls every slot; keep the "
                    "value on device, or use the sanctioned sampled "
                    "seam stepstats.sampled_sync (or '# noqa: "
                    "stpu-host-sync <reason>' for a one-off sync "
                    "point)")
            elif path in _ALWAYS_SYNC_CALLS:
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    f"{path}(...) forces a device sync — on the "
                    "serving path it stalls every slot; the only "
                    "sanctioned sync seam is stepstats.sampled_sync "
                    "(or '# noqa: stpu-host-sync <reason>')")

        # Taint-tracked float/np.asarray/print inside hot functions.
        seen: Set[int] = set()
        for name in hot:
            fn = index.get(name)
            if fn is None or id(fn) in seen:
                continue
            seen.add(id(fn))
            for finding in _FnScan(self, ctx, fn, jitted).findings:
                yield finding
