"""``stpu-donation`` — use-after-donate on jitted entry points.

``donate_argnums``/``donate_argnames`` hands a buffer's storage to
XLA: after the call the caller's reference points at memory the
compiled program has already overwritten. On the CPU tier-1 mesh
donation is a silent no-op (XLA copies), so a use-after-donate passes
every test here and returns garbage the first time it runs on a real
TPU — the nastiest possible class of "works on my machine". This rule
makes the contract static:

  * **Caller side** — at every call to a donating jitted entry point,
    the donated argument (a name or a dotted path like
    ``self._cache``) must either be REBOUND from the call's return in
    the same statement (``logits, cache = step(..., cache)``) or go
    dead: any later read of the donated path in the enclosing function
    is use-after-donate. A donating call inside a loop that does not
    rebind is flagged outright — the next iteration reads the donated
    buffer.
  * **Callee side** — a donated parameter must (transitively) flow
    into the jitted function's return value. XLA only aliases a
    donated input to an OUTPUT; a donated param that reaches no output
    is silently un-donated (HBM double-buffers) — the exact trap the
    decode-cache plumbing documents.

Recognized donation sites: ``@functools.partial(jax.jit,
donate_argnums=...)`` decorators and ``jax.jit(fn_or_lambda,
donate_argnums=...)`` calls (including ``name = jax.jit(...)``
bindings, whose call sites are then tracked by name). Resolution is
per-module — cross-module donation flows are out of scope.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule

_JIT_NAMES = {"jax.jit", "jit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


@dataclasses.dataclass
class _Donator:
    """One jitted callable with donated args."""
    name: Optional[str]          # call-site name, if bound to one
    params: List[str]            # positional parameter names
    donated: List[int]           # positional indices into params
    donated_names: List[str]     # donate_argnames entries
    fn_node: Optional[ast.AST]   # FunctionDef or Lambda for alias check
    lineno: int

    def donated_params(self) -> List[str]:
        out = [self.params[i] for i in self.donated
               if i < len(self.params)]
        out.extend(n for n in self.donated_names if n in self.params)
        return out


def _const_indices(node: ast.AST) -> List[int]:
    """(1, 2) / [1] / 1 -> [1, 2] / [1] / [1]."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _const_names(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _positional_params(args: ast.arguments) -> List[str]:
    return [a.arg for a in (*args.posonlyargs, *args.args)]


def _donate_kwargs(call: ast.Call):
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = _const_indices(kw.value)
        elif kw.arg == "donate_argnames":
            names = _const_names(kw.value)
    return nums, names


def _collect_donators(ctx: FileContext) -> List[_Donator]:
    """Every donating jitted callable defined in this module."""
    # Module functions by name, for `jax.jit(step, ...)` resolution.
    fn_by_name: Dict[str, ast.AST] = {}
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_by_name.setdefault(node.name, node)

    donators: List[_Donator] = []
    for node in ctx.nodes:
        # Decorated defs: @functools.partial(jax.jit, donate_argnums=..)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                dec_name = core.dotted_path(dec.func)
                if dec_name not in _PARTIAL_NAMES:
                    continue
                if not (dec.args and core.dotted_path(dec.args[0])
                        in _JIT_NAMES):
                    continue
                nums, names = _donate_kwargs(dec)
                if nums or names:
                    donators.append(_Donator(
                        node.name, _positional_params(node.args),
                        nums, names, node, node.lineno))
        # jax.jit(fn_or_lambda, donate_argnums=...) calls.
        if isinstance(node, ast.Call) \
                and core.dotted_path(node.func) in _JIT_NAMES:
            nums, names = _donate_kwargs(node)
            if not (nums or names) or not node.args:
                continue
            wrapped = node.args[0]
            fn_node: Optional[ast.AST] = None
            params: List[str] = []
            if isinstance(wrapped, ast.Lambda):
                fn_node = wrapped
                params = _positional_params(wrapped.args)
            elif isinstance(wrapped, ast.Name):
                fn_node = fn_by_name.get(wrapped.id)
                if fn_node is not None:
                    params = _positional_params(fn_node.args)
            # Bind to a call-site name when the jit result is assigned.
            bound = None
            stmt = ctx.parents.get(node)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.value is node:
                bound = stmt.targets[0].id
            donators.append(_Donator(bound, params, nums, names,
                                     fn_node, node.lineno))
    return donators


# ------------------------------------------------------ callee side
def _aliases_output(fn_node: ast.AST, param: str) -> bool:
    """Does the donated param (transitively) reach a return value?"""
    if isinstance(fn_node, ast.Lambda):
        return any(isinstance(n, ast.Name) and n.id == param
                   for n in ast.walk(fn_node.body))
    taint: Set[str] = {param}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn_node):
            targets: List[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            if value is None:
                continue
            if not any(isinstance(n, ast.Name) and n.id in taint
                       for n in ast.walk(value)):
                continue
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id not in taint:
                        taint.add(n.id)
                        changed = True
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Return) and node.value is not None:
            if any(isinstance(n, ast.Name) and n.id in taint
                   for n in ast.walk(node.value)):
                return True
    return False


# ------------------------------------------------------ caller side
def _stmt_of(node: ast.AST, ctx: FileContext) -> ast.stmt:
    cur = node
    while not isinstance(cur, ast.stmt):
        cur = ctx.parents[cur]
    return cur


def _flatten_targets(stmt: ast.stmt) -> List[ast.AST]:
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        raw = stmt.targets
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        raw = [stmt.target]
    else:
        return targets
    stack = list(raw)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        else:
            targets.append(t)
    return targets


def _enclosing_scope(node: ast.AST, ctx: FileContext) -> ast.AST:
    scope = ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.Module)
    return scope if scope is not None else ctx.tree


def _enclosing_loop(node: ast.AST, ctx: FileContext
                    ) -> Optional[ast.AST]:
    """Nearest For/While ancestor INSIDE the same function scope."""
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        cur = ctx.parents.get(cur)
    return None


def _stored_in_loop_before(loop: ast.AST, stmt: ast.stmt, path: str
                           ) -> bool:
    """Is ``path`` freshly stored inside the loop body, textually
    before the donating statement? Then each iteration donates a new
    buffer (``cache = init_cache(b); step(b, cache)``) and the
    back-edge read is of a fresh value, not the donated one."""
    excluded = set(id(n) for n in ast.walk(stmt))
    for node in ast.walk(loop):
        if id(node) in excluded:
            continue
        if not isinstance(node, (ast.Name, ast.Attribute)):
            continue
        if not isinstance(getattr(node, "ctx", None), ast.Store):
            continue
        if core.dotted_path(node) != path:
            continue
        if node.lineno < stmt.lineno:
            return True
    return False


def _first_event_after(scope: ast.AST, stmt: ast.stmt, path: str,
                       ctx: FileContext):
    """First (Load|Store) of ``path`` textually after ``stmt`` in
    ``scope``. Returns (kind, lineno) or None."""
    excluded = set(id(n) for n in ast.walk(stmt))
    end = getattr(stmt, "end_lineno", stmt.lineno)
    events = []
    for node in ast.walk(scope):
        if id(node) in excluded:
            continue
        if not isinstance(node, (ast.Name, ast.Attribute)):
            continue
        if core.dotted_path(node) != path:
            continue
        kind = ("store" if isinstance(getattr(node, "ctx", None),
                                      (ast.Store, ast.Del))
                else "load")
        events.append((node.lineno, node.col_offset, kind))
    events.sort()
    for lineno, _col, kind in events:
        if lineno > end:
            return kind, lineno
    return None


@core.register
class DonationRule(Rule):
    id = "stpu-donation"
    title = "use-after-donate / donated input aliasing no output"
    rationale = ("Donated buffers are invalid after the call on real "
                 "TPUs (the CPU tier-1 mesh silently copies); donated "
                 "args must be rebound from the return or go dead, "
                 "and donated params must alias an output.")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        donators = _collect_donators(ctx)

        # Callee side: donated param must reach a return.
        for d in donators:
            if d.fn_node is None:
                continue
            for param in d.donated_params():
                if not _aliases_output(d.fn_node, param):
                    label = d.name or "<lambda>"
                    yield Finding(
                        ctx.rel, d.lineno, self.id,
                        f"donated parameter `{param}` of `{label}` "
                        "aliases no output — XLA only donates an "
                        "input that aliases an output; return the "
                        "updated buffer or drop the donation")

        # Caller side: track calls to named donators.
        by_name = {d.name: d for d in donators if d.name}
        if not by_name:
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name):
                continue
            d = by_name.get(node.func.id)
            if d is None:
                continue
            donated_args: List[ast.AST] = [
                node.args[i] for i in d.donated if i < len(node.args)]
            for kw in node.keywords:
                if kw.arg in d.donated_names:
                    donated_args.append(kw.value)
            stmt = _stmt_of(node, ctx)
            target_paths = {core.dotted_path(t)
                            for t in _flatten_targets(stmt)}
            for arg in donated_args:
                path = core.dotted_path(arg)
                if path is None:
                    continue  # a temporary: nothing outlives the call
                if path in target_paths:
                    continue  # rebound from the return — the contract
                loop = _enclosing_loop(node, ctx)
                if loop is not None and not _stored_in_loop_before(
                        loop, stmt, path):
                    yield Finding(
                        ctx.rel, node.lineno, self.id,
                        f"`{path}` is donated to `{d.name}` inside a "
                        "loop without being rebound from the return — "
                        "the next iteration reads a donated buffer")
                    continue
                scope = _enclosing_scope(node, ctx)
                event = _first_event_after(scope, stmt, path, ctx)
                if event is not None and event[0] == "load":
                    yield Finding(
                        ctx.rel, event[1], self.id,
                        f"`{path}` is read after being donated to "
                        f"`{d.name}` (line {node.lineno}) — rebind it "
                        "from the call's return or stop using it; on "
                        "TPU the buffer is already overwritten")
