"""``stpu-env`` — every STPU_* env read resolves through the contract.

~45 ``STPU_*`` knobs are read across orchestration layers (CLI, LB,
engine, gang driver, jobs controller, agent daemon). Before the
registry, nothing related a knob's name, default, and doc — the drift
failure mode where two call sites parse the same knob with different
defaults (the class of bug "Adaptive Orchestration" attributes config
incidents to). This rule makes ``utils/env_contract.py`` load-bearing:

  * an ``os.environ.get``/``os.getenv``/``os.environ[...]`` read of an
    ``STPU_*`` name that is NOT in the registry is a violation — new
    knobs must be declared (default + doc) before first read;
  * a read whose inline default LITERAL disagrees with the registered
    default is a violation — one knob, one default, everywhere.

Names are resolved statically: string literals, module constants
(``ENABLE_ENV = "STPU_TRACE"`` — same file first, then a cross-file
table built in ``prepare()`` for dotted reads like ``tracing.ENV_CTX``;
ambiguous bare names never resolve cross-file). Dynamic defaults
(``str(10 * 1024 * 1024)``) can't be compared statically and are
skipped — the registry still pins the canonical value for the doc
table. Env WRITES (``os.environ[...] = ...``, ``.pop``) are stamps,
not config reads, and are out of scope.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule
from skypilot_tpu.utils import env_contract

_GET_CALLS = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
_ENVIRON = {"os.environ", "environ"}


def _local_constants(ctx: FileContext) -> Dict[str, str]:
    """NAME -> 'STPU_*' for constant string assignments in this file."""
    out: Dict[str, str] = {}
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and node.value.value.startswith(env_contract.PREFIX):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


class _EnvRead:
    """One detected env read: the name expression + optional default."""

    def __init__(self, node: ast.AST, name_expr: ast.AST,
                 default: Optional[ast.AST], has_default: bool):
        self.node = node
        self.name_expr = name_expr
        self.default = default
        self.has_default = has_default


def _env_reads(ctx: FileContext) -> Iterable[_EnvRead]:
    for node in ctx.nodes:
        if isinstance(node, ast.Call):
            path = core.dotted_path(node.func)
            if path in _GET_CALLS and node.args:
                default = node.args[1] if len(node.args) > 1 else None
                for kw in node.keywords:
                    if kw.arg == "default":
                        default = kw.value
                yield _EnvRead(node, node.args[0], default,
                               default is not None)
        elif isinstance(node, ast.Subscript) \
                and isinstance(getattr(node, "ctx", None), ast.Load) \
                and core.dotted_path(node.value) in _ENVIRON:
            yield _EnvRead(node, node.slice, None, False)


@core.register
class EnvContractRule(Rule):
    id = "stpu-env"
    title = "STPU_* env read outside utils/env_contract.py"
    rationale = ("Unregistered knobs and per-site default literals are "
                 "how two orchestration layers end up parsing the same "
                 "env var differently; every STPU_* read must resolve "
                 "through the central registry's name + default.")

    def __init__(self) -> None:
        # Cross-file constant table: bare NAME -> set of STPU_* values
        # it is bound to anywhere in the scanned tree. Only UNAMBIGUOUS
        # names (one value) resolve for dotted reads.
        self._cross: Dict[str, Set[str]] = {}

    def prepare(self, contexts: Sequence[FileContext]) -> None:
        self._cross = {}
        for ctx in contexts:
            for name, value in _local_constants(ctx).items():
                self._cross.setdefault(name, set()).add(value)

    def _resolve(self, expr: ast.AST,
                 local: Dict[str, str]) -> Optional[str]:
        if isinstance(expr, ast.Constant) \
                and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            if expr.id in local:
                return local[expr.id]
            values = self._cross.get(expr.id, set())
            return next(iter(values)) if len(values) == 1 else None
        if isinstance(expr, ast.Attribute):
            values = self._cross.get(expr.attr, set())
            return next(iter(values)) if len(values) == 1 else None
        return None

    @staticmethod
    def _default_literal(read: _EnvRead
                         ) -> Tuple[bool, Optional[str]]:
        """(comparable, normalized default). Only an INLINE constant
        default can disagree with the registry: a presence-style read
        with no default (``if os.environ.get("STPU_X"):``) and a
        dynamic default expression are both out of scope."""
        if not read.has_default:
            return False, None
        if isinstance(read.default, ast.Constant):
            value = read.default.value
            return True, None if value is None else str(value)
        return False, None

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        local = _local_constants(ctx)
        for read in _env_reads(ctx):
            name = self._resolve(read.name_expr, local)
            if name is None or not name.startswith(env_contract.PREFIX):
                continue
            knob = env_contract.REGISTRY.get(name)
            if knob is None:
                yield Finding(
                    ctx.rel, read.node.lineno, self.id,
                    f"`{name}` is read but not registered in "
                    "utils/env_contract.py — declare the knob "
                    "(default + one-line doc) before reading it")
                continue
            comparable, default = self._default_literal(read)
            if comparable and default != knob.default:
                yield Finding(
                    ctx.rel, read.node.lineno, self.id,
                    f"`{name}` read with default {default!r} but "
                    f"env_contract.py registers {knob.default!r} — "
                    "one knob, one default (fix the site or the "
                    "registry)")
