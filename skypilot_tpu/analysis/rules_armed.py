"""``stpu-armed-guard`` — observability call sites on serving/training
hot paths must be disarm-free.

Every observability subsystem in this repo follows the same contract:
a module-level ``ENABLED`` flag that is ``False`` by default, armed
via one env knob, and a hot-path discipline of *one flag load and a
falsy branch* when disarmed (pinned by the monkeypatch-bomb tests).
That contract only holds if every call from a hot module into
``tracing`` / ``stepstats`` / ``trainstats`` / ``fault_injection`` /
``reqlog`` sits under the subsystem's flag — an unguarded
``stepstats.record(...)`` costs dict building and a lock on every
step even when nobody asked for telemetry, and an unguarded
``fault_injection.fire(...)`` re-reads its plan on the per-token
path.

A call into one of those modules is compliant when ANY of:

  * it sits (lexically) under an ``if``/``elif`` whose test references
    ``<mod>.ENABLED`` — compound tests count
    (``if reqlog.ENABLED and stats.get("reqlog") is not None:``), as
    does a local alias bound from the flag
    (``armed = stepstats.ENABLED`` ... ``if armed:``) and a call in
    the test itself AFTER the short-circuiting flag check
    (``if trainstats.ENABLED and trainstats.sync_due():``);
  * it lives in an armed-only helper: a same-file function whose
    EVERY call site is itself guarded (the engine's
    ``_record_step`` / ``_stamp_dispatch`` / ``_record_admission``
    pattern — "only reached while stepstats.ENABLED, the callers
    guard"). The closure is computed per file, to a fixpoint, so a
    guarded helper calling another helper stays compliant;
  * the callee is a documented NOOP-returning / pure helper that is
    safe disarmed (``_SANCTIONED``): the tracing context plumbing
    (``extract`` / ``format_ctx`` / ``parse_ctx`` / ``child_env`` /
    ``SpanContext`` are pure; ``start_span`` / ``record_span`` return
    no-ops when disarmed), the crash-path flight dumps
    (``dump_flight`` runs once at teardown, never per-token), and the
    operator-requested admin reads (``snapshot``, ``reqlog.read`` /
    ``requests_path`` and the profile capture trio serve explicit
    ``/perf`` / ``/requests`` / ``/profile`` requests, not the
    decode loop).

Anything else is a finding. A genuinely-exempt site carries
``# noqa: stpu-armed-guard <reason>``.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule

# The serving + training hot modules (same '/'-bounded match as
# stpu-host-sync). Cold control-plane code may call the subsystems
# unguarded — one dict build per launch is noise; per-token is not.
TARGET_FILES = ("serve/decode_engine.py", "serve/load_balancer.py",
                "serve/gang_replica.py", "recipes/serve_llm.py",
                "train/trainer.py", "train/checkpoint.py",
                "recipes/llama_lora.py", "recipes/mixtral_ep.py",
                "recipes/resnet_ddp.py")

# The flag-gated observability subsystems this rule polices.
MODULES = ("tracing", "stepstats", "trainstats", "fault_injection",
           "reqlog")

# Documented safe-when-disarmed callees (module docstring has the
# per-entry rationale). Everything here either returns a no-op /
# pure value with the flag down, or only runs on a crash/teardown or
# operator-requested admin path.
_SANCTIONED = {
    "tracing.start_span", "tracing.record_span", "tracing.extract",
    "tracing.format_ctx", "tracing.parse_ctx", "tracing.child_env",
    "tracing.SpanContext",
    "stepstats.dump_flight", "trainstats.dump_flight",
    "stepstats.snapshot", "trainstats.snapshot",
    "stepstats.begin_profile", "stepstats.capture_profile",
    "stepstats.profiles_dir",
    "reqlog.read", "reqlog.requests_path",
}


def _call_module(node: ast.Call) -> Optional[str]:
    """The polices-this module a call targets, else None."""
    path = core.dotted_path(node.func)
    if path is None or "." not in path:
        return None
    head = path.split(".", 1)[0]
    return head if head in MODULES else None


def _flag_aliases(fn: Optional[ast.AST], mod: str) -> Set[str]:
    """Local names bound from ``<mod>.ENABLED`` inside fn (e.g.
    ``armed = stepstats.ENABLED`` or ``traced = tracing.ENABLED and
    ...``) — an ``if armed:`` over one of these IS a flag guard."""
    names: Set[str] = set()
    if fn is None:
        return names
    want = f"{mod}.ENABLED"
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        if any(core.dotted_path(n) == want
               for n in ast.walk(node.value)
               if isinstance(n, ast.Attribute)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _mentions_flag(test: ast.AST, mod: str, aliases: Set[str]) -> bool:
    want = f"{mod}.ENABLED"
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and core.dotted_path(n) == want:
            return True
        if isinstance(n, ast.Name) and n.id in aliases:
            return True
    return False


def _is_guarded(ctx: FileContext, node: ast.AST, mod: str,
                aliases: Set[str]) -> bool:
    """True when node sits under (or inside the test of) an if/elif
    that references the module's ENABLED flag."""
    prev: ast.AST = node
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.If, ast.IfExp)):
            in_test = prev is cur.test
            body = cur.body if isinstance(cur.body, list) else [cur.body]
            in_body = prev in body
            if (in_test or in_body) and _mentions_flag(
                    cur.test, mod, aliases):
                return True
            # The orelse of a flag check is the DISARMED branch —
            # keep walking, an outer guard may still apply.
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef)):
            # Guards don't cross a function boundary lexically; the
            # armed-only-helper closure handles that case.
            return False
        prev, cur = cur, ctx.parents.get(cur)
    return False


def _function_index(ctx: FileContext) -> Dict[str, ast.AST]:
    index: Dict[str, ast.AST] = {}
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            index.setdefault(node.name, node)
    return index


def _enclosing_function(ctx: FileContext,
                        node: ast.AST) -> Optional[ast.AST]:
    return ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)


def _armed_only(ctx: FileContext, mod: str,
                index: Dict[str, ast.AST]) -> Set[str]:
    """Fixpoint of same-file functions that are only ever called with
    the module's flag up: every call site is lexically guarded, or
    sits inside a function already in the set."""
    # name -> [(call node, enclosing fn name or None)]
    sites: Dict[str, List[Tuple[ast.Call, Optional[str]]]] = {}
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = core.call_name(node)
        if name not in index:
            continue
        fn = _enclosing_function(ctx, node)
        sites.setdefault(name, []).append(
            (node, fn.name if fn is not None else None))

    armed: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, calls in sites.items():
            if name in armed:
                continue
            ok = True
            for call, caller in calls:
                aliases = _flag_aliases(
                    index.get(caller) if caller else None, mod)
                if _is_guarded(ctx, call, mod, aliases):
                    continue
                if caller is not None and caller in armed:
                    continue
                ok = False
                break
            if ok:
                armed.add(name)
                changed = True
    return armed


@core.register
class ArmedGuardRule(Rule):
    id = "stpu-armed-guard"
    title = "unguarded observability call on a hot path"
    rationale = ("The zero-cost-when-disarmed contract (one flag "
                 "load, falsy branch) only holds if hot-path calls "
                 "into tracing/stepstats/trainstats/fault_injection/"
                 "reqlog sit under the subsystem's ENABLED flag or "
                 "are documented no-op helpers.")

    def targets(self, rel: str) -> bool:
        return any(rel == t or rel.endswith("/" + t)
                   for t in TARGET_FILES)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        index = _function_index(ctx)
        armed_cache: Dict[str, Set[str]] = {}
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            mod = _call_module(node)
            if mod is None:
                continue
            path = core.dotted_path(node.func)
            if path in _SANCTIONED:
                continue
            fn = _enclosing_function(ctx, node)
            aliases = _flag_aliases(fn, mod)
            if _is_guarded(ctx, node, mod, aliases):
                continue
            if mod not in armed_cache:
                armed_cache[mod] = _armed_only(ctx, mod, index)
            if fn is not None and fn.name in armed_cache[mod]:
                continue
            yield Finding(
                ctx.rel, node.lineno, self.id,
                f"{path}(...) on a hot path without a {mod}.ENABLED "
                "guard — disarmed requests pay for telemetry nobody "
                "asked for; guard the call site (compound tests "
                "count), move it into a helper whose callers all "
                "guard, or annotate '# noqa: stpu-armed-guard "
                "<reason>' for a documented no-op helper")
