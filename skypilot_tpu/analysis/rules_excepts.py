"""``stpu-except`` — swallowed exceptions in the control plane
(ported from tools/check_excepts.py).

``except Exception: pass`` in the serving / jobs / agent control
planes is how zombie states are born: a probe loop that eats its own
failure keeps a dead replica READY, a teardown that eats its failure
leaks a billing cluster, and nothing ever surfaces in logs or metrics.
Narrow catches with a recovery action are fine; catching EVERYTHING
and doing NOTHING is not. Genuinely-best-effort sites annotate
``# noqa: stpu-except <reason>``.
"""
from __future__ import annotations

import ast
from typing import Iterable

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule

TARGET_DIRS = ("skypilot_tpu/serve", "skypilot_tpu/agent",
               "skypilot_tpu/jobs")


def _swallows_everything(handler: ast.ExceptHandler) -> bool:
    if not (len(handler.body) == 1
            and isinstance(handler.body[0], ast.Pass)):
        return False
    if handler.type is None:
        return True
    return (isinstance(handler.type, ast.Name)
            and handler.type.id in ("Exception", "BaseException"))


@core.register
class ExceptRule(Rule):
    id = "stpu-except"
    title = "except [Exception]: pass in the control plane"
    rationale = ("A handler that catches everything and does nothing "
                 "turns failures into zombie states (dead-but-READY "
                 "replicas, leaked clusters) with no log/metric trail.")

    def targets(self, rel: str) -> bool:
        return any(rel.startswith(d + "/") or rel.startswith(
            d.split("/", 1)[-1] + "/") for d in TARGET_DIRS)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _swallows_everything(node):
                continue
            shown = ctx.line(node.lineno).strip() or "except: pass"
            yield Finding(
                ctx.rel, node.lineno, self.id,
                f"swallowed exception `{shown}` — handle it, narrow "
                "the catch, or annotate '# noqa: stpu-except "
                "<reason>' if it is genuinely best-effort")
