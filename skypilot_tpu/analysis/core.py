"""Shared static-analysis core: parse once, run every rule, one
suppression grammar, one report shape.

The framework owns everything the four pre-existing lint scripts each
reimplemented:

  * file discovery + a SINGLE ``ast.parse`` per file (a ``FileContext``
    carries the tree, a parent map, and a flattened node list that all
    rules share — adding a rule never adds another tree walk);
  * the unified suppression grammar::

        # noqa: stpu-<rule>[, stpu-<rule>...] <mandatory reason>

    A marker with no (or a too-short) reason does NOT suppress — the
    reason is the review artifact, exactly the check_excepts contract,
    now uniform across every rule;
  * reporting: ``file:line:rule-id: message`` text or a pinned JSON
    schema (``[{"path", "line", "rule", "message"}]``).

Rules subclass :class:`Rule` and register via :func:`register`. A rule
only sees files whose repo-relative path it claims via ``targets()``,
and returns raw findings — suppression is applied centrally.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_TARGET = REPO_ROOT / "skypilot_tpu"

# The unified suppression marker. The reason is MANDATORY and must be
# real prose (>= MIN_REASON_CHARS non-space chars): an unexplained
# exemption is how lint discipline rots.
NOQA_RE = re.compile(
    r"#\s*noqa:\s*(?P<rules>stpu-[a-z0-9-]+(?:\s*,\s*stpu-[a-z0-9-]+)*)"
    r"(?P<reason>[^#]*)")
MIN_REASON_CHARS = 8


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: where, which rule, what."""
    path: str       # relative to the scan root
    line: int
    rule: str       # e.g. "stpu-wallclock"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.rule}: {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"path": self.path, "line": self.line,
                "rule": self.rule, "message": self.message}


class _Noqa:
    """Per-line suppressions parsed once per file."""

    def __init__(self, lines: Sequence[str]):
        # line number -> (frozenset of rule ids, reason string)
        self.by_line: Dict[int, Tuple[frozenset, str]] = {}
        for lineno, line in enumerate(lines, start=1):
            m = NOQA_RE.search(line)
            if not m:
                continue
            rules = frozenset(
                r.strip() for r in m.group("rules").split(","))
            reason = m.group("reason").strip(" \t-—:")
            self.by_line[lineno] = (rules, reason)

    def status(self, lineno: int, rule: str) -> str:
        """'suppressed' | 'no-reason' (marker present, reason missing)
        | 'none'."""
        entry = self.by_line.get(lineno)
        if entry is None or rule not in entry[0]:
            return "none"
        if len(entry[1].replace(" ", "")) >= MIN_REASON_CHARS:
            return "suppressed"
        return "no-reason"


class FileContext:
    """Everything rules need about one file, computed exactly once."""

    def __init__(self, path: pathlib.Path, rel: str):
        self.path = path
        self.rel = rel
        try:
            self.text = path.read_text(errors="replace")
            self.error: Optional[str] = None
            self.error_line = 1
        except OSError as e:
            self.text = ""
            self.error = f"unreadable: {e}"
            self.error_line = 1
        self.lines: List[str] = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        try:
            self.tree = ast.parse(self.text)
        except SyntaxError as e:
            # Rules silently skip a tree-less file, so the failure MUST
            # surface as a finding — a lint gate that exits 0 on a file
            # it never inspected is worse than no gate.
            self.error = f"syntax error: {e.msg}"
            self.error_line = e.lineno or 1
        # One walk builds both the flat node list and the parent map
        # every rule shares.
        self.nodes: List[ast.AST] = []
        self.parents: Dict[ast.AST, ast.AST] = {}
        if self.tree is not None:
            stack: List[ast.AST] = [self.tree]
            while stack:
                node = stack.pop()
                self.nodes.append(node)
                for child in ast.iter_child_nodes(node):
                    self.parents[child] = node
                    stack.append(child)
        self.noqa = _Noqa(self.lines)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def enclosing(self, node: ast.AST, *kinds) -> Optional[ast.AST]:
        """Nearest ancestor of one of the given AST types."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = self.parents.get(cur)
        return None


class Rule:
    """Base class for one analyzer.

    Subclasses set ``id`` / ``title`` / ``rationale`` (the doc catalog
    pulls these), claim files via ``targets(rel)``, and yield raw
    ``Finding``s from ``check(ctx)``. ``prepare(contexts)`` runs once
    before any ``check`` for rules that need cross-file state (the env
    rule's constant table, for instance).
    """

    id: str = ""
    title: str = ""
    rationale: str = ""

    def targets(self, rel: str) -> bool:
        return rel.endswith(".py")

    def prepare(self, contexts: Sequence[FileContext]) -> None:
        pass

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate + register a rule by its id."""
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"{rule_cls.__name__} has no id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return rule_cls


def all_rules() -> List[Rule]:
    return list(_REGISTRY.values())


def get_rule(rule_id: str) -> Rule:
    if rule_id not in _REGISTRY:
        known = ", ".join(sorted(_REGISTRY))
        raise KeyError(f"unknown rule {rule_id!r} (known: {known})")
    return _REGISTRY[rule_id]


def _discover(paths: Sequence[pathlib.Path]) -> List[pathlib.Path]:
    files: List[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    # De-dup while preserving order (overlapping path args).
    seen, out = set(), []
    for f in files:
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


def run_check(paths: Optional[Sequence[pathlib.Path]] = None,
              rules: Optional[Sequence[str]] = None,
              root: Optional[pathlib.Path] = None,
              respect_targets: bool = True) -> List[Finding]:
    """Run ``rules`` (default: all) over ``paths`` (default: the repo's
    skypilot_tpu/ tree). Returns suppression-filtered findings sorted
    by (path, line, rule). ``root`` anchors the relative paths in the
    report (defaults to the repo root for in-repo scans, else the
    common parent of ``paths``). ``respect_targets=False`` runs the
    selected rules on every discovered file regardless of each rule's
    ``targets()`` claim — the tools/ shims use it to keep the
    historical lint-exactly-these-paths API."""
    if paths is None:
        paths = [DEFAULT_TARGET]
    paths = [pathlib.Path(p).resolve() for p in paths]
    if root is None:
        anchored = all(REPO_ROOT in p.parents or p == REPO_ROOT
                       for p in paths)
        root = REPO_ROOT if anchored else _common_root(paths)
    root = pathlib.Path(root).resolve()

    selected: List[Rule] = ([get_rule(r) for r in rules]
                            if rules is not None
                            else all_rules())

    contexts: List[FileContext] = []
    for f in _discover(paths):
        try:
            rel = str(f.resolve().relative_to(root))
        except ValueError:
            rel = str(f)
        # Parsing is the expensive step: skip files no selected rule
        # claims (e.g. `--rule stpu-atomic` parses 2 files, not ~100).
        # Untargeted files also skip the stpu-parse gate — a file no
        # rule would inspect can't mask a finding.
        if respect_targets and not any(r.targets(rel)
                                       for r in selected):
            continue
        contexts.append(FileContext(f, rel))

    for rule in selected:
        rule.prepare(contexts)

    findings: List[Finding] = []
    for ctx in contexts:
        if ctx.error is not None:
            # Core-level finding (rule id "stpu-parse"): no rule saw
            # this file, which must fail the gate, not pass it.
            findings.append(Finding(
                ctx.rel, ctx.error_line, "stpu-parse",
                f"{ctx.error} — no rule inspected this file"))
            continue
        for rule in selected:
            if respect_targets and not rule.targets(ctx.rel):
                continue
            for finding in rule.check(ctx):
                status = ctx.noqa.status(finding.line, finding.rule)
                if status == "suppressed":
                    continue
                if status == "no-reason":
                    finding = dataclasses.replace(
                        finding, message=finding.message +
                        f" (noqa: {finding.rule} present but the "
                        "reason is missing — reasons are mandatory)")
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _common_root(paths: Sequence[pathlib.Path]) -> pathlib.Path:
    parents = [p if p.is_dir() else p.parent for p in paths]
    common = parents[0]
    for p in parents[1:]:
        while common not in (p, *p.parents):
            common = common.parent
    return common


def render_text(findings: Sequence[Finding]) -> str:
    return "\n".join(f.render() for f in findings)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps([f.to_json() for f in findings], indent=2)


# --------------------------------------------------------- shared helpers
def dotted_path(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain of plain names, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def call_name(node: ast.Call) -> Optional[str]:
    """Trailing name of the called expression ('psum' for lax.psum)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None
