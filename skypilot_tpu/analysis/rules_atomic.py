"""``stpu-atomic`` — bare durable writes in crash-consistency-critical
files (ported from tools/check_atomic_writes.py).

The checkpoint/restore contract (train/checkpoint.py) and the managed-
jobs state layer (jobs/state.py) are exactly the files whose writes a
SIGKILL must never tear: a half-written checkpoint manifest or state
file silently poisons the resume path the whole preemption story rests
on. Every durable write must go through the atomic temp + fsync +
rename helper (``checkpoint.atomic_write_bytes``). The helper itself
(functions named ``atomic_write_bytes``) is exempt — someone has to
own the raw fd. Everything else annotates
``# noqa: stpu-atomic <reason>``.
"""
from __future__ import annotations

import ast
from typing import Iterable

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext, Finding, Rule

TARGET_FILES = ("skypilot_tpu/train/checkpoint.py",
                "skypilot_tpu/jobs/state.py")

# Functions that ARE the atomic protocol; their internals are the one
# sanctioned raw-write site.
HELPER_FUNCTIONS = {"atomic_write_bytes"}

_WRITE_OS_FLAGS = {"O_WRONLY", "O_RDWR", "O_CREAT", "O_APPEND",
                   "O_TRUNC"}


def _mode_of_open(call: ast.Call) -> str:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


def _os_flags(call: ast.Call) -> set:
    names = set()
    for node in ast.walk(call):
        if isinstance(node, ast.Attribute) and node.attr.startswith("O_"):
            names.add(node.attr)
        elif isinstance(node, ast.Name) and node.id.startswith("O_"):
            names.add(node.id)
    return names


def _violation_kind(node: ast.Call) -> str:
    """'' when fine, else a short description of the raw write."""
    func = node.func
    if isinstance(func, ast.Name) and func.id == "open":
        mode = _mode_of_open(node)
        if any(c in mode for c in "wax+"):
            return f"bare open(..., {mode!r})"
    elif isinstance(func, ast.Attribute):
        if func.attr == "open" and isinstance(func.value, ast.Name) \
                and func.value.id == "os":
            if _os_flags(node) & _WRITE_OS_FLAGS:
                return "raw os.open() with write flags"
        elif func.attr in ("write_text", "write_bytes"):
            return f".{func.attr}() durable write"
    return ""


@core.register
class AtomicWriteRule(Rule):
    id = "stpu-atomic"
    title = "non-atomic durable write in a crash-critical file"
    rationale = ("A SIGKILL mid-write tears bare open()/write_text() "
                 "output; durable state must go through "
                 "checkpoint.atomic_write_bytes (temp+fsync+rename).")

    def targets(self, rel: str) -> bool:
        # '/'-bounded suffix match: restrain/checkpoint.py must NOT
        # match train/checkpoint.py.
        suffixes = [t for full in TARGET_FILES
                    for t in (full, full.split("/", 1)[-1])]
        return any(rel == t or rel.endswith("/" + t) for t in suffixes)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            kind = _violation_kind(node)
            if not kind:
                continue
            helper = ctx.enclosing(node, ast.FunctionDef,
                                   ast.AsyncFunctionDef)
            while helper is not None and \
                    helper.name not in HELPER_FUNCTIONS:
                helper = ctx.enclosing(helper, ast.FunctionDef,
                                       ast.AsyncFunctionDef)
            if helper is not None:
                continue
            yield Finding(
                ctx.rel, node.lineno, self.id,
                f"{kind} — durable state writes must go through "
                "checkpoint.atomic_write_bytes (temp + fsync + "
                "rename), or carry '# noqa: stpu-atomic <reason>'")
