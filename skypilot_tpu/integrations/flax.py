"""jax/flax/optax train-step integration for `stpu bench`.

The tpu-native analog of the reference's keras/lightning callbacks
(sky/callbacks/sky_callback/integrations/keras.py:14): instead of a
framework callback object, a jitted-step decorator — the natural unit
of a jax training loop.

    step = wrap_train_step(make_train_step(...), total_steps=1000)
    for batch in loader:
        state, metrics = step(state, batch)

Timing notes: steps dispatch asynchronously, but the steady-state
seconds/step the recorder computes is still the true device rate —
dispatch backpressures once the device queue fills, so wall-clock
deltas between completed dispatches converge to device step time (the
same property the reference's non-blocking callbacks rely on). The
first (compile) step is excluded by the recorder.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

from skypilot_tpu import callbacks


def wrap_train_step(step_fn: Callable, *,
                    total_steps: Optional[int] = None) -> Callable:
    """Wrap a (jitted) train step so each invocation is one bench step.

    Arms the recorder on first call (callbacks.init is an env-gated
    no-op outside a benchmark run, so wrapping is always safe). Arming
    is skipped when a recorder is already live — wrapping a second
    function (an eval step, say) must not reset accumulated timings —
    and registers an exit flush so short runs (< the recorder's
    write_every) still land their summary without user code calling
    flush.
    """
    armed = []

    @functools.wraps(step_fn)
    def wrapped(*args, **kwargs):
        if not armed:
            if callbacks._state is None:  # noqa: SLF001 — arm once
                if callbacks.init(total_steps=total_steps):
                    import atexit
                    atexit.register(callbacks.flush)
            armed.append(True)
        callbacks.step_begin()
        out = step_fn(*args, **kwargs)
        callbacks.step_end()
        return out

    return wrapped
