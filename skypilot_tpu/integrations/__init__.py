"""Framework step-callback integrations for `stpu bench`.

Reference analog: sky/callbacks/sky_callback/integrations (keras.py:14,
pytorch_lightning.py:11, transformers.py:13) — drop-in callbacks that
let the benchmark harness time USER training code unchanged. The
TPU-native set differs by ecosystem: the first-class frameworks here
are jax/flax/optax loops and transformers Trainers (the torch path the
reference also covers); keras/lightning users can use the generic
`callbacks.step_iterator` directly.

    # any python loop:
    from skypilot_tpu import callbacks as sky_callback
    for batch in sky_callback.step_iterator(loader): ...

    # jax/flax/optax jitted step:
    from skypilot_tpu.integrations.flax import wrap_train_step
    train_step = wrap_train_step(train_step)

    # HF transformers Trainer:
    from skypilot_tpu.integrations.transformers import (
        SkyTransformersCallback)
    trainer = Trainer(..., callbacks=[SkyTransformersCallback()])

All integrations are no-ops unless the benchmark harness armed
``STPU_BENCHMARK_LOG_DIR`` (callbacks.init contract).
"""
