"""HF transformers Trainer integration for `stpu bench`.

Reference analog: sky/callbacks/sky_callback/integrations/
transformers.py:13 (SkyTransformersCallback wrapping TrainerCallback).
Add to any Trainer and `stpu bench` times the steps with the user's
training code unchanged:

    from skypilot_tpu.integrations.transformers import (
        SkyTransformersCallback)
    trainer = Trainer(model=..., args=...,
                      callbacks=[SkyTransformersCallback()])

No-op unless the benchmark harness exported STPU_BENCHMARK_LOG_DIR.
"""
from __future__ import annotations

from skypilot_tpu import callbacks

try:
    from transformers import TrainerCallback as _TrainerCallback
except ImportError:  # transformers not installed: degrade to a plain
    _TrainerCallback = object  # class so importing this module works


class SkyTransformersCallback(_TrainerCallback):
    """TrainerCallback bridging HF step events to the bench recorder."""

    def on_train_begin(self, args, state, control, **kwargs):
        total = getattr(state, "max_steps", None) or None
        callbacks.init(total_steps=total)

    def on_step_begin(self, args, state, control, **kwargs):
        callbacks.step_begin()

    def on_step_end(self, args, state, control, **kwargs):
        callbacks.step_end()

    def on_train_end(self, args, state, control, **kwargs):
        callbacks.flush()
