"""Deterministic synthetic datasets for the recipe tree.

Hermetic stand-ins for MNIST / IMDB / ImageNet / LM corpora: class structure
is real (learnable signal, held-out eval), generation is a pure function of
a seed, and no bytes leave the machine. The reference's recipes pull from
torchvision/HF hubs; a zero-egress TPU image cannot, and benchmark loops
shouldn't pay dataloader noise anyway.
"""
from __future__ import annotations

from typing import Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mnist_like(seed: int, n: int, image_size: int = 28,
               n_classes: int = 10, template_seed: int = 1234
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Images whose class is a fixed random template plus noise.

    Templates come from `template_seed` so train/eval splits (different
    `seed`) share the same class structure; linearly separable but noisy
    enough that a small CNN shows a real training curve.
    """
    templates = np.random.RandomState(template_seed).randn(
        n_classes, image_size, image_size)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=(n,))
    noise = rng.randn(n, image_size, image_size) * 1.5
    images = templates[labels] + noise
    return images[..., None].astype(np.float32), labels.astype(np.int32)


def imdb_like(seed: int, n: int, seq_len: int = 128,
              vocab_size: int = 1000) -> Tuple[np.ndarray, np.ndarray]:
    """Token sequences with sentiment-bearing tokens.

    Tokens [10, 30) lean positive, [30, 50) negative; the label is which
    group dominates. A pooled classifier must learn token identity ->
    sentiment, the same shape as bag-of-words IMDB.
    """
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 2, size=(n,)).astype(np.int32)
    tokens = rng.randint(50, vocab_size, size=(n, seq_len))
    n_signal = seq_len // 8
    for i in range(n):
        lo = 10 if labels[i] == 1 else 30
        pos = rng.choice(seq_len, size=n_signal, replace=False)
        tokens[i, pos] = rng.randint(lo, lo + 20, size=n_signal)
    return tokens.astype(np.int32), labels


def lm_tokens(seed: int, n_seqs: int, seq_len: int,
              vocab_size: int) -> np.ndarray:
    """Markov-ish token streams: next token correlates with the previous
    one, so a language model has a learnable (non-uniform) target."""
    rng = np.random.RandomState(seed)
    out = np.empty((n_seqs, seq_len), dtype=np.int32)
    cur = rng.randint(0, vocab_size, size=(n_seqs,))
    for t in range(seq_len):
        out[:, t] = cur
        jump = rng.random(n_seqs) < 0.15
        cur = np.where(jump, rng.randint(0, vocab_size, size=(n_seqs,)),
                       (cur * 31 + 7) % vocab_size)
    return out


def batches(arrays: Tuple[np.ndarray, ...], batch_size: int, seed: int,
            steps: int, skip: int = 0) -> Iterator[Tuple[np.ndarray, ...]]:
    """Infinite shuffled minibatch stream, sliced to `steps`.

    ``skip`` is the data-position half of checkpoint/resume: drawing
    and discarding the first ``skip`` index batches advances the RNG
    exactly as the original run did, so a run resumed at step k sees
    the SAME batch at step k+1 that an uninterrupted run would — the
    precondition for bit-identical resume (train/checkpoint.py).
    """
    n = arrays[0].shape[0]
    rng = np.random.RandomState(seed)
    for _ in range(skip):
        rng.randint(0, n, size=(batch_size,))
    for _ in range(steps):
        idx = rng.randint(0, n, size=(batch_size,))
        yield tuple(a[idx] for a in arrays)
