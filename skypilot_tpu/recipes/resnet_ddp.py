"""Multi-node data-parallel ResNet — the DDP benchmark named config.

Reference analog: examples/torch_ddp_benchmark/torch_ddp_benchmark.yaml
(resnet101 under torch DDP, wired by MASTER_ADDR/NODE_RANK env vars; its
published numbers are in BASELINE.md). Native version: a flax ResNet whose
gradient sync is an XLA psum over the global device mesh, bootstrapped from
the framework env contract via `train.distributed.initialize_from_env` —
the first real consumer of SKYPILOT_COORDINATOR_ADDR.

Sync paths, picked automatically:
  * federated (real multi-host TPU slice): one jit over the global mesh,
    per-process data via make_array_from_process_local_data; psum rides ICI.
  * non-federated multi-process (CPU local provider in tests): local jit +
    coordination-service KV mean-allreduce of gradients — still true
    synchronous DDP (all ranks average every step), just not an XLA
    collective.

    python -m skypilot_tpu.recipes.resnet_ddp --steps 30 --tiny
"""
from __future__ import annotations

import argparse
import hashlib
import json
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.observability import trainstats
from skypilot_tpu.recipes import synthetic_data
from skypilot_tpu.train import distributed


class ResNetBlock(nn.Module):
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (3, 3), strides=(self.strides,) * 2,
                    use_bias=False)(x)
        y = nn.GroupNorm(num_groups=8)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=8)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.strides,) * 2,
                               use_bias=False)(x)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Stage widths/depths configurable; GroupNorm instead of BatchNorm so
    data parallelism needs no cross-device batch-stat sync."""
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    width: int = 64
    n_classes: int = 1000

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            feats = self.width * (2 ** i)
            for j in range(n_blocks):
                x = ResNetBlock(feats, strides=2 if i > 0 and j == 0
                                else 1)(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.n_classes)(x)


def _param_digest(params) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(params):
        h.update(np.asarray(leaf, dtype=np.float32).tobytes())
    return h.hexdigest()[:16]


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-process batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tiny", action="store_true",
                   help="small model/images for CPU tests")
    p.add_argument("--out-file", type=str, default=None,
                   help="write final metrics+param digest JSON here")
    args = p.parse_args(argv)

    ctx = distributed.initialize_from_env()
    if args.tiny:
        model = ResNet(stage_sizes=(1, 1), width=8, n_classes=10)
        args.image_size = 32
    else:
        model = ResNet(stage_sizes=(3, 4, 23, 3), width=64)  # resnet101

    print(f"resnet_ddp: rank={ctx.rank}/{ctx.num_nodes} "  # noqa: stpu-host-sync startup banner of host ints, before the loop
          f"local_devices={jax.local_device_count()} "
          f"global_devices={jax.device_count()} federated={ctx.federated}",
          flush=True)

    # Every process generates the same dataset (seeded) and reads its own
    # batch shard by rank, exactly like a sharded dataloader.
    n_classes = 10 if args.tiny else 1000

    def sample_batch(step: int):
        r = np.random.RandomState(args.seed + step * ctx.num_nodes
                                  + ctx.rank)
        x = r.randn(args.batch_size, args.image_size, args.image_size,
                    3).astype(np.float32)
        y = r.randint(0, n_classes, size=(args.batch_size,)).astype(
            np.int32)
        return x, y

    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, args.image_size, args.image_size, 3)))
    tx = optax.sgd(args.lr, momentum=0.9)
    opt_state = tx.init(params)

    if ctx.federated:
        # One logical program over all hosts' devices; batch sharded over
        # the dp axis, params replicated; XLA inserts the grad psum.
        world_batch_ = args.batch_size * ctx.num_nodes
        if world_batch_ % jax.device_count():
            raise SystemExit(
                f"global batch {world_batch_} not divisible by "
                f"{jax.device_count()} devices; raise --batch-size")
        mesh = Mesh(np.asarray(jax.devices()), ("dp",))  # noqa: stpu-host-sync device handles are host-side objects, not arrays
        batch_sharding = NamedSharding(mesh, P("dp"))
        replicated = NamedSharding(mesh, P())
        params = jax.device_put(params, replicated)
        opt_state = jax.device_put(opt_state, replicated)

        def globalize(x):
            return jax.make_array_from_process_local_data(
                batch_sharding, x)
    else:
        globalize = jnp.asarray

    @jax.jit
    def step_fn(params, x, y):
        def loss_fn(params):
            logits = model.apply(params, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return grads, loss

    @jax.jit
    def apply_fn(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state

    if trainstats.ENABLED:
        trainstats.configure(
            peak_flops=trainstats.detect_peak_flops(),
            host=ctx.rank, hosts=ctx.num_nodes, job="resnet_ddp")
    iter_times = []
    loss = None
    try:
        for i in range(args.steps):
            data_t0 = time.perf_counter()
            x, y = sample_batch(i)
            data_wait = time.perf_counter() - data_t0
            t0 = time.perf_counter()
            grads, loss = step_fn(params, globalize(x), globalize(y))
            if ctx.is_multiprocess and not ctx.federated:
                grads = distributed.kv_allreduce_mean(grads, ctx,
                                                      tag=str(i))
            params, opt_state = apply_fn(params, opt_state, grads)
            # The DDP bench fences every iteration by design — iter
            # times measure the full step, not just dispatch.
            jax.block_until_ready(params)  # noqa: stpu-host-sync benchmark iteration fence by design
            dur = time.perf_counter() - t0
            iter_times.append(dur)
            if trainstats.ENABLED:
                trainstats.record_step(step=i + 1, dur=dur,
                                       tokens=args.batch_size,
                                       data_wait_s=data_wait)
    except (Exception, KeyboardInterrupt) as e:
        if trainstats.ENABLED:
            trainstats.dump_flight("train_crash", error=repr(e))
        raise

    world_batch = args.batch_size * max(ctx.num_nodes, 1)
    p50 = float(np.median(iter_times[2:] or iter_times))
    # Host copies for the report: digesting/printing the device trees
    # directly would sync them inside the metrics build.
    params_host = jax.device_get(params)
    loss_host = jax.device_get(loss)
    metrics = {
        "recipe": "resnet_ddp",
        "rank": ctx.rank,
        "num_nodes": ctx.num_nodes,
        "steps": args.steps,
        "final_loss": float(loss_host),
        "p50_iter_seconds": round(p50, 4),
        "examples_per_second": round(world_batch / p50, 1),
        "param_digest": _param_digest(params_host),
    }
    if trainstats.ENABLED:
        snap = trainstats.snapshot()
        metrics["train_goodput"] = snap["goodput"]
        metrics["train_step_seconds"] = snap["step_seconds_mean"]
        trainstats.flush()
    print(json.dumps(metrics), flush=True)
    if args.out_file:
        with open(args.out_file, "w") as f:
            json.dump(metrics, f)
    return metrics


if __name__ == "__main__":
    main()
