"""Minimal TPU LLM inference server — the JetStream/vLLM-TPU serve config.

Reference analog: llm/vllm/serve.yaml and llm/mixtral/serve.yaml (the
reference points SkyServe at a vLLM container). Native version: a
stdlib-http server around models/llama.py greedy decoding, exposing the
endpoints SkyServe probes and balances:

    GET  /health    -> 200 once the model is compiled (readiness probe)
    POST /generate  {"prompt": [ids...], "max_tokens": N,
                     "temperature": 0.7, "seed": 1} -> {"tokens": [...]}

Decoding is a jitted lax.scan over a preallocated KV cache (static shapes,
one compile per bucket) — the shape a real TPU decode loop takes; batching,
streaming, and continuous scheduling live above this in SkyServe's LB.

    python -m skypilot_tpu.recipes.serve_llm --model tiny --port 8080
"""
from __future__ import annotations

import argparse
import functools
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp

from skypilot_tpu.models import gemma, llama, mixtral
from skypilot_tpu.train import distributed


def _model_api(cfg):
    """Static dispatch on the (static-argnum) config type: the cache
    functions of the model family being served."""
    if isinstance(cfg, mixtral.MixtralConfig):
        return mixtral
    if isinstance(cfg, gemma.GemmaConfig):
        return gemma
    return llama


# Request limits: prompt/decode lengths are padded to buckets so the jit
# cache stays bounded (≤ len(buckets) × len(mt buckets) compiles) and a
# hostile request cannot trigger unbounded allocation or a giant scan.
PROMPT_BUCKET = 64
MAX_PROMPT_TOKENS = 1024
MAX_GEN_TOKENS = 256
GEN_BUCKET = 16


def _ceil_to(n: int, b: int) -> int:
    return ((n + b - 1) // b) * b


def _pick(logits_row: jax.Array, temperature: float,
          key: jax.Array) -> jax.Array:
    if temperature > 0.0:
        return jax.random.categorical(
            key, logits_row / temperature, axis=-1).astype(jnp.int32)
    return jnp.argmax(logits_row, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0, 3, 5))
def _prefill(cfg: llama.LlamaConfig, params, buf: jax.Array,
             max_seq: int, start: jax.Array, temperature: float,
             key: jax.Array):
    """Streaming path, step 1: one O(S) prefill over the padded prompt;
    returns (first token (1,), KV cache). Shapes are bucket sizes so
    all prompts in a bucket share one compile."""
    api = _model_api(cfg)
    cache = api.init_cache(cfg, 1, max_seq)
    logits, cache = api.forward_with_cache(
        cfg, params, buf[None, :], cache, jnp.int32(0), valid_len=start,
        logits_at=jnp.asarray(start - 1, jnp.int32))
    return _pick(logits[:, 0], temperature, key), cache


@functools.partial(jax.jit, static_argnums=(0, 5), donate_argnums=(3,))
def _gen_step(cfg: llama.LlamaConfig, params, tok: jax.Array, cache,
              pos: jax.Array, temperature: float, key: jax.Array):
    """Streaming path, step 2..N: one O(max_seq) cached decode step —
    called per token so the handler can flush each token to the client
    as it exists (SSE), instead of waiting for the whole scan. The KV
    cache is DONATED: XLA aliases it in place instead of copying the
    whole O(layers * max_seq) buffer every token."""
    logits, cache = _model_api(cfg).forward_with_cache(
        cfg, params, tok[:, None], cache, pos)
    return _pick(logits[:, -1], temperature, key), cache


@functools.partial(jax.jit, static_argnums=(0, 4, 5))
def _decode(cfg: llama.LlamaConfig, params, buf: jax.Array,
            start: jax.Array, mt_pad: int,
            temperature: float, seed: jax.Array) -> jax.Array:
    """Continuation over a padded prompt buffer.

    buf: (s_pad,) int32 with the prompt in [0, start). Shapes are bucket
    sizes and the true prompt length is a dynamic scalar, so all prompts
    in a bucket share one compile (plus one per distinct temperature).
    Decoding is KV-cached (models/llama.decode): one O(S) prefill, then
    O(max_seq) per token — the vLLM/JetStream-shaped serving loop, not a
    quadratic recompute.
    """
    max_seq = buf.shape[0] + mt_pad
    return _model_api(cfg).decode(
        cfg, params, buf[None, :], start, mt_pad, max_seq,
        temperature=temperature, key=jax.random.key(seed))[0]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # chunked responses need 1.1
    server_ctx = None  # set by serve()

    def log_message(self, *args):
        pass

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/", "/health"):
            ready = self.server_ctx["ready"].is_set()
            self._json(200 if ready else 503,
                       {"status": "ok" if ready else "warming"})
        else:
            self._json(404, {"error": "not found"})

    def do_POST(self):
        if self.path != "/generate":
            self._json(404, {"error": "not found"})
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            req = json.loads(self.rfile.read(length) or b"{}")
            prompt = [int(t) for t in req["prompt"]]
            if not 1 <= len(prompt) <= MAX_PROMPT_TOKENS:
                raise ValueError(
                    f"prompt length must be in [1, {MAX_PROMPT_TOKENS}]")
            mt = min(max(int(req.get("max_tokens", 16)), 1),
                     MAX_GEN_TOKENS)
            # Quantized so the jit cache stays bounded; 0.0 = greedy.
            temperature = round(
                max(0.0, min(float(req.get("temperature", 0.0)), 2.0)),
                1)
            # Mask to uint32 range: any int is a valid seed, and an
            # out-of-range value must not escape the 400 contract.
            seed = int(req.get("seed", 0)) & 0xFFFFFFFF
            ctx = self.server_ctx
            s = len(prompt)
            s_pad = _ceil_to(s, PROMPT_BUCKET)
            mt_pad = _ceil_to(mt, GEN_BUCKET)
            buf = jnp.zeros((s_pad,), jnp.int32).at[:s].set(
                jnp.asarray(prompt, dtype=jnp.int32))
            stream = bool(req.get("stream"))
        except (KeyError, ValueError, TypeError) as e:
            self._json(400, {"error": str(e)})
            return
        if stream:
            started = []
            try:
                self._stream_generate(ctx, buf, s, s_pad, mt, mt_pad,
                                      temperature, seed, started)
            except Exception as e:  # noqa: BLE001
                if started:
                    # Headers/chunks already out — a JSON error response
                    # would corrupt the stream. Drop the connection; the
                    # truncated stream is the signal.
                    self.close_connection = True
                else:
                    self._json(400, {"error": str(e)})
            return
        try:
            with ctx["lock"]:
                toks = _decode(ctx["cfg"], ctx["params"], buf,
                               jnp.int32(s), mt_pad, temperature,
                               jnp.uint32(seed))
            self._json(200, {"tokens": [int(t) for t in toks[:mt]]})
        except (KeyError, ValueError, TypeError) as e:
            self._json(400, {"error": str(e)})

    def _stream_generate(self, ctx, buf, s, s_pad, mt, mt_pad,
                         temperature, seed, started) -> None:
        """SSE token stream: one `data: {"token": N}` event per decoded
        token, flushed as produced (chunked transfer), then
        `data: [DONE]` — the OpenAI-style contract LLM clients expect."""
        from skypilot_tpu.serve.load_balancer import (end_chunks,
                                                      write_chunk)
        cfg, params = ctx["cfg"], ctx["params"]
        key = jax.random.key(seed)
        # Prefill BEFORE the headers go out: a trace/compile error on a
        # fresh bucket must still be reportable as a clean error, not a
        # corrupted half-stream. The model lock is held ONLY around
        # compute, never across socket writes — a stalled client (TCP
        # backpressure on emit) must not block other requests.
        key, k = jax.random.split(key)
        with ctx["lock"]:
            tok, cache = _prefill(cfg, params, buf, s_pad + mt_pad,
                                  jnp.int32(s), temperature, k)
            tok.block_until_ready()

        started.append(True)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def emit(payload: str) -> None:
            write_chunk(self.wfile, f"data: {payload}\n\n".encode())

        emit(json.dumps({"token": int(tok[0])}))
        for i in range(mt - 1):
            key, k = jax.random.split(key)
            with ctx["lock"]:
                tok, cache = _gen_step(cfg, params, tok, cache,
                                       jnp.int32(s + i), temperature, k)
                tok.block_until_ready()
            emit(json.dumps({"token": int(tok[0])}))
        emit("[DONE]")
        end_chunks(self.wfile)


def serve(cfg: llama.LlamaConfig, params, port: int,
          ready_event: threading.Event = None) -> ThreadingHTTPServer:
    ctx = {"cfg": cfg, "params": params, "lock": threading.Lock(),
           "ready": ready_event or threading.Event()}

    handler = type("Handler", (_Handler,), {"server_ctx": ctx})
    httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)

    def warmup():
        buf = jnp.zeros((PROMPT_BUCKET,), jnp.int32)
        _decode(cfg, params, buf, jnp.int32(8), GEN_BUCKET, 0.0,
                jnp.uint32(0)).block_until_ready()
        ctx["ready"].set()

    threading.Thread(target=warmup, daemon=True).start()
    return httpd


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model",
                   choices=["tiny", "8b", "mixtral-tiny", "mixtral-8x7b",
                            "gemma-tiny", "gemma-2b", "gemma-7b"],
                   default="tiny")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    distributed.initialize_from_env()
    cfg = {
        "tiny": llama.LlamaConfig.tiny,
        "8b": llama.LlamaConfig.llama3_8b,
        "mixtral-tiny": mixtral.MixtralConfig.tiny,
        "mixtral-8x7b": mixtral.MixtralConfig.mixtral_8x7b,
        "gemma-tiny": gemma.GemmaConfig.tiny,
        "gemma-2b": gemma.GemmaConfig.gemma_2b,
        "gemma-7b": gemma.GemmaConfig.gemma_7b,
    }[args.model]()
    params = _model_api(cfg).init(cfg, jax.random.PRNGKey(args.seed))
    httpd = serve(cfg, params, args.port)
    print(f"serve_llm: listening on :{args.port}", flush=True)
    httpd.serve_forever()


if __name__ == "__main__":
    main()
