"""Minimal TPU LLM inference server — the JetStream/vLLM-TPU serve config.

Reference analog: llm/vllm/serve.yaml and llm/mixtral/serve.yaml (the
reference points SkyServe at a vLLM container). Native version: a
stdlib-http server around the shared model decode stack, exposing the
endpoints SkyServe probes and balances:

    GET  /health    -> 200 once the model is compiled (readiness probe)
    GET  /metrics   -> Prometheus exposition (engine slot/queue/token
                       metrics; merged into the LB's /metrics snapshot)
    POST /generate  {"prompt": [ids...], "max_tokens": N,
                     "temperature": 0.7, "seed": 1} -> {"tokens": [...]}

Requests are served by the slot-based continuous-batching decode engine
(serve/decode_engine.py): concurrent requests of ANY prompt length
share one KV cache batch, joining mid-flight into free slots (chunked
prefill interleaved with decode) and streaming per slot — no
model-lock-per-request serialization, no same-bucket-only batching.
``engine_slots=0`` falls back to the legacy locked fixed-batch path
(kept for apples-to-apples measurement; both paths donate their KV
cache through the jit boundary).

    python -m skypilot_tpu.recipes.serve_llm --model tiny --port 8080
"""
from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp

from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.models import family_name, gemma, llama, mixtral, model_api
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import reqlog
from skypilot_tpu.observability import stepstats
from skypilot_tpu.observability import tracing
from skypilot_tpu.serve import decode_engine
from skypilot_tpu.serve import gang_replica
from skypilot_tpu.serve import load_balancing_policies
from skypilot_tpu.train import distributed
from skypilot_tpu.utils import fault_injection


# Request limits: prompt/decode lengths are padded to buckets so the jit
# cache stays bounded (≤ len(buckets) × len(mt buckets) compiles) and a
# hostile request cannot trigger unbounded allocation or a giant scan.
PROMPT_BUCKET = 64
MAX_PROMPT_TOKENS = 1024
MAX_GEN_TOKENS = 256
GEN_BUCKET = 16

# Engine defaults (overridable per serve() call / env). The prefill
# chunk deliberately has NO constant here: the recipe leaves it at
# resolve_kv_geometry's 0 sentinel so the one derivation (tuning
# manifest -> DEFAULT_PREFILL_CHUNK fallback) lives in decode_engine —
# a literal here was exactly the three-call-site drift magnet the
# autotuner PR removed.
ENGINE_SLOTS = int(os.environ.get("STPU_ENGINE_SLOTS", "4"))
# Host-RAM KV spill tier budget (MiB) under the paged pool's trie:
# LRU-evicted prefix blocks spill D2H into a bounded host pool and
# re-admit H2D on a warm match, so the effective prefix cache grows
# from the HBM pool to host RAM at the cost of one block transfer per
# re-hit. 0 turns the tier off (evictions drop the leaf); default on
# at 64 MiB. Ignored by the dense engine (no trie, no tier).
ENGINE_PREFIX_CACHE_MB = float(
    os.environ.get("STPU_PREFIX_CACHE_MB", "64"))
# Paged KV block pool (decode_engine paged mode): one device-resident
# pool + per-slot block tables instead of dense per-slot cache rows —
# admission is free-block based and prefix hits alias blocks
# zero-copy. ON by default (bit-identical to dense, pinned by
# tests/test_paged_kv.py); STPU_KV_PAGED=0 keeps the dense path
# selectable for parity debugging (no prefix cache there).
ENGINE_KV_PAGED = os.environ.get("STPU_KV_PAGED", "1") == "1"
# 0 = auto-size the pool to the dense HBM budget
# (slots * max_seq / block + 1 scratch; doubled under KV_QUANT —
# int8 blocks are ~half the bytes).
ENGINE_KV_POOL_BLOCKS = int(os.environ.get("STPU_KV_POOL_BLOCKS", "0"))
# 0 = block size follows the prefill chunk (64).
ENGINE_KV_BLOCK_TOKENS = int(
    os.environ.get("STPU_KV_BLOCK_TOKENS", "0"))
# Quantized serving (decode_engine quant mode): KV_QUANT stores int8
# KV blocks + per-(layer, block, head) f32 scales in the paged pool
# (~2x block capacity at the same HBM budget; requires KV_PAGED);
# WEIGHT_QUANT serves int8 per-channel-scaled params. NOT
# bit-identical to bf16 — gated by the tests/test_quant.py parity
# suite (top-1 agreement + perplexity bound per family).
ENGINE_KV_QUANT = os.environ.get("STPU_KV_QUANT", "0") == "1"
ENGINE_WEIGHT_QUANT = os.environ.get("STPU_WEIGHT_QUANT", "0") == "1"
# Self-speculative decoding (decode_engine spec mode): up to K n-gram
# drafted tokens per slot per step, verified in one batched forward —
# bit-identical output, fewer memory-bound passes per token on
# repetitive/templated traffic. 0 disables (this release's default;
# the bench legs and chat-heavy deployments turn it on).
ENGINE_SPEC_K = int(os.environ.get("STPU_SPEC_K", "0"))
ENGINE_SPEC_NGRAM = int(os.environ.get("STPU_SPEC_NGRAM", "3"))
ENGINE_SPEC_MIN_ACCEPT = float(
    os.environ.get("STPU_SPEC_MIN_ACCEPT", "0.2"))
# Per-token stream timeout: how long a client handler waits for the
# NEXT token before declaring the engine wedged (surfaced as a clean
# EngineError, not a hang). Operator-tunable — the right bound is how
# fast wedged-device detection should be vs. the slowest honest step.
STREAM_TIMEOUT_SECONDS = float(
    os.environ.get("STPU_STREAM_TIMEOUT", "600"))
# Preemption-notice watcher poll interval (seconds): how often the
# replica checks the provider's metadata preemption signal (the fault
# point ``replica.preempt_notice`` stands in for the metadata server in
# tests and game-days). On a notice the replica KEEPS serving — it only
# advertises the notice via /health so the controller can flip it
# DRAINING and launch the replacement BEFORE the kill lands
# (replace-ahead); in-flight streams resume on peers through the LB
# journal when the kill arrives. 0 disables the watcher.
PREEMPT_NOTICE_POLL = float(
    os.environ.get("STPU_PREEMPT_NOTICE_POLL", "1.0"))
# Engine supervision (decode_engine.EngineSupervisor): restart a
# crashed engine loop this many times (capped exponential backoff
# starting at BACKOFF seconds) before declaring the replica dead.
ENGINE_MAX_RESTARTS = int(os.environ.get("STPU_ENGINE_MAX_RESTARTS",
                                         "3"))
ENGINE_RESTART_BACKOFF = float(
    os.environ.get("STPU_ENGINE_RESTART_BACKOFF", "1.0"))

# Topology tag for this replica (hosts x tp), exported so the LB's
# merged /metrics and loadgen reports can attribute SLO shifts to a
# replica_topology change. Info-style gauge: value is always 1, the
# labels carry the fact.
_TOPOLOGY_INFO = metrics.gauge(
    "stpu_replica_topology_info",
    "Replica serving topology (hosts x tensor-parallel degree); "
    "value is constant 1.", ("hosts", "tp"))
_PREEMPT_NOTICES = metrics.counter(
    "stpu_serve_preempt_notices_total",
    "Provider preemption notices observed by the replica's metadata "
    "watcher (fault point replica.preempt_notice); each one is a "
    "replace-ahead trigger for the controller.")


def _ceil_to(n: int, b: int) -> int:
    return ((n + b - 1) // b) * b


def _pick(logits_row: jax.Array, temperature: float,
          key: jax.Array) -> jax.Array:
    if temperature > 0.0:
        return jax.random.categorical(
            key, logits_row / temperature, axis=-1).astype(jnp.int32)
    return jnp.argmax(logits_row, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0, 3, 5))
def _prefill(cfg: llama.LlamaConfig, params, buf: jax.Array,
             max_seq: int, start: jax.Array, temperature: float,
             key: jax.Array):
    """Legacy streaming path, step 1: one O(S) prefill over the padded
    prompt; returns (first token (1,), KV cache). Shapes are bucket
    sizes so all prompts in a bucket share one compile."""
    api = model_api(cfg)
    cache = api.init_cache(cfg, 1, max_seq)
    logits, cache = api.forward_with_cache(
        cfg, params, buf[None, :], cache, jnp.int32(0), valid_len=start,
        logits_at=jnp.asarray(start - 1, jnp.int32))
    return _pick(logits[:, 0], temperature, key), cache


@functools.partial(jax.jit, static_argnums=(0, 5), donate_argnums=(3,))
def _gen_step(cfg: llama.LlamaConfig, params, tok: jax.Array, cache,
              pos: jax.Array, temperature: float, key: jax.Array):
    """Legacy streaming path, step 2..N: one cached decode step —
    called per token so the handler can flush each token to the client
    as it exists (SSE). The KV cache is DONATED: XLA aliases it in
    place instead of copying the whole O(layers * max_seq) buffer every
    token."""
    logits, cache = model_api(cfg).forward_with_cache(
        cfg, params, tok[:, None], cache, pos)
    return _pick(logits[:, -1], temperature, key), cache


@functools.partial(jax.jit, static_argnums=(0, 4, 5),
                   donate_argnums=(6,))
def _decode(cfg: llama.LlamaConfig, params, buf: jax.Array,
            start: jax.Array, mt_pad: int,
            temperature: float, cache, seed: jax.Array) -> jax.Array:
    """Legacy fixed-batch continuation over a padded prompt buffer.

    buf: (s_pad,) int32 with the prompt in [0, start). Shapes are bucket
    sizes and the true prompt length is a dynamic scalar, so all prompts
    in a bucket share one compile (plus one per distinct temperature).
    ``cache`` is allocated by the caller, DONATED, and returned (so XLA
    can alias it to the output) — the decode scan updates it in place
    instead of materializing a second full-size cache in HBM each step.
    Returns (tokens (mt_pad,), cache).
    """
    max_seq = buf.shape[0] + mt_pad
    toks, cache = model_api(cfg).decode(
        cfg, params, buf[None, :], start, mt_pad, max_seq,
        temperature=temperature, key=jax.random.key(seed),
        cache=cache, return_cache=True)
    return toks[0], cache


def _decode_locked(ctx, buf, s, mt_pad, temperature, seed):
    """Legacy path: allocate + donate a fresh cache under the model
    lock (the returned cache exists only for donation aliasing)."""
    cfg = ctx["cfg"]
    cache = model_api(cfg).init_cache(cfg, 1, buf.shape[0] + mt_pad)
    with ctx["lock"]:
        toks, _ = _decode(cfg, ctx["params"], buf, jnp.int32(s),
                          mt_pad, temperature, cache, jnp.uint32(seed))
        return toks


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # chunked responses need 1.1
    server_ctx = None  # set by serve()

    def log_message(self, *args):
        pass

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/", "/health"):
            ctx = self.server_ctx
            ready = ctx["ready"].is_set()
            engine = ctx.get("engine")
            gang = ctx.get("gang")
            if not ready:
                self._json(503, {"status": "warming"})
            elif gang is not None and not gang.healthy():
                # Gang replicas probe as ONE unit: host 0's /health
                # speaks for every host (the leader's membership
                # monitor), so a dead follower can never hide behind a
                # READY replica serving partial-gang garbage.
                self._json(503, {"status": "gang_degraded"})
            elif engine is not None and not engine.healthy():
                # The readiness probe must tell the truth about the
                # ENGINE, not just the HTTP process: a dead/restarting
                # engine behind a 200 probe is a zombie replica that
                # blackholes its share of traffic.
                self._json(503, {"status": "engine_down"})
            else:
                payload = {"status": "ok"}
                notice = ctx.get("preempt_notice")
                if notice is not None and notice.is_set():
                    # Preemption notice observed: the replica is still
                    # fully serving (200), but the controller's probe
                    # reads this flag and flips the replica DRAINING —
                    # replace-ahead, before the kill ever lands.
                    payload["preempt_notice"] = True
                self._json(200, payload)
        elif self.path == "/drain":
            self._json(200, self._drain_payload())
        elif self.path == "/perf":
            # Step-telemetry snapshot (observability/stepstats.py):
            # phase breakdown, occupancy, sampled dispatch/device
            # split over the step ring. Meaningful content needs
            # STPU_STEPSTATS=1 on the replica; disarmed it reports
            # armed=false with an empty ring. The LB merges every
            # ready replica's /perf like it merges /metrics.
            self._json(200, self._perf_payload())
        elif self.path == "/gang":
            gang = self.server_ctx.get("gang")
            if gang is None:
                self._json(404, {"error": "not a gang replica"})
            else:
                self._json(200, {
                    "topology": gang.topology.to_config(),
                    "label": gang.topology.label(),
                    "healthy": gang.healthy(),
                    "restarts": gang.restarts,
                    "members": gang.members_info()})
        elif self.path == "/metrics":
            # Replica-local registry (engine slot/queue/token families);
            # the LB pulls this into its merged /metrics snapshot.
            body = metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", metrics.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._json(404, {"error": "not found"})

    # ------------------------------------------------------------ perf
    def _perf_payload(self) -> dict:
        ctx = self.server_ctx
        doc = stepstats.snapshot()
        engine = ctx.get("engine")
        if engine is not None:
            doc["engine"] = {
                "healthy": engine.healthy(),
                "in_flight": engine.in_flight(),
                "draining": engine.draining(),
                "restarts": getattr(engine, "restarts", 0),
            }
            kv = engine.kv_config()
            if kv:
                # Quant mode line for `stpu perf`: which int8 paths
                # this replica serves with (resolve_kv_geometry output
                # — the same dict the gang handshake compares).
                doc["quant"] = {
                    "kv_quant": int(kv.get("kv_quant", 0)),
                    "weight_quant": int(kv.get("weight_quant", 0)),
                    "pool_blocks": int(kv.get("pool_blocks", 0)),
                }
                # Tuning line for `stpu perf`: the constants this
                # replica actually decodes with and which manifest
                # (payload-sha tag, or "default") supplied them.
                doc["tuning"] = {
                    "block": int(kv.get("block", 0)),
                    "chunk": int(kv.get("chunk", 0)),
                    "window": int(kv.get("window", 0)),
                    "spec_k": int(kv.get("spec_k", 0)),
                    "manifest": kv.get("manifest", "default"),
                }
            # Host KV tier line for `stpu perf`: spill/re-admit and
            # residency counters from the engine's HostBlockPool
            # (absent while the tier is off).
            tier = {}
            get_tier = getattr(engine, "host_tier_stats", None)
            if callable(get_tier):
                tier = get_tier() or {}
            if tier:
                doc["tier"] = {
                    "budget_mb": float(tier.get("budget_mb", 0.0)),
                    "bytes": int(tier.get("bytes", 0)),
                    "blocks": int(tier.get("blocks", 0)),
                    "spilled": int(tier.get("spilled", 0)),
                    "dropped": int(tier.get("evict_drops", 0)),
                    "lru_dropped": int(tier.get("lru_dropped", 0)),
                    "readmitted": int(tier.get("readmitted_blocks",
                                               0)),
                    "rehits": int(tier.get("rehits", 0)),
                }
        return doc

    def _start_profile(self) -> None:
        """POST /profile?seconds=N: capture an on-device
        ``jax.profiler`` trace to ``~/.stpu/logs/profiles/<stamp>/``.
        The capture runs on its own thread (the handler answers 202
        immediately with the target directory); one capture at a time
        per process."""
        import urllib.parse
        query = urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query)
        try:
            seconds = float(query.get("seconds", ["5"])[0])
        except ValueError:
            self._json(400, {"error": "seconds must be numeric"})
            return
        # Atomic claim BEFORE the 202: a racing second request must be
        # told 409, not promised a directory that never appears.
        if not stepstats.begin_profile():
            self._json(409, {"error": "a profile capture is already "
                                      "running"})
            return
        out_dir = os.path.join(
            str(stepstats.profiles_dir()),
            time.strftime("%Y%m%d-%H%M%S"))

        def capture():
            try:
                stepstats.capture_profile(seconds, out_dir=out_dir,
                                          claimed=True)
            except Exception:  # noqa: stpu-except — best-effort capture; the 202 already told the client where to look
                pass

        threading.Thread(target=capture, daemon=True,
                         name="profile-capture").start()
        self._json(202, {"profile_dir": out_dir,
                         "seconds": min(max(seconds, 0.05), 120.0)})

    # ----------------------------------------------------------- drain
    def _drain_payload(self) -> dict:
        ctx = self.server_ctx
        with ctx["inflight_lock"]:
            handler_inflight = ctx["inflight"][0]
        engine = ctx.get("engine")
        if engine is not None:
            # The engine's slot count hits zero while a handler thread
            # may still be FLUSHING queued tokens to a slow client —
            # the handler count covers that tail, so report the max of
            # the two views or a drain could truncate a live stream.
            return {"draining": engine.draining(),
                    "in_flight": max(engine.in_flight(),
                                     handler_inflight)}
        return {"draining": ctx["draining"].is_set(),
                "in_flight": handler_inflight}

    def _start_drain(self) -> None:
        """POST /drain: stop admitting new generations, report what is
        still in flight. The replica manager polls GET /drain until
        in_flight hits 0 (or its deadline) before terminating, so live
        token streams finish instead of truncating mid-rollout."""
        ctx = self.server_ctx
        ctx["draining"].set()
        engine = ctx.get("engine")
        if engine is not None:
            engine.drain()
        gang = ctx.get("gang")
        if gang is not None:
            # Drain is gang-wide: follower engines stop admitting too,
            # so scale-down leaves no host mid-lockstep.
            gang.drain()
        self._json(200, self._drain_payload())

    def do_POST(self):
        # Body consumed up front on EVERY path: an early error response
        # that leaves unread body bytes on an HTTP/1.1 keep-alive
        # connection corrupts the next request parsed off it.
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b""
        if self.path == "/drain":
            self._start_drain()
            return
        if self.path == "/profile" or self.path.startswith("/profile?"):
            self._start_profile()
            return
        if self.path != "/generate":
            self._json(404, {"error": "not found"})
            return
        if self.server_ctx["draining"].is_set():
            # Engine-path submits would raise EngineError anyway; this
            # also covers the legacy path and keeps the refusal shape
            # uniform (503 → the LB retries on a non-draining peer).
            self._json(503, {"error": "replica draining"})
            return
        try:
            req = json.loads(raw or b"{}")
            prompt = [int(t) for t in req["prompt"]]
            if not 1 <= len(prompt) <= MAX_PROMPT_TOKENS:
                raise ValueError(
                    f"prompt length must be in [1, {MAX_PROMPT_TOKENS}]")
            mt = min(max(int(req.get("max_tokens", 16)), 1),
                     MAX_GEN_TOKENS)
            # Quantized so the jit cache stays bounded; 0.0 = greedy.
            temperature = round(
                max(0.0, min(float(req.get("temperature", 0.0)), 2.0)),
                1)
            # Mask to uint32 range: any int is a valid seed, and an
            # out-of-range value must not escape the 400 contract.
            seed = int(req.get("seed", 0)) & 0xFFFFFFFF
            ctx = self.server_ctx
            stream = bool(req.get("stream"))
            # LB mid-stream resume contract: ``resume.emitted`` are the
            # tokens the client already received (they become a prompt
            # extension in the engine), ``resume.pos`` the absolute
            # emission position to continue from. The engine's
            # fold_in(seed, position) sampling keys make the
            # continuation bit-identical to the uninterrupted run.
            resume = None
            rd = req.get("resume")
            if rd is not None:
                if not isinstance(rd, dict):
                    raise ValueError("resume must be an object")
                resume = [int(t) for t in rd.get("emitted") or []]
                if not resume:
                    raise ValueError("resume.emitted must be non-empty")
                if int(rd.get("pos", -1)) != len(resume):
                    raise ValueError(
                        "resume.pos must equal len(resume.emitted)")
                if len(resume) >= mt:
                    raise ValueError(
                        "resume.emitted already covers max_tokens")
        except (KeyError, ValueError, TypeError) as e:
            self._json(400, {"error": str(e)})
            return
        engine = ctx.get("engine")
        if resume is not None and engine is None:
            # The legacy locked path has no absolute-position sampling
            # contract to resume into; only engine replicas honor it.
            self._json(400, {"error": "resume requires the decode "
                                      "engine (engine_slots > 0)"})
            return
        # Replica hop of the request's trace, continued from the LB's
        # X-STPU-Trace header (tracing.ENABLED guard = zero tracing
        # cost unarmed); the engine parents its queue/prefill/decode
        # spans under this one via the submit trace context.
        span = None
        if tracing.ENABLED:
            span = tracing.start_span(
                "replica.generate", kind="replica",
                parent=tracing.extract(self.headers),
                attrs={"prompt_tokens": len(prompt), "max_tokens": mt,
                       "stream": stream,
                       "resume": len(resume) if resume else 0,
                       "engine": engine is not None})
        # Legacy-path in-flight accounting (the engine tracks its own):
        # GET /drain must see requests this handler is still streaming.
        with ctx["inflight_lock"]:
            ctx["inflight"][0] += 1
        status = "error"
        try:
            if engine is not None:
                self._engine_generate(engine, prompt, mt, temperature,
                                      seed, stream, span, resume)
            else:
                self._legacy_generate(ctx, prompt, mt, temperature,
                                      seed, stream, span)
            status = "ok"
        except decode_engine.EngineError as e:
            if span is not None:
                span.event("engine_error", error=str(e))
            self._json(503, {"error": str(e)})
        except (KeyError, ValueError, TypeError) as e:
            self._json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — pre-header failures
            # (jit compile/runtime errors on a fresh bucket) must still
            # produce a clean JSON error; once headers are out, _sse
            # has already swallowed the exception and dropped the
            # connection, so this catch never corrupts a stream.
            self._json(500, {"error": f"{type(e).__name__}: {e}"})
        finally:
            with ctx["inflight_lock"]:
                ctx["inflight"][0] -= 1
            if span is not None:
                span.end(status=status)

    # ----------------------------------------------------- engine path
    def _engine_generate(self, engine, prompt, mt, temperature, seed,
                         stream, span=None, resume=None) -> None:
        gang = self.server_ctx.get("gang")
        trace = span.context() if span is not None else None
        if trace is None and reqlog.ENABLED:
            # Request-analytics join key: with tracing disarmed the LB
            # still stamps X-STPU-Trace (a reqlog-minted id, sampled
            # flag 00), and carrying it into the engine keys the
            # engine half of the request record. extract/parse are
            # pure string work — no tracing I/O, and the 00 flag keeps
            # every engine tracing guard short-circuited.
            trace = tracing.extract(self.headers)
        # Resume admission: ``mt`` is the ORIGINAL request budget — the
        # engine re-prefills the emitted tokens as a prompt extension
        # and regenerates only the remainder, emitting from the same
        # absolute positions (same seed) the dead upstream would have.
        remaining = mt - (len(resume) if resume else 0)
        if gang is not None:
            # Mirror the admission (prompt + sampling seed) to every
            # follower host BEFORE the local submit, so all hosts see
            # the same request order and execute identical jitted
            # submissions (the lockstep half of the gang contract).
            # Broadcast + local submit are ONE critical section:
            # concurrent handler threads interleaving them would admit
            # (A,B) on followers but (B,A) on host 0 — divergent slot
            # state, and on a real ICI-federated slice a mismatched
            # SPMD program.
            with self.server_ctx["gang_admit_lock"]:
                gang.broadcast_generate(prompt, remaining, temperature,
                                        seed, trace=trace,
                                        resume=resume)
                req = engine.submit(prompt, max_tokens=remaining,
                                    temperature=temperature, seed=seed,
                                    trace=trace, resume=resume)
        else:
            req = engine.submit(prompt, max_tokens=remaining,
                                temperature=temperature, seed=seed,
                                trace=trace, resume=resume)
        timeout = self.server_ctx["stream_timeout"]
        if not stream:
            self._json(200, {"tokens": req.result(timeout=timeout)})
            return
        it = req.stream(timeout=timeout)
        try:
            # First token BEFORE the headers go out: a prefill/compile
            # error must still be reportable as a clean JSON error, not
            # a corrupted half-stream.
            first = next(it)
        except decode_engine.EngineError as e:
            if span is not None:
                # end() here (idempotent — do_POST's finally no-ops)
                # so a 503'd stream records error like the non-stream
                # path, not a healthy-looking hop.
                span.event("engine_error", error=str(e))
                span.end(status="error")
            self._json(503, {"error": str(e)})
            return
        except StopIteration:
            self._json(200, {"tokens": []})
            return
        self._sse(req, [first], it, span,
                  resume_len=len(resume) if resume else 0)

    # ----------------------------------------------------- legacy path
    def _legacy_generate(self, ctx, prompt, mt, temperature, seed,
                         stream, span=None) -> None:
        s = len(prompt)
        s_pad = _ceil_to(s, PROMPT_BUCKET)
        mt_pad = _ceil_to(mt, GEN_BUCKET)
        buf = jnp.zeros((s_pad,), jnp.int32).at[:s].set(
            jnp.asarray(prompt, dtype=jnp.int32))
        if not stream:
            toks = _decode_locked(ctx, buf, s, mt_pad, temperature,
                                  seed)
            self._json(200, {"tokens": [int(t) for t in toks[:mt]]})
            return
        cfg, params = ctx["cfg"], ctx["params"]
        key = jax.random.key(seed)
        # Prefill BEFORE the headers go out (clean-error contract, as
        # above). The model lock is held ONLY around compute, never
        # across socket writes — a stalled client (TCP backpressure on
        # emit) must not block other requests.
        key, k = jax.random.split(key)
        with ctx["lock"]:
            tok, cache = _prefill(cfg, params, buf, s_pad + mt_pad,
                                  jnp.int32(s), temperature, k)
            tok.block_until_ready()

        def tokens():
            nonlocal tok, cache, key
            for i in range(mt - 1):
                key, k2 = jax.random.split(key)
                with ctx["lock"]:
                    tok, cache = _gen_step(cfg, params, tok, cache,
                                           jnp.int32(s + i),
                                           temperature, k2)
                    tok.block_until_ready()
                yield int(tok[0])

        self._sse(None, [int(tok[0])], tokens(), span)

    # ------------------------------------------------------------- SSE
    def _sse(self, req, first_tokens, rest_iter, span=None,
             resume_len: int = 0) -> None:
        """SSE token stream: one `data: {"token": N}` event per decoded
        token, flushed as produced (chunked transfer), then
        `data: [DONE]` — the OpenAI-style contract LLM clients expect.
        A mid-stream failure drops the connection (a JSON error would
        corrupt the stream; the truncated stream is the signal)."""
        from skypilot_tpu.serve.load_balancer import (end_chunks,
                                                      write_chunk)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        if resume_len:
            # Acknowledges the resume admission to the splicing LB:
            # this stream's first event is the token at absolute
            # position ``resume_len``, not position 0.
            self.send_header("X-STPU-Resume", str(resume_len))
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def emit(payload: str) -> None:
            write_chunk(self.wfile, f"data: {payload}\n\n".encode())

        t0 = time.perf_counter() if span is not None else 0.0
        sent = 0
        try:
            for tok in first_tokens:
                emit(json.dumps({"token": int(tok)}))
                sent += 1
            for tok in rest_iter:
                emit(json.dumps({"token": int(tok)}))
                sent += 1
            if reqlog.ENABLED and req is not None:
                self._emit_stats_frame(req)
            emit("[DONE]")
            end_chunks(self.wfile)
            if span is not None:
                # Stream-delivery child span: first flush → [DONE].
                tracing.record_span("replica.stream", "replica",
                                    span.context(), start_mono=t0,
                                    attrs={"tokens": sent})
        except Exception:  # noqa: BLE001 — client gone / engine died
            if req is not None:
                req.cancel()  # free the slot; don't decode into a void
            self.close_connection = True
            if span is not None:
                tracing.record_span("replica.stream", "replica",
                                    span.context(), start_mono=t0,
                                    status="error",
                                    attrs={"tokens": sent,
                                           "aborted": True})

    def _emit_stats_frame(self, req) -> None:
        """Trailing ``event: stats`` SSE frame (reqlog armed only): the
        engine half of the wide-event request record, assembled by
        _free_slot and readable once the token iterator exhausts
        (_DONE is queued after the record is attached), enriched with
        the engine-level fields the slot cannot see (quant modes,
        restarts survived). The LB strips this frame from the client
        stream and folds it into its half; a legacy LB/custom client
        that does not strip must ignore non-``data:``-only SSE events
        per the SSE spec. Emission failures fall through to _sse's
        abort path like any other mid-stream write error."""
        from skypilot_tpu.serve.load_balancer import write_chunk
        half = getattr(req, "reqlog_record", None)
        if half is None:
            return
        engine = self.server_ctx.get("engine")
        if engine is not None:
            kv = engine.kv_config()
            half["kv_quant"] = bool(kv.get("kv_quant"))
            half["weight_quant"] = bool(kv.get("weight_quant"))
            half["kv_paged"] = bool(kv.get("paged"))
            half["restarts"] = int(getattr(engine, "restarts", 0))
        write_chunk(self.wfile,
                    b"event: stats\ndata: "
                    + json.dumps(half, default=str).encode()
                    + b"\n\n")


def preempt_notice_watch(notice: threading.Event,
                         poll: float = None) -> None:
    """Watch the provider's preemption metadata signal.

    Real deployments poll the cloud metadata endpoint (e.g. the GCE
    ``instance/preempted`` key); this repro's signal source is the
    fault point ``replica.preempt_notice`` — an injected fault IS the
    notice, which makes the whole replace-ahead path game-day drivable.
    On a notice: set the shared event (surfaced via /health as
    ``preempt_notice: true``) and stop — the notice is terminal for
    this replica's lifetime; the controller takes it from there.
    """
    if poll is None:
        poll = PREEMPT_NOTICE_POLL
    while not notice.is_set():
        try:
            if fault_injection.ENABLED:
                fault_injection.fire("replica.preempt_notice")
        except fault_injection.InjectedFault:
            notice.set()
            _PREEMPT_NOTICES.inc()
            return
        time.sleep(poll)


def serve(cfg: llama.LlamaConfig, params, port: int,
          ready_event: threading.Event = None,
          engine_slots: int = None,
          prefix_cache_mb: float = None,
          stream_timeout: float = None,
          engine_max_restarts: int = None,
          engine_restart_backoff: float = None,
          topology: "gang_replica.ReplicaTopology" = None,
          mesh=None, rules=None,
          gang: "gang_replica.GangLeader" = None,
          kv_paged: bool = None,
          kv_pool_blocks: int = None,
          kv_block_tokens: int = None,
          kv_quant: bool = None,
          weight_quant: bool = None,
          spec_k: int = None,
          spec_ngram: int = None,
          spec_min_accept: float = None
          ) -> ThreadingHTTPServer:
    """Start the replica server. ``engine_slots`` > 0 (default: env
    STPU_ENGINE_SLOTS or 4) serves through the continuous-batching
    decode engine; 0 keeps the legacy locked fixed-batch path.
    ``prefix_cache_mb`` (default: env STPU_PREFIX_CACHE_MB or 64) is
    the host-RAM KV spill tier budget in MiB under the paged pool's
    trie — evicted prefix blocks spill D2H and re-admit H2D on a warm
    match; 0 turns the tier off (dense mode has no trie and ignores
    it).
    ``stream_timeout`` (default: env STPU_STREAM_TIMEOUT or 600) is the
    per-token wait before a wedged engine surfaces as a clean error.
    ``kv_quant``/``weight_quant`` (default: env STPU_KV_QUANT /
    STPU_WEIGHT_QUANT or 0) serve int8 KV blocks / int8 params —
    ~2x KV capacity per HBM byte, parity-gated (NOT bit-identical).
    ``spec_k`` (default: env STPU_SPEC_K or 0) arms self-speculative
    decoding — k n-gram-drafted tokens per slot verified in one
    batched forward, bit-identical output.
    The engine runs under an EngineSupervisor: a crashed compute loop
    flips /health to 503 and is restarted with fresh state (capped
    backoff, ``engine_max_restarts`` consecutive fast failures →
    permanently down so the replica manager replaces the replica).

    Sharded replicas (gang_replica.py): ``mesh``/``rules`` make the
    engine tensor-parallel (params must arrive pre-sharded), and
    ``gang`` is host 0's GangLeader — admitted requests broadcast to
    followers, /health covers gang membership, drain propagates, and
    an engine crash-restart restarts every host's engine."""
    if engine_slots is None:
        engine_slots = ENGINE_SLOTS
    if prefix_cache_mb is None:
        prefix_cache_mb = ENGINE_PREFIX_CACHE_MB
    if stream_timeout is None:
        stream_timeout = STREAM_TIMEOUT_SECONDS
    if engine_max_restarts is None:
        engine_max_restarts = ENGINE_MAX_RESTARTS
    if engine_restart_backoff is None:
        engine_restart_backoff = ENGINE_RESTART_BACKOFF
    if kv_paged is None:
        kv_paged = ENGINE_KV_PAGED
    if kv_pool_blocks is None:
        kv_pool_blocks = ENGINE_KV_POOL_BLOCKS
    if kv_block_tokens is None:
        kv_block_tokens = ENGINE_KV_BLOCK_TOKENS
    if kv_quant is None:
        kv_quant = ENGINE_KV_QUANT
    if weight_quant is None:
        weight_quant = ENGINE_WEIGHT_QUANT
    if spec_k is None:
        spec_k = ENGINE_SPEC_K
    if spec_ngram is None:
        spec_ngram = ENGINE_SPEC_NGRAM
    if spec_min_accept is None:
        spec_min_accept = ENGINE_SPEC_MIN_ACCEPT
    ctx = {"cfg": cfg, "params": params, "lock": threading.Lock(),
           "ready": ready_event or threading.Event(), "engine": None,
           "stream_timeout": float(stream_timeout),
           "draining": threading.Event(), "gang": gang,
           "gang_admit_lock": threading.Lock(),
           "preempt_notice": threading.Event(),
           "inflight": [0], "inflight_lock": threading.Lock()}
    _TOPOLOGY_INFO.labels(
        hosts=str(topology.hosts if topology else 1),
        tp=str(topology.tp if topology else 1)).set(1)
    if engine_slots > 0:
        first_build = [True]

        def _engine_factory():
            if gang is not None and not first_build[0]:
                # Supervisor crash-restart: followers rebuild in
                # lockstep or the gang serves from desynced caches.
                gang.broadcast_restart()
            first_build[0] = False
            return decode_engine.DecodeEngine(
                cfg, params, slots=engine_slots,
                max_seq=MAX_PROMPT_TOKENS + MAX_GEN_TOKENS,
                prefix_cache_mb=prefix_cache_mb,
                mesh=mesh, rules=rules,
                paged=bool(kv_paged),
                kv_pool_blocks=int(kv_pool_blocks),
                kv_block_tokens=int(kv_block_tokens),
                kv_quant=bool(kv_quant),
                weight_quant=bool(weight_quant),
                spec_k=int(spec_k),
                spec_ngram=int(spec_ngram),
                spec_min_accept=float(spec_min_accept))

        ctx["engine"] = decode_engine.EngineSupervisor(
            _engine_factory, max_restarts=engine_max_restarts,
            backoff_base=engine_restart_backoff).start()

    handler = type("Handler", (_Handler,), {"server_ctx": ctx})
    httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
    httpd.engine = ctx["engine"]  # visible for shutdown/tests
    httpd.gang = gang

    def warmup():
        if gang is not None and not gang.wait_ready():
            # Probes keep seeing "warming" → the replica manager's
            # initial-delay deadline replaces the half-formed gang.
            return
        if ctx["engine"] is not None:
            ctx["engine"].warmup()
        else:
            buf = jnp.zeros((PROMPT_BUCKET,), jnp.int32)
            _decode_locked(ctx, buf, 8, GEN_BUCKET, 0.0,
                           0).block_until_ready()
        ctx["ready"].set()

    threading.Thread(target=warmup, daemon=True).start()
    if PREEMPT_NOTICE_POLL > 0:
        threading.Thread(target=preempt_notice_watch,
                         args=(ctx["preempt_notice"],),
                         daemon=True, name="preempt-watch").start()
    return httpd


def _resolve_kv(args) -> dict:
    """CLI flags > STPU_KV_* env > defaults — resolved ONCE and used
    for the local engine, the follower engines, and the gang kv-config
    handshake, so every host of a gang replica pages (or not)
    identically."""
    return {
        "paged": (bool(args.kv_paged) if args.kv_paged is not None
                  else ENGINE_KV_PAGED),
        "pool_blocks": (int(args.kv_pool_blocks)
                        if args.kv_pool_blocks is not None
                        else ENGINE_KV_POOL_BLOCKS),
        "block_tokens": (int(args.kv_block_tokens)
                         if args.kv_block_tokens is not None
                         else ENGINE_KV_BLOCK_TOKENS),
        "kv_quant": (bool(args.kv_quant) if args.kv_quant is not None
                     else ENGINE_KV_QUANT),
        "weight_quant": (bool(args.weight_quant)
                         if args.weight_quant is not None
                         else ENGINE_WEIGHT_QUANT),
        "spec_k": (int(args.spec_k) if args.spec_k is not None
                   else ENGINE_SPEC_K),
        "spec_ngram": (int(args.spec_ngram)
                       if args.spec_ngram is not None
                       else ENGINE_SPEC_NGRAM),
        "spec_min_accept": (float(args.spec_min_accept)
                            if args.spec_min_accept is not None
                            else ENGINE_SPEC_MIN_ACCEPT),
        "prefix_cache_mb": (float(args.prefix_cache_mb)
                            if args.prefix_cache_mb is not None
                            else ENGINE_PREFIX_CACHE_MB),
    }


def _resolve_topology(args) -> "gang_replica.ReplicaTopology":
    """CLI flags > STPU_REPLICA_TOPOLOGY env (stamped by the replica
    manager) > unsharded default."""
    if args.replica_hosts or args.tp:
        hosts = int(args.replica_hosts or 1)
        tp = int(args.tp or 1)
        return gang_replica.ReplicaTopology(
            hosts=hosts, ici_axes={"tp": tp} if tp > 1 else {})
    return (gang_replica.ReplicaTopology.from_env()
            or gang_replica.ReplicaTopology())


def _build_model(args):
    cfg = {
        "tiny": llama.LlamaConfig.tiny,
        "8b": llama.LlamaConfig.llama3_8b,
        "mixtral-tiny": mixtral.MixtralConfig.tiny,
        "mixtral-8x7b": mixtral.MixtralConfig.mixtral_8x7b,
        "gemma-tiny": gemma.GemmaConfig.tiny,
        "gemma-2b": gemma.GemmaConfig.gemma_2b,
        "gemma-7b": gemma.GemmaConfig.gemma_7b,
    }[args.model]()
    if args.dtype:
        cfg = dataclasses.replace(
            cfg, dtype={"bfloat16": jnp.bfloat16,
                        "float32": jnp.float32}[args.dtype])
    params = model_api(cfg).init(cfg, jax.random.PRNGKey(args.seed))
    return cfg, params


def _spawn_follower_cmd(args, rank: int, topology, leader_port: int):
    """Self-spawn dev gang (`--replica-hosts N` outside a gang launch):
    follower processes on THIS machine, carrying the same rank/env
    contract a gang-launched host would see (SKYPILOT_NODE_RANK +
    STPU_TRACE_CTX propagation)."""
    env = dict(os.environ)
    env[agent_constants.NODE_RANK] = str(rank)
    env[agent_constants.NUM_NODES] = str(topology.hosts)
    env[gang_replica.GANG_ADDR_ENV] = f"127.0.0.1:{leader_port}"
    env.update(tracing.child_env())
    argv = [sys.executable, "-m", "skypilot_tpu.recipes.serve_llm",
            "--model", args.model, "--seed", str(args.seed),
            "--port", str(args.port),
            "--replica-hosts", str(topology.hosts),
            "--tp", str(topology.tp)]
    if args.dtype:
        argv += ["--dtype", args.dtype]
    if args.engine_slots is not None:
        argv += ["--engine-slots", str(args.engine_slots)]
    if args.prefix_cache_mb is not None:
        argv += ["--prefix-cache-mb", str(args.prefix_cache_mb)]
    if args.kv_paged is not None:
        argv += ["--kv-paged", str(int(args.kv_paged))]
    if args.kv_pool_blocks is not None:
        argv += ["--kv-pool-blocks", str(args.kv_pool_blocks)]
    if args.kv_block_tokens is not None:
        argv += ["--kv-block-tokens", str(args.kv_block_tokens)]
    if args.kv_quant is not None:
        argv += ["--kv-quant", str(int(args.kv_quant))]
    if args.weight_quant is not None:
        argv += ["--weight-quant", str(int(args.weight_quant))]
    if args.spec_k is not None:
        argv += ["--spec-k", str(args.spec_k)]
    if args.spec_ngram is not None:
        argv += ["--spec-ngram", str(args.spec_ngram)]
    if args.spec_min_accept is not None:
        argv += ["--spec-min-accept", str(args.spec_min_accept)]
    return subprocess.Popen(argv, env=env, start_new_session=True)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model",
                   choices=["tiny", "8b", "mixtral-tiny", "mixtral-8x7b",
                            "gemma-tiny", "gemma-2b", "gemma-7b"],
                   default="tiny")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replica-hosts", type=int, default=None,
                   help="hosts in this replica's serving gang (default "
                        "env STPU_REPLICA_TOPOLOGY or 1). Outside a "
                        "gang launch, host 0 self-spawns the follower "
                        "processes — the single-machine dev analog of "
                        "a gang-scheduled slice")
    p.add_argument("--tp", type=int, default=None,
                   help="tensor-parallel degree over the replica's "
                        "devices (params + KV cache sharded via "
                        "parallel/mesh.py ShardingRules)")
    p.add_argument("--dtype", choices=["bfloat16", "float32"],
                   default=None,
                   help="override the model compute dtype (float32 "
                        "makes TP output bit-identical to the "
                        "unsharded engine; bfloat16 matches only to "
                        "bf16 rounding, like any resharding)")
    p.add_argument("--engine-slots", type=int, default=None,
                   help="decode-engine slots (0 = legacy locked path; "
                        "default env STPU_ENGINE_SLOTS or 4)")
    p.add_argument("--prefix-cache-mb", type=float, default=None,
                   help="host-RAM KV spill tier budget in MiB under "
                        "the paged trie: LRU-evicted prefix blocks "
                        "spill D2H and re-admit H2D on a warm match. "
                        "0 = tier off (evictions drop). Default env "
                        "STPU_PREFIX_CACHE_MB or 64")
    p.add_argument("--kv-paged", type=int, choices=(0, 1),
                   default=None,
                   help="1 serves from the paged KV block pool (one "
                        "device pool + per-slot block tables; prefix "
                        "hits alias blocks zero-copy; admission is "
                        "free-block based). Default env STPU_KV_PAGED "
                        "or 0. Bit-identical to the dense path")
    p.add_argument("--kv-pool-blocks", type=int, default=None,
                   help="paged-KV pool size in blocks incl. scratch "
                        "(0 = auto: slots * max_seq / block + 1, the "
                        "dense HBM budget; default env "
                        "STPU_KV_POOL_BLOCKS)")
    p.add_argument("--kv-block-tokens", type=int, default=None,
                   help="paged-KV block size in tokens (also the "
                        "prefill chunk; 0 = the default 64-token "
                        "chunk; default env STPU_KV_BLOCK_TOKENS)")
    p.add_argument("--kv-quant", type=int, choices=(0, 1),
                   default=None,
                   help="1 stores int8 KV blocks (+ per-block/head "
                        "scales) in the paged pool — ~2x blocks at "
                        "the same HBM budget; requires --kv-paged. "
                        "NOT bit-identical to bf16 (parity-gated by "
                        "tests/test_quant.py). Default env "
                        "STPU_KV_QUANT or 0")
    p.add_argument("--weight-quant", type=int, choices=(0, 1),
                   default=None,
                   help="1 serves int8 per-channel-quantized params "
                        "(matmul weights + embed/lm_head; norms, "
                        "LoRA and the MoE router stay full "
                        "precision). Default env STPU_WEIGHT_QUANT "
                        "or 0")
    p.add_argument("--spec-k", type=int, default=None,
                   help="speculative decoding: tokens drafted per "
                        "slot per step from the slot's own n-gram "
                        "history, verified in one batched forward (0 "
                        "disables; default env STPU_SPEC_K or 0). "
                        "Output is bit-identical either way — greedy "
                        "AND seeded sampling")
    p.add_argument("--spec-ngram", type=int, default=None,
                   help="draft matcher n-gram length (default env "
                        "STPU_SPEC_NGRAM or 3)")
    p.add_argument("--spec-min-accept", type=float, default=None,
                   help="per-slot acceptance-rate floor below which a "
                        "slot stops drafting (default env "
                        "STPU_SPEC_MIN_ACCEPT or 0.2)")
    p.add_argument("--stream-timeout", type=float, default=None,
                   help="seconds to wait for the NEXT token before "
                        "failing the request as engine-stalled "
                        "(default env STPU_STREAM_TIMEOUT or 600); "
                        "lower = faster wedged-device detection, "
                        "higher = tolerate slower models")
    p.add_argument("--engine-max-restarts", type=int, default=None,
                   help="consecutive fast engine-crash restarts before "
                        "the replica reports permanently unhealthy "
                        "(default env STPU_ENGINE_MAX_RESTARTS or 3)")
    p.add_argument("--lb-port", type=int, default=0,
                   help="also start an in-process load balancer on "
                        "this port fronting the replica — the "
                        "single-host dev analog of the `stpu serve` "
                        "data plane")
    p.add_argument("--lb-policy",
                   choices=sorted(
                       load_balancing_policies.POLICIES),
                   default=None,
                   help="routing policy for the --lb-port balancer; "
                        "prefix_affinity keeps shared-prefix traffic "
                        "on the replica whose prefix cache is warm. "
                        "Deployed services set "
                        "service.load_balancing_policy in the YAML "
                        "instead.")
    args = p.parse_args(argv)
    if args.lb_policy and not args.lb_port:
        p.error("--lb-policy only configures the --lb-port balancer; "
                "deployed services set service.load_balancing_policy "
                "in the YAML")

    topology = _resolve_topology(args)
    rank = int(os.environ.get(agent_constants.NODE_RANK, "0"))
    # Bring up jax.distributed from the gang env contract (federates
    # every host's chips on a real slice; non-fatal no-op elsewhere).
    distributed.initialize_from_env()
    cfg, params = _build_model(args)
    mesh, rules = gang_replica.build_mesh(topology)
    if mesh is not None:
        params = gang_replica.shard_params(cfg, params, mesh, rules)

    kv = _resolve_kv(args)
    # The handshake compares EFFECTIVE geometry (auto-sized pool
    # included), not raw knobs: two hosts with identical STPU_KV_* but
    # different slot counts would auto-size different pools and pass a
    # raw-knob check while diverging in admission.
    kv_geo = decode_engine.resolve_kv_geometry(
        slots=(args.engine_slots if args.engine_slots
               else ENGINE_SLOTS),
        max_seq=MAX_PROMPT_TOKENS + MAX_GEN_TOKENS,
        paged=kv["paged"],
        kv_pool_blocks=kv["pool_blocks"],
        kv_block_tokens=kv["block_tokens"],
        kv_quant=kv["kv_quant"], weight_quant=kv["weight_quant"],
        spec_k=kv["spec_k"], spec_ngram=kv["spec_ngram"],
        spec_min_accept=kv["spec_min_accept"],
        host_cache_mb=kv["prefix_cache_mb"],
        family=family_name(cfg),
        tp=(mesh.devices.size if mesh is not None else 1))
    if topology.hosts > 1 and rank > 0:
        # Non-zero hosts never front HTTP: they run the lockstep
        # follower loop against the leader's gang channel, mirroring
        # every submission into the same sharded engine.
        def _follower_engine():
            return decode_engine.DecodeEngine(
                cfg, params,
                slots=(args.engine_slots
                       if args.engine_slots else ENGINE_SLOTS),
                max_seq=MAX_PROMPT_TOKENS + MAX_GEN_TOKENS,
                prefix_cache_mb=kv["prefix_cache_mb"],
                mesh=mesh, rules=rules,
                paged=kv["paged"],
                kv_pool_blocks=kv["pool_blocks"],
                kv_block_tokens=kv["block_tokens"],
                kv_quant=kv["kv_quant"],
                weight_quant=kv["weight_quant"],
                spec_k=kv["spec_k"],
                spec_ngram=kv["spec_ngram"],
                spec_min_accept=kv["spec_min_accept"])

        sys.exit(gang_replica.follower_serve(
            _follower_engine, topology,
            gang_replica.follower_addr(args.port), rank,
            kv_config=kv_geo))

    gang = None
    if topology.hosts > 1:
        gang_launched = int(os.environ.get(
            agent_constants.NUM_NODES, "1")) > 1 and \
            not os.environ.get(gang_replica.GANG_ADDR_ENV)
        if gang_launched:
            # Followers derive the channel address from the env
            # contract (head ip + serving port + offset), so the bind
            # port is fixed.
            gang = gang_replica.GangLeader(
                topology,
                port=args.port + gang_replica.GANG_PORT_OFFSET,
                kv_config=kv_geo)
        else:
            # Self-spawn dev gang: OS-assigned channel port, followers
            # on this machine with the address stamped explicitly
            # (the lambda reads gang.port after construction binds it).
            gang = gang_replica.GangLeader(
                topology, spawn=lambda r: _spawn_follower_cmd(
                    args, r, topology, gang.port),
                kv_config=kv_geo)
            gang.start_followers()

    httpd = serve(cfg, params, args.port,
                  engine_slots=args.engine_slots,
                  prefix_cache_mb=kv["prefix_cache_mb"],
                  stream_timeout=args.stream_timeout,
                  engine_max_restarts=args.engine_max_restarts,
                  topology=topology, mesh=mesh, rules=rules,
                  gang=gang, kv_paged=kv["paged"],
                  kv_pool_blocks=kv["pool_blocks"],
                  kv_block_tokens=kv["block_tokens"],
                  kv_quant=kv["kv_quant"],
                  weight_quant=kv["weight_quant"],
                  spec_k=kv["spec_k"], spec_ngram=kv["spec_ngram"],
                  spec_min_accept=kv["spec_min_accept"])
    if gang is not None and httpd.engine is not None:
        # Whole-gang restart rebuilds host 0's engine too.
        gang.set_engine_reset(httpd.engine.restart_now)

    def _term(signum, frame):
        del signum, frame
        # Flight recorder first: a SIGTERM'd replica's last step ring
        # is the only record of what it was doing when the teardown /
        # scale-down landed (armed replicas only — an unarmed ring is
        # empty and a dump per routine teardown would just be noise).
        if stepstats.ENABLED:
            stepstats.dump_flight("sigterm")
        if gang is not None:
            # SIGTERM propagates to every host: followers get an
            # explicit shutdown, self-spawned ones are reaped — no
            # orphan processes.
            gang.shutdown()
        os._exit(143)
    signal.signal(signal.SIGTERM, _term)
    if args.lb_port:
        from skypilot_tpu.serve import load_balancer as lb_lib
        policy = load_balancing_policies.make_policy(args.lb_policy)
        policy.set_ready_replicas([f"http://127.0.0.1:{args.port}"])
        lb_lib.run_load_balancer(args.lb_port, policy,
                                 lb_lib.RequestRecorder())
        print(f"serve_llm: LB ({args.lb_policy or 'round_robin'}) "
              f"on :{args.lb_port}", flush=True)
    print(f"serve_llm: listening on :{args.port}", flush=True)
    httpd.serve_forever()


if __name__ == "__main__":
    main()
