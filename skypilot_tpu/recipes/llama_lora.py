"""Llama-3.1 LoRA finetune with crash-consistent checkpointing — the
flagship recipe.

Reference analog: llm/llama-3_1-finetuning/lora.yaml (torchtune LoRA with
checkpoints to a MOUNT-mode bucket, lines 24-30 — the reference's
checkpoint/resume pattern). Native version: low-rank adapters on the
attention projections of models/llama.py (applied as y@A@B inside
`lora_dense`, never materializing the full-rank delta), base weights
frozen via gradients taken only w.r.t. the adapter subtree, and
crash-consistent checkpoints (train/checkpoint.py: atomic rename +
checksummed manifest + async D2H) written to --checkpoint-dir every
``--ckpt-every`` steps — point it at a MOUNT-mode storage path
(examples/llama31_lora.yaml), or let a managed job stamp it via
$STPU_JOB_CKPT_DIR, and a preempted run resumes **bit-identically**:
the full train state (adapters, optimizer state, step, data position,
PRNG key) round-trips as raw bytes and the data stream replays from
the exact saved position.

Preemption grace: the agent layer forwards SIGTERM to this process
(agent/host_wrapper.py); the loop finishes the in-flight step, saves a
final checkpoint, and exits with rc 143 so the controller records an
interrupted (not succeeded) task with a fresh checkpoint to resume.

    python -m skypilot_tpu.recipes.llama_lora --model tiny --steps 20 \
        --checkpoint-dir /checkpoints/run1
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from skypilot_tpu.models import llama
from skypilot_tpu.observability import trainstats
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.recipes import synthetic_data
from skypilot_tpu.train import checkpoint as checkpoint_lib
from skypilot_tpu.train import distributed, trainer
from skypilot_tpu.utils import fault_injection


def init_lora(cfg: llama.LlamaConfig, rank: int, key: jax.Array,
              targets=("wq", "wk", "wv", "wo")) -> dict:
    """Adapter tree matching the stacked-layer layout: A ~ N(0, 1/d), B = 0
    (so the model starts exactly at the base weights)."""
    d = cfg.dim
    outs = {"wq": cfg.n_heads * cfg.head_dim,
            "wk": cfg.n_kv_heads * cfg.head_dim,
            "wv": cfg.n_kv_heads * cfg.head_dim,
            "wo": d}
    ins = {"wq": d, "wk": d, "wv": d, "wo": cfg.n_heads * cfg.head_dim}
    layers = {}
    keys = jax.random.split(key, len(targets))
    for k, name in zip(keys, targets):
        layers[name + "_lora_a"] = (
            jax.random.normal(k, (cfg.n_layers, ins[name], rank),
                              dtype=jnp.float32) *
            (ins[name] ** -0.5)).astype(cfg.dtype)
        layers[name + "_lora_b"] = jnp.zeros(
            (cfg.n_layers, rank, outs[name]), dtype=cfg.dtype)
    return {"layers": layers}


def merge_params(base: dict, lora: dict) -> dict:
    merged = dict(base)
    merged["layers"] = {**base["layers"], **lora["layers"]}
    return merged


def num_params(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def build_arg_parser(model_choices, default_model) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=model_choices,
                   default=default_model)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--lora-rank", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", type=str,
                   default=os.environ.get(checkpoint_lib.CKPT_DIR_ENV),
                   help="checkpoint root (train/checkpoint.py format); "
                        "a MOUNT-mode bucket path makes runs resumable "
                        "across preemptions. Defaults to "
                        f"${checkpoint_lib.CKPT_DIR_ENV}, which the "
                        "managed-jobs controller stamps per job.")
    p.add_argument("--ckpt-every", "--save-every", dest="ckpt_every",
                   type=int, default=10,
                   help="save a checkpoint every N steps (a preemption "
                        "replays at most N-1 steps)")
    p.add_argument("--ckpt-keep", type=int,
                   default=checkpoint_lib.DEFAULT_KEEP,
                   help="retention: newest checkpoints kept on disk")
    p.add_argument("--ckpt-sync", action="store_true",
                   help="write checkpoints synchronously on the step "
                        "path (default: async D2H + background write)")
    return p


def main(argv=None) -> dict:
    args = build_arg_parser(["tiny", "8b"], "tiny").parse_args(argv)
    cfg = (llama.LlamaConfig.llama3_8b() if args.model == "8b"
           else llama.LlamaConfig.tiny())
    return run_lora(llama, cfg, args, recipe_name="llama_lora")


def run_lora(model_lib, cfg, args, recipe_name: str) -> dict:
    """LoRA finetune loop, generic over the dense model families (llama
    and gemma share forward/param_specs/lora_dense; gemma_lora.py passes
    its module + config here)."""
    setup_t0 = time.perf_counter()
    ctx = distributed.initialize_from_env()
    if args.seq_len > cfg.max_seq_len:
        raise SystemExit(f"--seq-len {args.seq_len} exceeds model max "
                         f"{cfg.max_seq_len}")

    mesh = mesh_lib.make_mesh({"fsdp": -1})
    rules = mesh_lib.DEFAULT_RULES
    print(f"{recipe_name}: model={args.model} "  # noqa: stpu-host-sync startup banner of host ints, before the loop
          f"devices={jax.device_count()} "
          f"rank={ctx.rank}/{ctx.num_nodes}", flush=True)

    # Base params: sharded by the rule table (fsdp over embed axes); the
    # adapters are tiny and stay replicated.
    base_shardings = mesh_lib.tree_shardings(mesh, rules,
                                             model_lib.param_specs(cfg))
    base = jax.jit(lambda k: model_lib.init(cfg, k),
                   out_shardings=base_shardings)(
                       jax.random.PRNGKey(args.seed))
    lora = init_lora(cfg, args.lora_rank, jax.random.PRNGKey(args.seed + 1))
    tx = optax.adamw(args.lr)
    opt_state = tx.init(lora)
    start_step = 0
    data_start = 0
    # Training PRNG key: carried in the checkpoint (full-TrainState
    # contract) so any stochastic op added later resumes mid-stream
    # instead of restarting its randomness.
    rng_dev = jax.random.PRNGKey(args.seed + 2)
    train_rng = jax.device_get(rng_dev)

    def _state_tree(step: int):
        return {"lora": lora, "opt_state": opt_state,
                "step": np.int64(step), "data_pos": np.int64(step),
                "rng": train_rng}

    saver = None
    if args.checkpoint_dir:
        ckpt_dir = os.path.abspath(
            os.path.expanduser(args.checkpoint_dir))
        saver = checkpoint_lib.Checkpointer(
            ckpt_dir, keep=args.ckpt_keep,
            async_save=not args.ckpt_sync)
        restored = checkpoint_lib.restore_latest(ckpt_dir,
                                                 like=_state_tree(0))
        if restored is not None:
            # Restored leaves are host arrays; put them back as
            # replicated (uncommitted-on-one-device clashes with the
            # mesh-sharded base inside jit). Raw-byte round-trip: no
            # dtype cast, so resume is bit-identical.
            from jax.sharding import NamedSharding, PartitionSpec
            replicated = NamedSharding(mesh, PartitionSpec())
            def _replicate(x):
                return jax.device_put(jnp.asarray(x), replicated)
            lora = jax.tree.map(_replicate, restored.tree["lora"])
            opt_state = jax.tree.map(_replicate,
                                     restored.tree["opt_state"])
            train_rng = np.asarray(restored.tree["rng"])
            start_step = int(restored.tree["step"])
            # Data position is its own leaf (not derived from step):
            # loops where they diverge — gradient accumulation,
            # multi-epoch shuffles — resume the stream correctly.
            data_start = int(restored.tree["data_pos"])
            print(f"{recipe_name}: resumed from step {start_step}",
                  flush=True)

    def constrain(x, spec):
        return mesh_lib.constrain(x, mesh, rules, spec)

    @jax.jit
    def step_fn(base, lora, opt_state, tokens):
        base = jax.tree.map(jax.lax.stop_gradient, base)

        def loss_fn(lora):
            params = merge_params(base, lora)
            with mesh_lib.use_mesh(mesh, rules):
                logits = model_lib.forward(cfg, params, tokens,
                                       constrain=constrain)
            return trainer.cross_entropy_loss(logits[:, :-1],
                                              tokens[:, 1:])
        loss, grads = jax.value_and_grad(loss_fn)(lora)
        updates, opt_state = tx.update(grads, opt_state, lora)
        return optax.apply_updates(lora, updates), opt_state, loss

    data = synthetic_data.lm_tokens(args.seed + ctx.rank, 256,
                                    args.seq_len, cfg.vocab_size)
    # Preemption grace: the gang layer forwards SIGTERM here; finish
    # the in-flight step, save, exit 143 (train/checkpoint.py).
    grace = checkpoint_lib.GraceHandler.install()
    if trainstats.ENABLED:
        trainstats.configure(
            flops_per_token=cfg.flops_per_token(args.seq_len),
            peak_flops=trainstats.detect_peak_flops(),
            host=ctx.rank, hosts=ctx.num_nodes, job=recipe_name)
        if start_step:
            # A resumed run's setup wall (restore + re-init) is
            # restart downtime in the goodput breakdown.
            trainstats.note_downtime(time.perf_counter() - setup_t0)
    t0 = time.time()
    loss = None
    losses = []
    # One-step-delayed loss fetch: each iteration fetches the PREVIOUS
    # step's loss (already resident by then) so logging never syncs
    # the hot loop — float(loss) here would stall every step.
    delayed = trainer.DelayedFetch()
    tokens_per_step = args.batch_size * args.seq_len
    # On-device XLA profile of the training loop when STPU_PROFILE_DIR
    # is set (tensorboard-loadable); zero-cost no-op otherwise. The
    # `with` guarantees the trace is finalized even when a step raises.
    from skypilot_tpu import callbacks
    try:
        with callbacks.device_profile():
            # Data position: skip replays the RNG draws of the
            # completed steps, so step k's batch is the same whether
            # or not the run was interrupted (bit-identical resume).
            mark = time.perf_counter()
            for i, (tokens,) in enumerate(
                    synthetic_data.batches((data,), args.batch_size,
                                           args.seed,
                                           args.steps - start_step,
                                           skip=data_start)):
                data_wait = time.perf_counter() - mark
                step = start_step + i + 1
                step_t0 = time.perf_counter()
                lora, opt_state, loss = step_fn(base, lora, opt_state,
                                                jnp.asarray(tokens))
                dispatch_s = time.perf_counter() - step_t0
                fetched = None
                prev = delayed.rotate(loss)
                if prev is not None:
                    host_loss = jax.device_get(prev)
                    fetched = float(host_loss)
                    losses.append(fetched)
                device_s = None
                if trainstats.ENABLED and trainstats.sync_due():
                    device_s = trainstats.sampled_sync(loss)
                dur = time.perf_counter() - step_t0
                # Chaos seam: deterministic mid-epoch crash/preempt
                # (STPU_FAULTS="train.step:kill:skip=K").
                if fault_injection.ENABLED:
                    fault_injection.fire("train.step", step=step)
                # Snapshot ONCE: SIGTERM landing between a
                # save-condition read and the exit-branch read must not
                # skip the grace save while still reporting it happened.
                preempting = grace.triggered
                ckpt_s = 0.0
                if saver is not None and (step % args.ckpt_every == 0
                                          or step == args.steps
                                          or preempting):
                    ckpt_t0 = time.perf_counter()
                    saver.save(step, _state_tree(step))
                    ckpt_s = time.perf_counter() - ckpt_t0
                if trainstats.ENABLED:
                    trainstats.record_step(
                        step=step, dur=dur, tokens=tokens_per_step,
                        data_wait_s=data_wait, ckpt_s=ckpt_s,
                        dispatch_s=dispatch_s, device_s=device_s,
                        delayed=({"loss": fetched}
                                 if fetched is not None else None))
                if preempting:
                    if saver is not None:
                        saver.wait()  # the grace save must be durable
                    if trainstats.ENABLED:
                        trainstats.dump_flight("sigterm")
                    print(json.dumps({
                        "recipe": recipe_name, "preempted": True,
                        "resumed_from": start_step, "stopped_at": step,
                        "last_ckpt_step": (saver.last_saved_step
                                           if saver is not None
                                           else None),
                    }), flush=True)
                    raise SystemExit(
                        checkpoint_lib.GraceHandler.GRACE_EXIT_CODE)
                mark = time.perf_counter()
            # Drain the outstanding handle: the fetch both logs the
            # final loss and blocks until the last step completed.
            final = delayed.drain()
            if final is not None:
                host_loss = jax.device_get(final)
                losses.append(float(host_loss))
    except (Exception, KeyboardInterrupt) as e:
        if trainstats.ENABLED:
            trainstats.dump_flight("train_crash", error=repr(e))
        raise
    if saver is not None:
        saver.wait()

    wall = time.time() - t0  # noqa: stpu-wallclock workload wall-time report
    steps_run = max(args.steps - start_step, 0)
    tokens_seen = steps_run * args.batch_size * args.seq_len
    # Host copy for reporting: the adapters are tiny, and counting the
    # device tree directly would sync it into the metrics print.
    lora_host = jax.device_get(lora)
    metrics = {
        "recipe": recipe_name,
        "model": args.model,
        "lora_params": num_params(lora_host),
        "base_params": cfg.num_params(),
        "resumed_from": start_step,
        "last_ckpt_step": (saver.last_saved_step
                           if saver is not None else None),
        "steps": args.steps,
        "first_loss": losses[0] if losses else None,
        "final_loss": losses[-1] if losses else None,
        "tokens_per_second": round(tokens_seen / wall, 1) if wall else 0,
        "wall_seconds": round(wall, 2),
    }
    if trainstats.ENABLED:
        snap = trainstats.snapshot()
        metrics["train_mfu"] = snap["mfu"]
        metrics["train_goodput"] = snap["goodput"]
        metrics["train_step_seconds"] = snap["step_seconds_mean"]
        metrics["train_tokens_per_sec"] = snap["tokens_per_sec"]
        trainstats.flush()
    print(json.dumps(metrics), flush=True)
    return metrics


if __name__ == "__main__":
    main()
