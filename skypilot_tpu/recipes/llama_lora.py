"""Llama-3.1 LoRA finetune with bucket checkpointing — the flagship recipe.

Reference analog: llm/llama-3_1-finetuning/lora.yaml (torchtune LoRA with
checkpoints to a MOUNT-mode bucket, lines 24-30 — the reference's
checkpoint/resume pattern). Native version: low-rank adapters on the
attention projections of models/llama.py (applied as y@A@B inside
`lora_dense`, never materializing the full-rank delta), base weights
frozen via gradients taken only w.r.t. the adapter subtree, and orbax
checkpoints written to --checkpoint-dir — point it at a MOUNT-mode storage
path (examples/llama31_lora.yaml) and a preempted managed job resumes from
the last step.

    python -m skypilot_tpu.recipes.llama_lora --model tiny --steps 20 \
        --checkpoint-dir /checkpoints/run1
"""
from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.recipes import synthetic_data
from skypilot_tpu.train import distributed, trainer


def init_lora(cfg: llama.LlamaConfig, rank: int, key: jax.Array,
              targets=("wq", "wk", "wv", "wo")) -> dict:
    """Adapter tree matching the stacked-layer layout: A ~ N(0, 1/d), B = 0
    (so the model starts exactly at the base weights)."""
    d = cfg.dim
    outs = {"wq": cfg.n_heads * cfg.head_dim,
            "wk": cfg.n_kv_heads * cfg.head_dim,
            "wv": cfg.n_kv_heads * cfg.head_dim,
            "wo": d}
    ins = {"wq": d, "wk": d, "wv": d, "wo": cfg.n_heads * cfg.head_dim}
    layers = {}
    keys = jax.random.split(key, len(targets))
    for k, name in zip(keys, targets):
        layers[name + "_lora_a"] = (
            jax.random.normal(k, (cfg.n_layers, ins[name], rank),
                              dtype=jnp.float32) *
            (ins[name] ** -0.5)).astype(cfg.dtype)
        layers[name + "_lora_b"] = jnp.zeros(
            (cfg.n_layers, rank, outs[name]), dtype=cfg.dtype)
    return {"layers": layers}


def merge_params(base: dict, lora: dict) -> dict:
    merged = dict(base)
    merged["layers"] = {**base["layers"], **lora["layers"]}
    return merged


def num_params(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def build_arg_parser(model_choices, default_model) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=model_choices,
                   default=default_model)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--lora-rank", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="orbax checkpoint root; a MOUNT-mode bucket path "
                        "makes runs resumable across preemptions")
    p.add_argument("--save-every", type=int, default=10)
    return p


def main(argv=None) -> dict:
    args = build_arg_parser(["tiny", "8b"], "tiny").parse_args(argv)
    cfg = (llama.LlamaConfig.llama3_8b() if args.model == "8b"
           else llama.LlamaConfig.tiny())
    return run_lora(llama, cfg, args, recipe_name="llama_lora")


def run_lora(model_lib, cfg, args, recipe_name: str) -> dict:
    """LoRA finetune loop, generic over the dense model families (llama
    and gemma share forward/param_specs/lora_dense; gemma_lora.py passes
    its module + config here)."""
    ctx = distributed.initialize_from_env()
    if args.seq_len > cfg.max_seq_len:
        raise SystemExit(f"--seq-len {args.seq_len} exceeds model max "
                         f"{cfg.max_seq_len}")

    mesh = mesh_lib.make_mesh({"fsdp": -1})
    rules = mesh_lib.DEFAULT_RULES
    print(f"{recipe_name}: model={args.model} devices={jax.device_count()} "
          f"rank={ctx.rank}/{ctx.num_nodes}", flush=True)

    # Base params: sharded by the rule table (fsdp over embed axes); the
    # adapters are tiny and stay replicated.
    base_shardings = mesh_lib.tree_shardings(mesh, rules,
                                             model_lib.param_specs(cfg))
    base = jax.jit(lambda k: model_lib.init(cfg, k),
                   out_shardings=base_shardings)(
                       jax.random.PRNGKey(args.seed))
    lora = init_lora(cfg, args.lora_rank, jax.random.PRNGKey(args.seed + 1))
    tx = optax.adamw(args.lr)
    opt_state = tx.init(lora)
    start_step = 0

    mgr = ocp = None
    if args.checkpoint_dir:
        import orbax.checkpoint as ocp
        mgr = ocp.CheckpointManager(
            os.path.abspath(os.path.expanduser(args.checkpoint_dir)),
            options=ocp.CheckpointManagerOptions(max_to_keep=3))
        latest = mgr.latest_step()
        if latest is not None:
            restored = mgr.restore(
                latest, args=ocp.args.StandardRestore(
                    {"lora": lora, "opt_state": opt_state}))
            # Restored arrays land on one device; put them back as
            # replicated (uncommitted-on-one-device clashes with the
            # mesh-sharded base inside jit).
            from jax.sharding import NamedSharding, PartitionSpec
            replicated = NamedSharding(mesh, PartitionSpec())
            def _replicate(ref, x):
                return jax.device_put(jnp.asarray(x, dtype=ref.dtype),
                                      replicated)
            lora = jax.tree.map(_replicate, lora, restored["lora"])
            opt_state = jax.tree.map(_replicate, opt_state,
                                     restored["opt_state"])
            start_step = latest
            print(f"{recipe_name}: resumed from step {latest}", flush=True)

    def constrain(x, spec):
        return mesh_lib.constrain(x, mesh, rules, spec)

    @jax.jit
    def step_fn(base, lora, opt_state, tokens):
        base = jax.tree.map(jax.lax.stop_gradient, base)

        def loss_fn(lora):
            params = merge_params(base, lora)
            with mesh_lib.use_mesh(mesh, rules):
                logits = model_lib.forward(cfg, params, tokens,
                                       constrain=constrain)
            return trainer.cross_entropy_loss(logits[:, :-1],
                                              tokens[:, 1:])
        loss, grads = jax.value_and_grad(loss_fn)(lora)
        updates, opt_state = tx.update(grads, opt_state, lora)
        return optax.apply_updates(lora, updates), opt_state, loss

    data = synthetic_data.lm_tokens(args.seed + ctx.rank, 256,
                                    args.seq_len, cfg.vocab_size)
    t0 = time.time()
    loss = None
    losses = []
    # On-device XLA profile of the training loop when STPU_PROFILE_DIR
    # is set (tensorboard-loadable); zero-cost no-op otherwise. The
    # `with` guarantees the trace is finalized even when a step raises.
    from skypilot_tpu import callbacks
    with callbacks.device_profile():
        for i, (tokens,) in enumerate(
                synthetic_data.batches((data,), args.batch_size,
                                       args.seed,
                                       args.steps - start_step)):
            step = start_step + i + 1
            lora, opt_state, loss = step_fn(base, lora, opt_state,
                                            jnp.asarray(tokens))
            losses.append(float(loss))
            if mgr is not None and (step % args.save_every == 0
                                    or step == args.steps):
                mgr.save(step, args=ocp.args.StandardSave(
                    {"lora": lora, "opt_state": opt_state}))
        if loss is not None:
            loss.block_until_ready()
    if mgr is not None:
        mgr.wait_until_finished()

    wall = time.time() - t0
    steps_run = max(args.steps - start_step, 0)
    tokens_seen = steps_run * args.batch_size * args.seq_len
    metrics = {
        "recipe": recipe_name,
        "model": args.model,
        "lora_params": num_params(lora),
        "base_params": cfg.num_params(),
        "resumed_from": start_step,
        "steps": args.steps,
        "first_loss": losses[0] if losses else None,
        "final_loss": losses[-1] if losses else None,
        "tokens_per_second": round(tokens_seen / wall, 1) if wall else 0,
        "wall_seconds": round(wall, 2),
    }
    print(json.dumps(metrics), flush=True)
    return metrics


if __name__ == "__main__":
    main()
