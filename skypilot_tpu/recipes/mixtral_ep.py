"""Mixtral-8x7B expert-parallel pretraining step — the MoE named config.

Reference analog: llm/mixtral/ (the reference hands vLLM a set of GPUs and
vLLM does the expert math internally). Native version: models/mixtral.py's
one-hot dispatch/combine MoE trained under an ep-sharded mesh; XLA inserts
the expert all-to-alls over ICI.

    python -m skypilot_tpu.recipes.mixtral_ep --model tiny --steps 10
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from skypilot_tpu.models import mixtral
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.recipes import synthetic_data
from skypilot_tpu.train import distributed, trainer


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=["tiny", "8x7b"], default="tiny")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--ep", type=int, default=-1,
                   help="expert-parallel axis size (-1: all devices)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    ctx = distributed.initialize_from_env()
    cfg = (mixtral.MixtralConfig.mixtral_8x7b() if args.model == "8x7b"
           else mixtral.MixtralConfig.tiny())

    n_dev = jax.device_count()
    ep = args.ep if args.ep != -1 else min(n_dev, cfg.n_experts)
    mesh = mesh_lib.make_mesh({"dp": -1, "ep": ep})
    rules = mesh_lib.DEFAULT_RULES
    print(f"mixtral_ep: model={args.model} mesh={dict(mesh.shape)} "
          f"rank={ctx.rank}/{ctx.num_nodes}", flush=True)

    shardings = mesh_lib.tree_shardings(mesh, rules,
                                        mixtral.param_specs(cfg))
    params = jax.jit(lambda k: mixtral.init(cfg, k),
                     out_shardings=shardings)(
                         jax.random.PRNGKey(args.seed))
    tx = trainer.make_optimizer(trainer.TrainConfig(total_steps=args.steps))
    state = trainer.init_train_state(params, tx)

    step = trainer.make_train_step(
        lambda p, tokens, constrain: mixtral.forward(
            cfg, p, tokens, constrain=constrain),
        tx, mesh, rules)

    data = synthetic_data.lm_tokens(args.seed, 128, args.seq_len,
                                    cfg.vocab_size)
    t0 = time.time()
    metrics = None
    losses = []
    for (tokens,) in synthetic_data.batches((data,), args.batch_size,
                                            args.seed, args.steps):
        state, metrics = step(state, {"tokens": jnp.asarray(tokens)})
        losses.append(float(metrics["loss"]))
    jax.block_until_ready(state.params)
    wall = time.time() - t0  # noqa: stpu-wallclock workload wall-time report

    out = {
        "recipe": "mixtral_ep",
        "model": args.model,
        "mesh": dict(mesh.shape),
        "steps": args.steps,
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "aux_loss": float(metrics["aux_loss"]),
        "tokens_per_second": round(
            args.steps * args.batch_size * args.seq_len / wall, 1),
        "wall_seconds": round(wall, 2),
    }
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
