"""Mixtral-8x7B expert-parallel pretraining step — the MoE named config.

Reference analog: llm/mixtral/ (the reference hands vLLM a set of GPUs and
vLLM does the expert math internally). Native version: models/mixtral.py's
one-hot dispatch/combine MoE trained under an ep-sharded mesh; XLA inserts
the expert all-to-alls over ICI.

    python -m skypilot_tpu.recipes.mixtral_ep --model tiny --steps 10
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from skypilot_tpu.models import mixtral
from skypilot_tpu.observability import trainstats
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.recipes import synthetic_data
from skypilot_tpu.train import distributed, trainer


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=["tiny", "8x7b"], default="tiny")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--ep", type=int, default=-1,
                   help="expert-parallel axis size (-1: all devices)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--with-grad-norm", action="store_true",
                   help="report grad_norm per step (an EXTRA full "
                        "sweep over every gradient — pure MFU tax, "
                        "so benches leave it off)")
    args = p.parse_args(argv)

    ctx = distributed.initialize_from_env()
    cfg = (mixtral.MixtralConfig.mixtral_8x7b() if args.model == "8x7b"
           else mixtral.MixtralConfig.tiny())

    n_dev = jax.device_count()
    ep = args.ep if args.ep != -1 else min(n_dev, cfg.n_experts)
    mesh = mesh_lib.make_mesh({"dp": -1, "ep": ep})
    rules = mesh_lib.DEFAULT_RULES
    print(f"mixtral_ep: model={args.model} "  # noqa: stpu-host-sync startup banner of host ints, before the loop
          f"mesh={dict(mesh.shape)} "
          f"rank={ctx.rank}/{ctx.num_nodes}", flush=True)

    shardings = mesh_lib.tree_shardings(mesh, rules,
                                        mixtral.param_specs(cfg))
    params = jax.jit(lambda k: mixtral.init(cfg, k),
                     out_shardings=shardings)(
                         jax.random.PRNGKey(args.seed))
    tx = trainer.make_optimizer(trainer.TrainConfig(total_steps=args.steps))
    state = trainer.init_train_state(params, tx)

    # grad_norm defaults OFF here: its extra sweep over every gradient
    # is pure MFU tax on the bench path (trainer.make_train_step).
    step = trainer.make_train_step(
        lambda p, tokens, constrain: mixtral.forward(
            cfg, p, tokens, constrain=constrain),
        tx, mesh, rules, with_grad_norm=args.with_grad_norm)

    if trainstats.ENABLED:
        trainstats.configure(
            flops_per_token=cfg.flops_per_token(),
            peak_flops=trainstats.detect_peak_flops(),
            host=ctx.rank, hosts=ctx.num_nodes, job="mixtral_ep")
    data = synthetic_data.lm_tokens(args.seed, 128, args.seq_len,
                                    cfg.vocab_size)
    t0 = time.time()
    aux_loss = None
    losses = []
    # One-step-delayed metrics fetch: each iteration fetches the
    # PREVIOUS step's metrics dict (already resident) — float()-ing
    # this step's loss here would sync the device every iteration.
    delayed = trainer.DelayedFetch()
    tokens_per_step = args.batch_size * args.seq_len
    try:
        mark = time.perf_counter()
        for i, (tokens,) in enumerate(
                synthetic_data.batches((data,), args.batch_size,
                                       args.seed, args.steps)):
            data_wait = time.perf_counter() - mark
            step_t0 = time.perf_counter()
            state, metrics = step(state, {"tokens": jnp.asarray(tokens)})
            dispatch_s = time.perf_counter() - step_t0
            fetched = None
            grad_norm = None
            prev = delayed.rotate(metrics)
            if prev is not None:
                host_m = jax.device_get(prev)
                fetched = float(host_m["loss"])
                losses.append(fetched)
                aux_loss = float(host_m["aux_loss"])
                if "grad_norm" in host_m:
                    grad_norm = float(host_m["grad_norm"])
            device_s = None
            if trainstats.ENABLED and trainstats.sync_due():
                device_s = trainstats.sampled_sync(metrics["loss"])
            dur = time.perf_counter() - step_t0
            if trainstats.ENABLED:
                trainstats.record_step(
                    step=i + 1, dur=dur, tokens=tokens_per_step,
                    data_wait_s=data_wait, dispatch_s=dispatch_s,
                    device_s=device_s,
                    delayed=({"loss": fetched, "grad_norm": grad_norm}
                             if fetched is not None else None))
            mark = time.perf_counter()
        # Drain: fetching the final metrics blocks until the last
        # step's results are ready (the old end-of-run fence).
        final = delayed.drain()
        if final is not None:
            host_m = jax.device_get(final)
            losses.append(float(host_m["loss"]))
            aux_loss = float(host_m["aux_loss"])
    except (Exception, KeyboardInterrupt) as e:
        if trainstats.ENABLED:
            trainstats.dump_flight("train_crash", error=repr(e))
        raise
    wall = time.time() - t0  # noqa: stpu-wallclock workload wall-time report

    out = {
        "recipe": "mixtral_ep",
        "model": args.model,
        "mesh": dict(mesh.shape),
        "steps": args.steps,
        "first_loss": losses[0] if losses else None,
        "final_loss": losses[-1] if losses else None,
        "aux_loss": aux_loss,
        "tokens_per_second": round(
            args.steps * args.batch_size * args.seq_len / wall, 1),
        "wall_seconds": round(wall, 2),
    }
    if trainstats.ENABLED:
        snap = trainstats.snapshot()
        out["train_mfu"] = snap["mfu"]
        out["train_goodput"] = snap["goodput"]
        out["train_step_seconds"] = snap["step_seconds_mean"]
        out["train_tokens_per_sec"] = snap["tokens_per_sec"]
        trainstats.flush()
    print(json.dumps(out), flush=True)  # noqa: stpu-host-sync host metrics report after the loop (mesh shape is host ints)
    return out


if __name__ == "__main__":
    main()
