"""Flax MNIST — the minimum end-to-end "aha" recipe.

Reference analog: examples/tpu/tpuvm_mnist.yaml (clones the flax repo and
runs its MNIST example on a TPU VM). Native version: a small flax CNN,
jit-compiled, sharded over whatever devices the host has; launched by
examples/tpu_mnist.yaml.

    python -m skypilot_tpu.recipes.mnist --steps 300
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from skypilot_tpu import callbacks as sky_callback
from skypilot_tpu.recipes import synthetic_data
from skypilot_tpu.train import distributed


class CNN(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Conv(features=16, kernel_size=(3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=32, kernel_size=(3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(features=128)(x)
        x = nn.relu(x)
        return nn.Dense(features=10)(x)


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    ctx = distributed.initialize_from_env()
    print(f"mnist: devices={jax.devices()} rank={ctx.rank}/"
          f"{ctx.num_nodes}", flush=True)

    model = CNN()
    images, labels = synthetic_data.mnist_like(args.seed, 8192)
    test_x, test_y = synthetic_data.mnist_like(args.seed + 1, 1024)

    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, 28, 28, 1)))
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(params):
            logits = model.apply(params, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def accuracy(params, x, y):
        return jnp.mean(jnp.argmax(model.apply(params, x), -1) == y)

    sky_callback.init(total_steps=args.steps)
    t0 = time.time()
    loss = None
    for x, y in sky_callback.step_iterator(
            synthetic_data.batches((images, labels), args.batch_size,
                                   args.seed, args.steps)):
        params, opt_state, loss = step(params, opt_state, x, y)
    loss.block_until_ready()
    sky_callback.flush()

    acc = float(accuracy(params, test_x, test_y))
    metrics = {
        "recipe": "mnist",
        "steps": args.steps,
        "final_loss": float(loss),
        "test_accuracy": acc,
        "wall_seconds": round(time.time() - t0, 2),  # noqa: stpu-wallclock workload wall-time report
    }
    print(json.dumps(metrics), flush=True)
    if args.steps >= 100 and acc < 0.8:
        raise SystemExit(f"mnist accuracy {acc:.3f} below 0.8 — "
                         f"training did not converge")
    return metrics


if __name__ == "__main__":
    main()
