"""Gemma LoRA finetune — the llama_lora machinery on the gemma family.

Reference analog: llm/gemma (the reference's Gemma recipes launch HF
containers; /root/reference/llm/gemma/README.md). Native version: the
shared LoRA loop (recipes/llama_lora.run_lora) with gemma's config —
adapters ride the same ``lora_dense`` seam in the shared attention
blocks, so the only gemma-specific code is model selection. Checkpoints
to a MOUNT-mode bucket resume across preemptions exactly like the llama
recipe (examples/gemma_lora.yaml).

    python -m skypilot_tpu.recipes.gemma_lora --model tiny --steps 20 \
        --checkpoint-dir /checkpoints/run1
"""
from __future__ import annotations

from skypilot_tpu.models import gemma
from skypilot_tpu.recipes import llama_lora


def main(argv=None) -> dict:
    args = llama_lora.build_arg_parser(
        ["tiny", "2b", "7b"], "tiny").parse_args(argv)
    cfg = {
        "tiny": gemma.GemmaConfig.tiny,
        "2b": gemma.GemmaConfig.gemma_2b,
        "7b": gemma.GemmaConfig.gemma_7b,
    }[args.model]()
    return llama_lora.run_lora(gemma, cfg, args,
                               recipe_name="gemma_lora")


if __name__ == "__main__":
    main()
