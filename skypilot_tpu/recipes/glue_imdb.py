"""Text-classification finetune — huggingface_glue_imdb named config.

Reference analog: examples/huggingface_glue_imdb_app.yaml (BERT finetune on
IMDB via HF Trainer). Native version: a small transformer encoder
classifier in flax over hermetic sentiment data; 1 node, CPU-runnable (the
BASELINE.md contract for this config).

    python -m skypilot_tpu.recipes.glue_imdb --steps 200
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from skypilot_tpu.recipes import synthetic_data
from skypilot_tpu.train import distributed


class EncoderBlock(nn.Module):
    dim: int
    heads: int

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm()(x)
        y = nn.MultiHeadDotProductAttention(num_heads=self.heads)(y, y)
        x = x + y
        y = nn.LayerNorm()(x)
        y = nn.Dense(self.dim * 4)(y)
        y = nn.gelu(y)
        return x + nn.Dense(self.dim)(y)


class TextClassifier(nn.Module):
    vocab_size: int = 1000
    dim: int = 64
    heads: int = 4
    n_layers: int = 2
    n_classes: int = 2

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(self.vocab_size, self.dim)(tokens)
        pos = self.param("pos", nn.initializers.normal(0.02),
                         (tokens.shape[-1], self.dim))
        x = x + pos
        for _ in range(self.n_layers):
            x = EncoderBlock(self.dim, self.heads)(x)
        x = nn.LayerNorm()(x)
        return nn.Dense(self.n_classes)(x.mean(axis=1))


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    distributed.initialize_from_env()
    model = TextClassifier()
    tokens, labels = synthetic_data.imdb_like(args.seed, 4096,
                                              seq_len=args.seq_len)
    test_x, test_y = synthetic_data.imdb_like(args.seed + 1, 512,
                                              seq_len=args.seq_len)

    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, args.seq_len), jnp.int32))
    tx = optax.adamw(args.lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(params):
            logits = model.apply(params, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def accuracy(params, x, y):
        return jnp.mean(jnp.argmax(model.apply(params, x), -1) == y)

    t0 = time.time()
    loss = None
    for x, y in synthetic_data.batches((tokens, labels), args.batch_size,
                                       args.seed, args.steps):
        params, opt_state, loss = step(params, opt_state, x, y)
    loss.block_until_ready()

    acc = float(accuracy(params, test_x, test_y))
    metrics = {
        "recipe": "glue_imdb",
        "steps": args.steps,
        "final_loss": float(loss),
        "test_accuracy": acc,
        "wall_seconds": round(time.time() - t0, 2),  # noqa: stpu-wallclock workload wall-time report
    }
    print(json.dumps(metrics), flush=True)
    if args.steps >= 150 and acc < 0.75:
        raise SystemExit(f"glue_imdb accuracy {acc:.3f} below 0.75")
    return metrics


if __name__ == "__main__":
    main()
