"""Task: the declarative unit of work.

Reference analog: sky/task.py (Task:171, from_yaml_config:347,
set_resources:629, set_file_mounts:707, __rshift__:1159). Same surface —
name/setup/run/num_nodes/envs/workdir/file_mounts/resources/service — with
one TPU-native semantic shift: ``num_nodes`` counts *slices* (each slice's
host fan-out is implicit in the accelerator, e.g. tpu-v5p-64 = 8 hosts that
always gang together).
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Set, Tuple, Union

import yaml

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu.resources import Resources
from skypilot_tpu.utils import schemas

_VALID_NAME_RE = re.compile(r"^[a-zA-Z0-9]([-_.a-zA-Z0-9]*[a-zA-Z0-9])?$")

CommandOrGen = Union[str, Callable[[int, List[str]], Optional[str]], None]


class Task:
    """A coarse-grained unit: setup + run on num_nodes slices."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: CommandOrGen = None,
        envs: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: int = 1,
    ):
        self.name = name
        if name is not None and not _VALID_NAME_RE.match(name):
            raise exceptions.InvalidTaskError(
                f"Invalid task name {name!r}")
        self.setup = setup
        self.run = run
        self.envs: Dict[str, str] = {
            k: str(v) for k, v in (envs or {}).items()}
        self.workdir = workdir
        if num_nodes < 1:
            raise exceptions.InvalidTaskError(
                f"num_nodes must be >= 1, got {num_nodes}")
        self.num_nodes = num_nodes
        self.file_mounts: Dict[str, str] = {}
        self.storage_mounts: Dict[str, Any] = {}  # path -> data.Storage
        self.resources: Tuple[Resources, ...] = (Resources(),)
        self.service: Optional[Any] = None        # serve.SkyServiceSpec
        self.best_resources: Optional[Resources] = None
        self.estimated_runtime_seconds: Optional[float] = None

        # Auto-register with an ambient `with Dag():` block.
        current = dag_lib.get_current_dag()
        if current is not None:
            current.add(self)

    # ------------------------------------------------------------------
    def set_resources(
        self, resources: Union[Resources, Set[Resources],
                               List[Resources], Tuple[Resources, ...]]
    ) -> "Task":
        if isinstance(resources, Resources):
            resources = (resources,)
        self.resources = tuple(resources)
        if not self.resources:
            raise exceptions.InvalidTaskError("Empty resources set")
        return self

    def set_file_mounts(self, mounts: Optional[Dict[str, str]]) -> "Task":
        if mounts is None:
            self.file_mounts = {}
            return self
        for dst, src in mounts.items():
            if not isinstance(src, str):
                raise exceptions.InvalidTaskError(
                    f"file_mounts[{dst!r}] must be a path/URI string; use "
                    f"set_storage_mounts for storage objects")
        self.file_mounts = dict(mounts)
        return self

    def set_storage_mounts(self, mounts: Optional[Dict[str, Any]]) -> "Task":
        self.storage_mounts = dict(mounts or {})
        return self

    def update_envs(self, envs: Dict[str, str]) -> "Task":
        self.envs.update({k: str(v) for k, v in envs.items()})
        return self

    @property
    def uses_spot(self) -> bool:
        """Whether this task requests spot (preemptible) capacity — the
        single source of truth for serve's pool placement and the
        fallback-spec validation."""
        return bool(self.resources) and \
            next(iter(self.resources)).use_spot

    def set_time_estimator(
            self, fn: Callable[[Resources], float]) -> "Task":
        self._time_estimator = fn
        return self

    def estimate_runtime(self, resources: Resources) -> float:
        fn = getattr(self, "_time_estimator", None)
        if fn is None:
            # Default 1 hour, matching the reference's assumption when no
            # estimator is given (sky/optimizer.py:255-263).
            return 3600.0
        return float(fn(resources))

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any],
                         env_overrides: Optional[Dict[str, str]] = None
                         ) -> "Task":
        schemas.validate_task(config)
        envs = dict(config.get("envs") or {})
        if env_overrides:
            envs.update(env_overrides)
        missing = [k for k, v in envs.items() if v is None]
        if missing:
            raise exceptions.InvalidTaskError(
                f"Environment variable(s) {missing} need values; pass "
                f"--env {missing[0]}=... or set a default in the YAML.")
        task = cls(
            name=config.get("name"),
            setup=config.get("setup"),
            run=config.get("run"),
            envs=envs,
            workdir=config.get("workdir"),
            num_nodes=config.get("num_nodes", 1),
        )

        res_config = dict(config.get("resources") or {})
        any_of = res_config.pop("any_of", None)
        if any_of:
            candidates = []
            for override in any_of:
                merged = {**res_config, **override}
                candidates.append(Resources.from_yaml_config(merged))
            task.set_resources(tuple(candidates))
        else:
            task.set_resources(Resources.from_yaml_config(res_config))

        file_mounts: Dict[str, str] = {}
        storage_specs: Dict[str, Dict] = {}
        for dst, src in (config.get("file_mounts") or {}).items():
            if isinstance(src, str):
                file_mounts[dst] = src
            else:
                storage_specs[dst] = src
        task.set_file_mounts(file_mounts)
        if storage_specs:
            from skypilot_tpu.data import storage as storage_lib
            task.set_storage_mounts({
                dst: storage_lib.Storage.from_yaml_config(spec)
                for dst, spec in storage_specs.items()})

        if config.get("service"):
            from skypilot_tpu.serve import service_spec
            task.service = service_spec.SkyServiceSpec.from_yaml_config(
                config["service"])
        return task

    @classmethod
    def from_yaml(cls, path: str,
                  env_overrides: Optional[Dict[str, str]] = None) -> "Task":
        with open(os.path.expanduser(path)) as f:
            config = yaml.safe_load(f)
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f"{path} does not contain a YAML mapping")
        return cls.from_yaml_config(config, env_overrides)

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.name:
            out["name"] = self.name
        if self.workdir:
            out["workdir"] = self.workdir
        if self.num_nodes != 1:
            out["num_nodes"] = self.num_nodes
        if len(self.resources) == 1:
            res = self.resources[0].to_yaml_config()
        else:
            res = {"any_of": [r.to_yaml_config() for r in self.resources]}
        if res:
            out["resources"] = res
        if self.envs:
            out["envs"] = dict(self.envs)
        mounts: Dict[str, Any] = dict(self.file_mounts)
        for dst, store in self.storage_mounts.items():
            mounts[dst] = store.to_yaml_config()
        if mounts:
            out["file_mounts"] = mounts
        if self.setup:
            out["setup"] = self.setup
        if self.run is not None and isinstance(self.run, str):
            out["run"] = self.run
        if self.service is not None:
            out["service"] = self.service.to_yaml_config()
        return out

    def to_yaml(self, path: str) -> None:
        with open(os.path.expanduser(path), "w") as f:
            yaml.safe_dump(self.to_yaml_config(), f, sort_keys=False)

    # ------------------------------------------------------------------
    def __rshift__(self, other: "Task") -> "Task":
        current = dag_lib.get_current_dag()
        if current is None:
            raise exceptions.DagError(
                "task_a >> task_b requires an active `with Dag():` block")
        current.add_edge(self, other)
        return other

    def __repr__(self) -> str:
        res = self.best_resources or (
            self.resources[0] if len(self.resources) == 1
            else f"{len(self.resources)} candidates")
        n = f", num_nodes={self.num_nodes}" if self.num_nodes != 1 else ""
        return f"Task({self.name or '<unnamed>'}: {res}{n})"
