"""The STPU_* environment-variable contract — one registry, one truth.

Every ``STPU_*`` knob the framework reads is declared here with its
default and a one-line doc. The ``stpu-env`` analyzer
(``analysis/rules_env.py``) statically cross-checks every
``os.environ``/``os.getenv`` read in ``skypilot_tpu/`` against this
table: an unregistered read fails, and a read whose inline default
literal disagrees with the registered default fails — the config-drift
failure mode where two layers parse the same knob differently.

``stpu check --env-table`` renders the registry as the markdown knob
table embedded in docs/static-analysis.md (a tier-1 test keeps the doc
byte-identical to :func:`render_markdown_table`, so it can never
drift).

Stdlib-only and import-light: the analyzer and the CLI both import it,
and neither wants jax.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

PREFIX = "STPU_"


@dataclasses.dataclass(frozen=True)
class EnvKnob:
    name: str
    # The default literal as it appears at read sites (``None`` = the
    # knob is unset-sensitive: code branches on presence, not value).
    default: Optional[str]
    doc: str


def _k(name: str, default: Optional[str], doc: str) -> EnvKnob:
    if not name.startswith(PREFIX):
        raise ValueError(f"env knob {name!r} must start with {PREFIX}")
    if not doc.strip():
        raise ValueError(f"env knob {name!r} needs a doc line")
    return EnvKnob(name, default, doc)


_KNOBS = (
    # ------------------------------------------------ client state
    _k("STPU_HOME", "~/.stpu",
       "Client state root (utils/paths.py). Controllers export the "
       "expanded form $HOME/.stpu — same directory after expanduser."),
    _k("STPU_SSH_CONFIG", "~/.ssh/config",
       "SSH config parsed for cluster host aliases."),
    _k("STPU_BUCKET_ROOT", None,
       "Global local-bucket namespace root; controllers export "
       "$STPU_HOME/buckets so head and client resolve one namespace."),
    _k("STPU_TIMELINE_FILE", None,
       "Write a Chrome-trace timeline of CLI phases to this path."),
    # ------------------------------------------------ observability
    _k("STPU_RUN_ID", None,
       "Run id correlating lifecycle events CLI -> gang driver -> "
       "hosts; auto-generated and exported when unset."),
    _k("STPU_DISABLE_EVENTS", "0",
       "\"1\" disables the JSONL lifecycle event log."),
    _k("STPU_TRACE", "0",
       "\"1\" arms distributed tracing in this process and children."),
    _k("STPU_TRACE_SAMPLE", "1",
       "Root-span sampling rate in [0, 1]; children inherit so traces "
       "are whole-or-absent."),
    _k("STPU_TRACE_CTX", None,
       "Serialized parent span context stamped into child envs "
       "(trace32-span16-flags)."),
    _k("STPU_STEPSTATS", "0",
       "\"1\" arms per-engine-step performance telemetry (step ring, "
       "/perf phase breakdown, flight-recorder context)."),
    _k("STPU_STEPSTATS_RING", "1024",
       "Step-ring capacity in records (the window /perf aggregates "
       "over and the flight recorder dumps)."),
    _k("STPU_STEPSTATS_SYNC_EVERY", "0",
       "Sample a timed block_until_ready every N decode steps to "
       "split dispatch vs device time (0 disables; the only "
       "sanctioned sync on the serve hot path)."),
    _k("STPU_REQLOG", "0",
       "\"1\" arms the wide-event per-request analytics log "
       "(requests.jsonl: one joined LB+engine record per request)."),
    _k("STPU_REQLOG_SAMPLE", "1",
       "Request-log keep rate in [0, 1] for SUCCESSFUL requests; "
       "errors, resumed streams and slow requests are always kept."),
    _k("STPU_REQLOG_SLOW_TTFT", "1.0",
       "TTFT seconds at or above which a request counts as slow and "
       "bypasses request-log sampling."),
    _k("STPU_REQLOG_SLOW_E2E", "10.0",
       "End-to-end seconds at or above which a request counts as slow "
       "and bypasses request-log sampling."),
    _k("STPU_DISABLE_USAGE_COLLECTION", "0",
       "\"1\" disables usage reporting (wins over configured sinks)."),
    # ------------------------------------------------ fleet telemetry
    _k("STPU_FLEET", "1",
       "\"0\" disarms the controller-resident fleet telemetry "
       "collector (no store, no SLO monitor, /fleet answers 503)."),
    _k("STPU_FLEET_COLLECT_SECONDS", "0",
       "Fleet collector scrape period, seconds (0 = follow the "
       "controller tick)."),
    _k("STPU_FLEET_RAW_SECONDS", "10",
       "Fleet store raw-tier bucket width, seconds."),
    _k("STPU_FLEET_RAW_RETENTION", "900",
       "Fleet store raw-tier retention, seconds; older points "
       "downsample into the rollup tier."),
    _k("STPU_FLEET_ROLLUP_SECONDS", "60",
       "Fleet store rollup-tier bucket width, seconds."),
    _k("STPU_FLEET_ROLLUP_RETENTION", "86400",
       "Fleet store rollup-tier retention, seconds (the telemetry "
       "horizon)."),
    _k("STPU_SLO_FAST_WINDOW", "300",
       "SLO burn-rate fast window, seconds (page-worthy burn)."),
    _k("STPU_SLO_SLOW_WINDOW", "3600",
       "SLO burn-rate slow window, seconds (sustained burn; breach "
       "needs BOTH windows over the threshold)."),
    _k("STPU_SLO_BURN_THRESHOLD", "1.0",
       "Burn-rate multiple that trips a breach in both windows (1.0 "
       "= burning the error budget exactly at the sustainable rate)."),
    # ------------------------------------------------ chaos
    _k("STPU_FAULTS", None,
       "Fault-injection spec (point:mode:p=..;...) armed at import."),
    _k("STPU_FAULTS_SEED", "0",
       "Seed for the fault-injection RNG (bit-identical chaos runs)."),
    # ------------------------------------------------ backends/agent
    _k("STPU_SKIP_IDENTITY_CHECK", None,
       "\"1\" skips the cloud-identity ownership check on cluster "
       "state handover."),
    _k("STPU_DISABLE_DAEMON", None,
       "\"1\" skips spawning the head agent daemon (hermetic tests)."),
    _k("STPU_DAEMON_INTERVAL", None,
       "Agent daemon poll interval override, seconds."),
    _k("STPU_AUTOSTOP_GRACE_SECONDS", "10",
       "Grace window before autostop teardown after the idle trigger."),
    _k("STPU_TEARDOWN_GRACE_SECONDS", "5",
       "SIGTERM grace for local jobs to flush a final checkpoint "
       "before teardown removes host dirs (0 disables)."),
    _k("STPU_FORCE_PY_AGENT", None,
       "Any value forces the pure-python gang coordinator over the "
       "native host agent."),
    _k("STPU_SKIP_HEALTH_PROBE", None,
       "\"1\" skips the pre-barrier TPU health probe on gang launch."),
    _k("STPU_EXEC_TOKEN", None,
       "Auth token presented to the remote exec agent."),
    _k("STPU_GANG_COORD_ADDR", None,
       "host:port of the gang coordinator for host wrappers."),
    _k("STPU_GANG_COORD_TOKEN", "",
       "Auth token for the direct-connect gang coordinator; empty "
       "selects the loopback-only unauthenticated mode."),
    # ------------------------------------------------ jobs/training
    _k("STPU_JOBS_POLL_SECONDS", "15",
       "Managed-jobs controller watch-tick interval, seconds."),
    _k("STPU_JOB_CKPT_DIR", None,
       "Per-task checkpoint dir stamped into every (re)launch by the "
       "jobs controller; recipes default --checkpoint-dir to it."),
    _k("STPU_PROFILE_DIR", None,
       "Write an on-device XLA profile of the training loop here."),
    _k("STPU_TRAINSTATS", "0",
       "\"1\" arms per-train-step goodput telemetry (step ring, live "
       "MFU, goodput breakdown, straggler detection, flight-recorder "
       "crash dumps)."),
    _k("STPU_TRAINSTATS_RING", "512",
       "Train-step ring capacity in records (the window MFU/goodput "
       "aggregate over and the flight recorder dumps)."),
    _k("STPU_TRAINSTATS_SYNC_EVERY", "0",
       "Sample a timed block_until_ready every N train steps to split "
       "dispatch vs device time (0 disables; the only sanctioned "
       "sync on the train hot path)."),
    _k("STPU_TRAINSTATS_DIR", None,
       "Trainstats output dir for per-host JSONL + snapshot.json "
       "(default $STPU_JOB_CKPT_DIR/trainstats when a managed job, "
       "else in-memory only)."),
    _k("STPU_TRAIN_STRAGGLER_SECONDS", "2.0",
       "Per-host step-boundary lag over the gang median that flags a "
       "straggler (host 0 scans; 0 disables)."),
    _k("STPU_BENCHMARK_LOG_DIR", None,
       "Benchmark-harness summary-log dir (callbacks.init contract)."),
    # ------------------------------------------------ serve control
    _k("STPU_SERVE_TICK_SECONDS", "10",
       "Serve controller reconcile tick, seconds."),
    _k("STPU_LB_SYNC_SECONDS", "2",
       "LB <-> controller sync interval, seconds."),
    _k("STPU_LB_POLICY", None,
       "Default load-balancing policy when the spec sets none."),
    _k("STPU_LB_RETRIES", "2",
       "Extra pre-first-byte attempts per proxied request."),
    _k("STPU_LB_MAX_BODY_BYTES", "10485760",
       "Request-body cap (413 above it, checked before buffering)."),
    _k("STPU_LB_BREAKER_THRESHOLD", "3",
       "Consecutive connect failures that eject a replica."),
    _k("STPU_LB_BREAKER_BACKOFF", "2",
       "Breaker half-open re-probe backoff base, seconds."),
    _k("STPU_LB_BREAKER_BACKOFF_CAP", "60",
       "Breaker backoff ceiling, seconds."),
    _k("STPU_LB_STREAM_RESUMES", "1",
       "Mid-stream resume attempts per proxied stream: upstream "
       "deaths after the first byte re-submit prompt+emitted to a "
       "peer and splice the continuation (0 disables journaling)."),
    _k("STPU_LB_RESUME_JOURNAL_MB", "8",
       "Global byte budget (MiB) for in-flight stream resume "
       "journals; over-budget streams evict (degrade to plain "
       "abort)."),
    # ------------------------------------------------ serve engine
    _k("STPU_ENGINE_SLOTS", "4",
       "Decode-engine slot count (continuous-batching concurrency)."),
    _k("STPU_KV_PAGED", "1",
       "\"0\" falls back to dense per-slot cache rows (no prefix "
       "cache, no quantized KV); default serves from the paged KV "
       "block pool (one device pool + per-slot block tables, "
       "zero-copy prefix aliasing). Bit-identical either way while "
       "STPU_KV_QUANT=0."),
    _k("STPU_KV_QUANT", "0",
       "\"1\" stores int8 KV blocks + per-(layer, block, head) f32 "
       "scales in the paged pool — ~2x blocks at the same HBM "
       "budget (auto pool sizing doubles). Requires STPU_KV_PAGED=1; "
       "NOT bit-identical to bf16, gated by the tests/test_quant.py "
       "parity suite."),
    _k("STPU_WEIGHT_QUANT", "0",
       "\"1\" serves int8 per-output-channel-quantized params "
       "(matmul weights + embed/lm_head; norms, LoRA adapters and "
       "the MoE router stay full precision). Parity-gated like "
       "STPU_KV_QUANT."),
    _k("STPU_SPEC_K", "0",
       "Speculative decoding: tokens drafted per slot per decode "
       "step, verified in one batched forward (0 disables; output "
       "stays bit-identical to non-speculative decode)."),
    _k("STPU_SPEC_NGRAM", "3",
       "Speculative draft matcher n-gram length over each slot's own "
       "token history (prompt lookup)."),
    _k("STPU_SPEC_MIN_ACCEPT", "0.2",
       "Per-slot draft acceptance-rate floor: a slot whose measured "
       "acceptance falls below it (after >= 16 drafted tokens) stops "
       "drafting."),
    _k("STPU_KV_POOL_BLOCKS", "0",
       "Paged-KV pool size in blocks incl. the scratch block (0 = "
       "auto: slots * max_seq / block + 1, the dense HBM budget; "
       "doubled under STPU_KV_QUANT=1 — int8 blocks are ~half the "
       "bytes)."),
    _k("STPU_KV_BLOCK_TOKENS", "0",
       "Paged-KV block size in tokens; also becomes the prefill "
       "chunk — blocks and chunks are one unit (0 = the engine's "
       "prefill chunk, default 64)."),
    _k("STPU_PREFIX_CACHE_MB", "64",
       "Host-RAM KV spill-tier budget in MiB under the paged prefix "
       "trie: LRU-evicted prefix blocks spill D2H into a bounded "
       "host pool and re-admit H2D on a warm match instead of "
       "re-prefilling. 0 disables the tier (evictions drop the KV). "
       "Rides the gang kv-config handshake; ignored on the dense "
       "path."),
    _k("STPU_TUNE_MANIFEST", None,
       "Tuning-manifest override for the decode engine: a path loads "
       "that sha256-pinned `stpu tune` manifest, \"0\" disables "
       "tuning (hand-pinned defaults), unset auto-loads "
       "~/.stpu/tuning/manifest.json when present. Tuned geometry "
       "rides the gang kv-config handshake, so every member must "
       "resolve the same manifest."),
    _k("STPU_STREAM_TIMEOUT", "600",
       "Per-token stream timeout before the engine is declared "
       "wedged, seconds."),
    _k("STPU_ENGINE_MAX_RESTARTS", "3",
       "Consecutive fast engine crashes before permanent-down."),
    _k("STPU_ENGINE_RESTART_BACKOFF", "1.0",
       "Engine crash-restart backoff base, seconds."),
    _k("STPU_PREEMPT_NOTICE_POLL", "1.0",
       "Replica preemption-notice watcher poll interval, seconds "
       "(fault point replica.preempt_notice; 0 disables). A notice "
       "surfaces on /health and triggers controller replace-ahead."),
    # ------------------------------------------------ gang replicas
    _k("STPU_REPLICA_TOPOLOGY", None,
       "hosts x tp replica topology stamped by replica_managers into "
       "every gang member's env."),
    _k("STPU_GANG_SERVE_ADDR", None,
       "Explicit gang channel address for self-spawned followers "
       "(dev stacks); gang-launched followers derive it from the env "
       "contract instead."),
    _k("STPU_GANG_HB_SECONDS", "0.5",
       "Gang follower heartbeat interval, seconds."),
    _k("STPU_GANG_HB_TIMEOUT", "5",
       "Heartbeat silence that marks a gang member dead, seconds."),
    _k("STPU_GANG_MAX_RESTARTS", "3",
       "Consecutive fast whole-gang restarts before permanent-down."),
)

REGISTRY: Dict[str, EnvKnob] = {k.name: k for k in _KNOBS}
if len(REGISTRY) != len(_KNOBS):
    raise RuntimeError("duplicate STPU_* names in env_contract")


def get(name: str) -> EnvKnob:
    return REGISTRY[name]


def render_markdown_table() -> str:
    """The knob table embedded in docs/static-analysis.md (a tier-1
    test pins the doc to this exact output)."""
    lines = ["| knob | default | meaning |",
             "|---|---|---|"]
    for knob in sorted(REGISTRY.values(), key=lambda k: k.name):
        default = "(unset)" if knob.default is None else \
            f"`{knob.default}`" if knob.default else "`\"\"`"
        lines.append(f"| `{knob.name}` | {default} | {knob.doc} |")
    return "\n".join(lines)
