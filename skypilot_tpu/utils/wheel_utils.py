"""Build the framework wheel for shipping to clusters.

Reference analog: sky/backends/wheel_utils.py (~/.sky/wheels/<hash>/ —
every cluster runs the same version the client launched with). Cached by
content hash of the package tree; rebuilds only when sources change.
"""
from __future__ import annotations

import functools
import hashlib
import pathlib
import shutil
import subprocess
import sys

import filelock

from skypilot_tpu.utils import paths

_PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent
_REPO_ROOT = _PKG_ROOT.parent


@functools.lru_cache(maxsize=1)
def _tree_hash() -> str:
    # Cached: the source tree is fixed for one client invocation, and
    # launch paths consult the version repeatedly (reuse check, ship).
    h = hashlib.sha256()
    for p in sorted(_PKG_ROOT.rglob("*.py")):
        h.update(p.read_bytes())
    return h.hexdigest()[:16]


def runtime_version() -> str:
    """Content hash identifying the runtime this client would ship —
    compared against the cluster's RUNTIME_VERSION_PATH stamp on reuse."""
    return _tree_hash()


def wheel_dir() -> pathlib.Path:
    d = paths.home() / "wheels"
    d.mkdir(parents=True, exist_ok=True)
    return d


def build_wheel() -> pathlib.Path:
    """Returns the path to the built wheel, building if stale."""
    tag = _tree_hash()
    out_dir = wheel_dir() / tag
    lock = filelock.FileLock(str(paths.locks_dir() / "wheel.lock"))
    with lock:
        existing = list(out_dir.glob("*.whl"))
        if existing:
            return existing[0]
        if out_dir.exists():
            shutil.rmtree(out_dir)
        out_dir.mkdir(parents=True)
        # Build from a temp copy so setuptools' build/ and egg-info
        # droppings never land in the working repo. --no-build-isolation:
        # isolated builds try to download setuptools, which fails on
        # zero-egress hosts.
        import tempfile
        with tempfile.TemporaryDirectory(prefix="stpu-wheel-") as td:
            src = pathlib.Path(td) / "src"
            shutil.copytree(
                _REPO_ROOT, src,
                ignore=shutil.ignore_patterns(
                    ".git", "build", "*.egg-info", "__pycache__",
                    ".pytest_cache", "tests"))
            proc = subprocess.run(
                [sys.executable, "-m", "pip", "wheel", "--no-deps",
                 "--no-build-isolation",
                 "--wheel-dir", str(out_dir), str(src)],
                capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"wheel build failed:\n{proc.stderr[-2000:]}")
        wheels = list(out_dir.glob("*.whl"))
        if not wheels:
            raise RuntimeError("wheel build produced no artifact")
        return wheels[0]
