"""JSON-schema validation for task YAML / service spec / user config.

Reference analog: sky/utils/schemas.py (905 LoC of hand-built jsonschema
dicts validated on every Task.from_yaml_config). Kept to the fields this
framework implements; validation errors surface the YAML path.
"""
from __future__ import annotations

from typing import Any, Dict

import jsonschema

from skypilot_tpu import exceptions

_RESOURCES_SCHEMA = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "cloud": {"type": "string"},
        "accelerator": {"type": "string"},
        "accelerators": {
            "anyOf": [{"type": "string"},
                      {"type": "object",
                       "additionalProperties": {"type": "integer"}}],
        },
        "instance_type": {"type": "string"},
        "cpus": {"anyOf": [{"type": "integer"}, {"type": "string"}]},
        "memory": {"anyOf": [{"type": "number"}, {"type": "string"}]},
        "region": {"type": "string"},
        "zone": {"type": "string"},
        "use_spot": {"type": "boolean"},
        "spot_recovery": {"type": "string"},
        "job_recovery": {"type": "string"},
        "disk_size": {"type": "integer"},
        "image_id": {"type": "string"},
        "runtime_version": {"type": "string"},
        "autostop": {"anyOf": [{"type": "integer"}, {"type": "boolean"}]},
        "ports": {
            "anyOf": [{"type": "integer"}, {"type": "string"},
                      {"type": "array",
                       "items": {"anyOf": [{"type": "integer"},
                                           {"type": "string"}]}}],
        },
        "labels": {"type": "object",
                   "additionalProperties": {"type": "string"}},
        "any_of": {"type": "array", "items": {"type": "object"}},
    },
}

_STORAGE_SCHEMA = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "name": {"type": "string"},
        "source": {"anyOf": [{"type": "string"},
                             {"type": "array",
                              "items": {"type": "string"}}]},
        "store": {"type": "string",
                  "enum": ["gcs", "s3", "r2", "ibm", "azure",
                           "local"]},
        "persistent": {"type": "boolean"},
        "mode": {"type": "string", "enum": ["MOUNT", "COPY"]},
    },
}

_SERVICE_SCHEMA = {
    "type": "object",
    "additionalProperties": False,
    "required": ["readiness_probe"],
    "properties": {
        "readiness_probe": {
            "anyOf": [
                {"type": "string"},
                {"type": "object",
                 "additionalProperties": False,
                 "properties": {
                     "path": {"type": "string"},
                     "initial_delay_seconds": {"type": "integer"},
                     "post_data": {"type": ["object", "string"]},
                 }},
            ],
        },
        "replicas": {"type": "integer"},
        "upstream_timeout_seconds": {"type": "integer"},
        "drain_timeout_seconds": {"type": "integer"},
        # Keep in sync with serve.load_balancing_policies.POLICIES (the
        # schema layer must not import the serve/jax stack).
        "load_balancing_policy": {
            "type": "string",
            "enum": ["round_robin", "prefix_affinity"],
        },
        # Per-replica slice topology (serve/gang_replica.py): each
        # replica is a gang of `hosts` machines whose devices form one
        # mesh, with `ici_axes` naming the intra-slice parallel axes
        # (serving uses tp). Kept jax-free here: the schema layer must
        # not import the serve/compute stack.
        "replica_topology": {
            "type": "object",
            "additionalProperties": False,
            "required": ["hosts"],
            "properties": {
                "hosts": {"type": "integer", "minimum": 1},
                "ici_axes": {
                    "type": "object",
                    "additionalProperties": {"type": "integer",
                                             "minimum": 1},
                },
            },
        },
        "replica_policy": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "min_replicas": {"type": "integer"},
                "max_replicas": {"type": "integer"},
                "target_qps_per_replica": {"type": "number"},
                "qps_window_seconds": {"type": "integer"},
                "upscale_delay_seconds": {"type": "integer"},
                "downscale_delay_seconds": {"type": "integer"},
                "base_ondemand_fallback_replicas": {"type": "integer"},
                "dynamic_ondemand_fallback": {"type": "boolean"},
                # Keep in sync with serve.autoscalers.from_spec (the
                # schema layer must not import the serve stack).
                "scaling_policy": {
                    "type": "string",
                    "enum": ["qps", "latency"],
                },
            },
        },
        # SLO objectives evaluated by the controller's fleet collector
        # (observability/slo.py). Kind-specific constraints (latency
        # kinds need threshold_seconds) are enforced by
        # slo.Objective.from_config at spec-build time.
        "slo": {
            "type": "object",
            "additionalProperties": False,
            "required": ["objectives"],
            "properties": {
                "objectives": {
                    "type": "array",
                    "minItems": 1,
                    "items": {
                        "type": "object",
                        "additionalProperties": False,
                        "required": ["kind"],
                        "properties": {
                            "kind": {
                                "type": "string",
                                "enum": ["ttft", "tpot", "error_rate"],
                            },
                            "target": {
                                "type": "number",
                                "exclusiveMinimum": 0,
                                "exclusiveMaximum": 1,
                            },
                            "threshold_seconds": {
                                "type": "number",
                                "exclusiveMinimum": 0,
                            },
                        },
                    },
                },
            },
        },
    },
}

TASK_SCHEMA = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "name": {"type": "string"},
        "workdir": {"type": "string"},
        "num_nodes": {"type": "integer", "minimum": 1},
        "setup": {"type": "string"},
        "run": {"type": "string"},
        "envs": {"type": "object",
                 "additionalProperties": {
                     "anyOf": [{"type": "string"}, {"type": "number"},
                               {"type": "null"}]}},
        "file_mounts": {
            "type": "object",
            "additionalProperties": {
                "anyOf": [{"type": "string"}, _STORAGE_SCHEMA],
            },
        },
        "resources": _RESOURCES_SCHEMA,
        "service": _SERVICE_SCHEMA,
    },
}

CONFIG_SCHEMA = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "gcp": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "project_id": {"type": "string"},
                "vpc_name": {"type": "string"},
                "use_internal_ips": {"type": "boolean"},
                "ssh_proxy_command": {"type": "string"},
            },
        },
        "jobs": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "controller": {
                    "type": "object",
                    "properties": {
                        "resources": _RESOURCES_SCHEMA,
                        "mode": {"enum": ["cluster", "local"]},
                    },
                },
            },
        },
        "serve": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "controller": {
                    "type": "object",
                    "properties": {
                        "resources": _RESOURCES_SCHEMA,
                        "mode": {"enum": ["cluster", "local"]},
                    },
                },
            },
        },
        "usage": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "loki_url": {"type": "string"},
                "endpoint": {"type": "string"},
            },
        },
        "catalog": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                # TTL-based refresh: re-run the fetcher when the CSVs
                # are older than this many hours (catalog/__init__.py
                # _maybe_refresh; reference:
                # sky/clouds/service_catalog/constants.py:2-4).
                "refresh_hours": {"type": "number", "minimum": 0},
            },
        },
        # Keys the code reads (slice_backend kubernetes plumbing,
        # AzureBlobStore, controller_utils bucket_store) — they must
        # also be schema-legal or a configured user crashes at load.
        "kubernetes": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "namespace": {"type": "string"},
                "gke_accelerator_type": {"type": "string"},
                "gke_tpu_topology": {"type": "string"},
            },
        },
        "azure": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "storage_account": {"type": "string"},
            },
        },
        "ibm": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "cos_region": {"type": "string"},
            },
        },
        "controller": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "bucket_store": {"type": "string"},
            },
        },
    },
}


def _validate(config: Dict[str, Any], schema: Dict[str, Any],
              what: str) -> None:
    try:
        jsonschema.validate(config, schema)
    except jsonschema.ValidationError as e:
        path = ".".join(str(p) for p in e.absolute_path) or "<root>"
        raise exceptions.InvalidTaskError(
            f"Invalid {what} at {path!r}: {e.message}") from e


def validate_task(config: Dict[str, Any]) -> None:
    _validate(config, TASK_SCHEMA, "task YAML")


def validate_resources(config: Dict[str, Any]) -> None:
    _validate(config, _RESOURCES_SCHEMA, "resources")


def validate_service(config: Dict[str, Any]) -> None:
    _validate(config, _SERVICE_SCHEMA, "service spec")


def validate_config(config: Dict[str, Any]) -> None:
    _validate(config, CONFIG_SCHEMA, "config")
