"""Remote command execution + file transfer.

Reference analog: sky/utils/command_runner.py (CommandRunner:153,
SSHCommandRunner:392 with ControlMaster/ProxyCommand, rsync:598). Two
implementations:

  * SSHCommandRunner — TPU-VM hosts over SSH with connection multiplexing.
  * LocalCommandRunner — a "host" that is a local directory + subprocess;
    powers the hermetic local cloud (`provision/local.py`), the analog of
    the reference's Kind-based `sky local up` path.
"""
from __future__ import annotations

import os
import pathlib
import shlex
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions

SSH_COMMON_OPTS = [
    "-o", "StrictHostKeyChecking=no",
    "-o", "UserKnownHostsFile=/dev/null",
    "-o", "IdentitiesOnly=yes",
    "-o", "ConnectTimeout=30",
    "-o", "ServerAliveInterval=20",
    "-o", "ServerAliveCountMax=3",
    "-o", "LogLevel=ERROR",
]


def _run_with_log(cmd: List[str], *, log_path: Optional[str],
                  stream_logs: bool, env: Optional[Dict[str, str]] = None,
                  cwd: Optional[str] = None, stdin=None) -> int:
    """Run, teeing stdout/stderr to log_path; returns returncode."""
    if log_path is None and stream_logs:
        proc = subprocess.run(cmd, env=env, cwd=cwd, stdin=stdin)
        return proc.returncode
    log_f = open(log_path, "ab") if log_path else subprocess.DEVNULL
    try:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, env=env,
                                cwd=cwd, stdin=stdin)
        assert proc.stdout is not None
        for line in proc.stdout:
            if log_path:
                log_f.write(line)
                log_f.flush()
            if stream_logs:
                print(line.decode(errors="replace"), end="", flush=True)
        return proc.wait()
    finally:
        if log_path:
            log_f.close()


def _script_file(script: str):
    """Spool a shell script to an anonymous temp file for use as a
    subprocess's stdin — env exports (task secrets among them) must ride
    stdin, never the ssh/kubectl/docker argv, where any co-tenant user
    can read them via `ps` (same exposure gang_exec._ssh_argv_and_script
    was rewritten to avoid)."""
    f = tempfile.TemporaryFile("w+b")
    f.write(script.encode())
    f.flush()
    f.seek(0)
    return f


def _env_script(cmd: str, env: Dict[str, str]) -> str:
    exports = "".join(f"export {k}={shlex.quote(str(v))}\n"
                      for k, v in env.items())
    return exports + cmd


class CommandRunner:
    """Abstract: run a shell command on a host / rsync files to it."""

    # Interpreter that has the framework wheel importable on the host.
    # SSH hosts pip-install the shipped wheel into the system python3;
    # local directory-hosts reuse this process's interpreter.
    remote_python = "python3"

    def __init__(self, node_id: str, internal_ip: str):
        self.node_id = node_id
        self.internal_ip = internal_ip

    def run(self, cmd: Union[str, List[str]], *,
            env: Optional[Dict[str, str]] = None,
            log_path: Optional[str] = None,
            stream_logs: bool = False,
            require_outputs: bool = False,
            ) -> Union[int, Tuple[int, str, str]]:
        raise NotImplementedError

    def rsync(self, source: str, target: str, *, up: bool,
              delete: bool = False,
              log_path: Optional[str] = None) -> None:
        """``delete=True`` mirrors (removes extraneous remote files) —
        only safe for the workdir sync, never for arbitrary mounts."""
        raise NotImplementedError

    def check_returncode(self, rc: int, cmd: str,
                         error_msg: str = "") -> None:
        if rc != 0:
            raise exceptions.CommandError(rc, cmd, error_msg)


class SSHCommandRunner(CommandRunner):
    """SSH with ControlMaster multiplexing; rsync-over-ssh transfers."""

    def __init__(self, node_id: str, ip: str, *, ssh_user: str,
                 ssh_key_path: str, port: int = 22,
                 proxy_command: Optional[str] = None):
        super().__init__(node_id, ip)
        self.ip = ip
        self.ssh_user = ssh_user
        self.ssh_key_path = os.path.expanduser(ssh_key_path)
        self.port = port
        self.proxy_command = proxy_command
        self._control_dir = tempfile.mkdtemp(prefix="stpu-ssh-")

    def _ssh_base(self) -> List[str]:
        opts = list(SSH_COMMON_OPTS)
        opts += ["-o", f"ControlPath={self._control_dir}/%C",
                 "-o", "ControlMaster=auto",
                 "-o", "ControlPersist=120s"]
        if self.proxy_command:
            opts += ["-o", f"ProxyCommand={self.proxy_command}"]
        return (["ssh"] + opts +
                ["-i", self.ssh_key_path, "-p", str(self.port)])

    def run(self, cmd, *, env=None, log_path=None, stream_logs=False,
            require_outputs=False):
        if isinstance(cmd, list):
            cmd = " ".join(shlex.quote(c) for c in cmd)
        # Login shell so PATH includes user installs (reference runs
        # everything under `bash --login -c`, sky/skylet/log_lib.py:261).
        # With env: the exports + command ride STDIN (`bash --login -s`)
        # so secrets never appear in the ssh argv (visible via ps).
        if env:
            remote = "bash --login -s"
            stdin = _script_file(_env_script(cmd, env))
        else:
            remote = f"bash --login -c {shlex.quote(cmd)}"
            stdin = None
        full = self._ssh_base() + [f"{self.ssh_user}@{self.ip}", remote]
        try:
            if require_outputs:
                proc = subprocess.run(full, capture_output=True,
                                      text=True, stdin=stdin)
                return proc.returncode, proc.stdout, proc.stderr
            return _run_with_log(full, log_path=log_path,
                                 stream_logs=stream_logs, stdin=stdin)
        finally:
            if stdin is not None:
                stdin.close()

    def rsync(self, source, target, *, up, delete=False, log_path=None):
        ssh_cmd = " ".join(self._ssh_base())
        rsync_cmd = ["rsync", "-avz"]
        if delete:
            rsync_cmd.append("--delete")
        rsync_cmd += [
            "--exclude", ".git/",
            "-e", ssh_cmd,
        ]
        if up:
            rsync_cmd += [source, f"{self.ssh_user}@{self.ip}:{target}"]
        else:
            rsync_cmd += [f"{self.ssh_user}@{self.ip}:{source}", target]
        rc = _run_with_log(rsync_cmd, log_path=log_path, stream_logs=False)
        self.check_returncode(rc, " ".join(rsync_cmd),
                              "rsync failed")


class KubernetesCommandRunner(CommandRunner):
    """SSH-free exec into a pod via ``kubectl exec`` / ``kubectl cp``
    (reference: KubernetesCommandRunner, sky/utils/command_runner.py:647).
    """

    def __init__(self, node_id: str, pod_name: str, namespace: str,
                 internal_ip: str = "", container: str = "stpu-host"):
        super().__init__(node_id, internal_ip)
        self.pod_name = pod_name
        self.namespace = namespace
        self.container = container

    def _exec_argv(self, interactive: bool = False) -> List[str]:
        """argv prefix that runs `bash -c <script>` inside the host;
        the one transport-specific piece (overridden by docker)."""
        argv = ["kubectl", "-n", self.namespace, "exec"]
        if interactive:
            argv.append("-i")
        return argv + [self.pod_name, "-c", self.container, "--",
                       "bash", "-c"]

    def run(self, cmd, *, env=None, log_path=None, stream_logs=False,
            require_outputs=False):
        if isinstance(cmd, list):
            cmd = " ".join(shlex.quote(c) for c in cmd)
        # env exports over stdin, not argv — see SSHCommandRunner.run.
        if env:
            full = self._exec_argv(interactive=True) + ["bash --login -s"]
            stdin = _script_file(_env_script(cmd, env))
        else:
            full = self._exec_argv() + [
                f"bash --login -c {shlex.quote(cmd)}"]
            stdin = None
        try:
            if require_outputs:
                proc = subprocess.run(full, capture_output=True,
                                      text=True, stdin=stdin)
                return proc.returncode, proc.stdout, proc.stderr
            return _run_with_log(full, log_path=log_path,
                                 stream_logs=stream_logs, stdin=stdin)
        finally:
            if stdin is not None:
                stdin.close()

    @staticmethod
    def _sh(p: str) -> str:
        """Quote a pod-side path keeping a leading ~ expandable —
        kubectl cp cannot expand ~, so transfers stream through the
        pod's shell instead."""
        if p == "~":
            return '"$HOME"'
        if p.startswith("~/"):
            return '"$HOME"/' + shlex.quote(p[2:])
        return shlex.quote(p)

    def _exec_stdin(self, remote_sh: str, stdin_cmd: Optional[List[str]],
                    stdin_file: Optional[str]) -> int:
        full = self._exec_argv(interactive=True) + [remote_sh]
        if stdin_cmd is not None:
            feeder = subprocess.Popen(stdin_cmd, stdout=subprocess.PIPE)
            proc = subprocess.run(full, stdin=feeder.stdout,
                                  capture_output=True)
            feeder.stdout.close()
            feeder.wait()
            return proc.returncode or feeder.returncode
        with open(stdin_file, "rb") as f:
            return subprocess.run(full, stdin=f,
                                  capture_output=True).returncode

    def rsync(self, source, target, *, up, delete=False, log_path=None):
        del log_path
        if not up:
            # Down: single file via cat (logs/artifacts).
            full = self._exec_argv() + [f"cat {self._sh(source)}"]
            with open(target, "wb") as out:
                rc = subprocess.run(full, stdout=out).returncode
            self.check_returncode(rc, "exec cat", source)
            return
        t = self._sh(target)
        if os.path.isdir(source):
            # Directory: tar pipe with rsync's into-dir semantics;
            # --delete emulated by clearing the target first.
            clear = f"rm -rf {t} && " if delete else ""
            rc = self._exec_stdin(
                f"{clear}mkdir -p {t} && tar xf - -C {t}",
                ["tar", "cf", "-", "--exclude=.git", "-C", source, "."],
                None)
        elif target.endswith("/"):
            base = shlex.quote(os.path.basename(source))
            rc = self._exec_stdin(
                f"mkdir -p {t} && cat > {t}/{base}", None, source)
        else:
            rc = self._exec_stdin(
                f"mkdir -p $(dirname {t}) && cat > {t}", None, source)
        self.check_returncode(rc, f"pod transfer {source} -> {target}",
                              "kubectl exec stream failed")


class DockerCommandRunner(KubernetesCommandRunner):
    """Exec into a local container via ``docker exec`` — identical
    transport shape to pods (stdin-streamed transfers, shell-expanded
    paths), different argv prefix (reference: docker_utils +
    LocalDockerBackend)."""

    def __init__(self, node_id: str, container: str):
        super().__init__(node_id, pod_name=container, namespace="",
                         internal_ip="127.0.0.1")

    def _exec_argv(self, interactive: bool = False) -> List[str]:
        argv = ["docker", "exec"]
        if interactive:
            argv.append("-i")
        return argv + [self.pod_name, "bash", "-c"]


class LocalCommandRunner(CommandRunner):
    """A fake host rooted at a local directory.

    ``~`` inside commands maps to the host root dir via $HOME so multi-host
    semantics (per-host file trees, per-host logs) hold on one machine.
    """

    remote_python = sys.executable

    def __init__(self, node_id: str, host_dir: str):
        super().__init__(node_id, "127.0.0.1")
        self.host_dir = pathlib.Path(host_dir)
        self.host_dir.mkdir(parents=True, exist_ok=True)

    def run(self, cmd, *, env=None, log_path=None, stream_logs=False,
            require_outputs=False):
        if isinstance(cmd, list):
            cmd = " ".join(shlex.quote(c) for c in cmd)
        full_env = dict(os.environ)
        full_env["HOME"] = str(self.host_dir)
        # Simulate the wheel install real hosts get: make the framework
        # importable from the fake host's cwd (the host root dir).
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        existing = full_env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(":"):
            full_env["PYTHONPATH"] = (f"{pkg_root}:{existing}"
                                      if existing else pkg_root)
        if env:
            full_env.update({k: str(v) for k, v in env.items()})
        argv = ["bash", "-c", cmd]
        if require_outputs:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  env=full_env, cwd=str(self.host_dir))
            return proc.returncode, proc.stdout, proc.stderr
        return _run_with_log(argv, log_path=log_path,
                             stream_logs=stream_logs, env=full_env,
                             cwd=str(self.host_dir))

    def rsync(self, source, target, *, up, delete=False, log_path=None):
        # Pure-python copy: the dev image may lack the rsync binary, and
        # local "hosts" are just directories anyway.
        import shutil
        del log_path
        target = target.replace("~", str(self.host_dir), 1) if up else \
            target
        source = source if up else \
            source.replace("~", str(self.host_dir), 1)
        # rsync semantics: a target ending in "/" is a directory to copy
        # INTO (pathlib silently strips the trailing slash, which would
        # otherwise turn "dir/" into a file named "dir").
        into_dir = target.endswith("/")
        dst = pathlib.Path(target).expanduser()
        src = pathlib.Path(source).expanduser()
        try:
            if src.is_dir():
                dst.mkdir(parents=True, exist_ok=True)
                shutil.copytree(src, dst, dirs_exist_ok=True,
                                ignore=shutil.ignore_patterns(".git"))
            else:
                if into_dir:
                    dst.mkdir(parents=True, exist_ok=True)
                    dst = dst / src.name
                dst.parent.mkdir(parents=True, exist_ok=True)
                shutil.copy2(src, dst)
        except OSError as e:
            raise exceptions.CommandError(
                1, f"copy {src} -> {dst}", str(e)) from e
