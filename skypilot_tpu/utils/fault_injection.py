"""Deterministic fault injection for chaos tests and game days.

The serving stack's failure handling (LB retries, circuit breakers,
engine supervision, replica drain) is only trustworthy if its failure
modes are REPRODUCIBLE — "kill a replica and see what happens" by hand
proves nothing about the next regression. This module gives the hot
paths named choke points that can be armed to fail on demand:

    STPU_FAULTS="lb.upstream:error:p=0.5;engine.step:raise:times=1"

or programmatically in tests::

    from skypilot_tpu.utils import fault_injection as fi
    with fi.inject("engine.step", times=1):
        ...   # the next engine decode step raises InjectedFault

Spec grammar (";"-separated rules): ``point:mode[:k=v[,k=v...]]`` with

    mode   ``raise`` / ``error``  -> raise InjectedFault at the point
           ``delay``              -> sleep ``s`` seconds at the point
           ``kill``               -> SIGKILL the *current process* at
                                     the point (crash chaos: a torn
                                     checkpoint write, a host dying
                                     mid-step — no cleanup runs, which
                                     is the point)
    p      trigger probability in [0, 1] (default 1.0)
    times  stop firing after this many triggers (default unlimited)
    skip   ignore the first N otherwise-eligible hits (default 0) —
           lets one rule target "the SECOND launch" or "step K"
           deterministically
    s      delay seconds (``delay`` mode only, default 0.05)

Probabilistic rules draw from ONE module RNG seeded by
``STPU_FAULTS_SEED`` (default 0), so a chaos run replays bit-identically
under the same spec + seed — flaky-chaos-test hell is a solved problem.

``InjectedFault`` subclasses ``ConnectionError`` on purpose: the choke
points sit on network/compute seams whose callers already catch
connection-shaped failures, so an injected fault exercises the SAME
recovery path a real dead replica would, not a parallel test-only one.

Overhead discipline: instrumented call sites guard with the module
attribute ``ENABLED`` (``if fault_injection.ENABLED: fault_injection
.fire(...)``) — with no faults armed the hot-path cost is one global
load and a falsy branch, nothing else. Stdlib-only.

Known points (callers may add more; names are dotted subsystem.seam):

    lb.upstream       load_balancer._proxy_to, before the upstream
                      connect — a pre-first-byte replica failure
    engine.step       decode_engine._decode_step, before the jitted
                      batched decode step — an engine-loop crash
    engine.prefill    decode_engine._prefill_one, before a prefill
                      chunk — a crash while admitting a prompt
    engine.verify     decode_engine._verify_decode_step, before the
                      jitted speculative verify pass — a crash inside
                      a multi-token verification step (rides the same
                      EngineSupervisor restart ladder as engine.step)
    replica.probe     replica_managers._http_probe — a failed
                      readiness probe
    controller.sync   load_balancer.run_lb_process — the LB's
                      controller sync RPC failing
    jobs.launch       jobs/recovery_strategy.StrategyExecutor._launch,
                      before the task-cluster launch — a failed or slow
                      (re)launch attempt
    ckpt.write        train/checkpoint._save_locked, between writing
                      the payload bytes and the atomic rename — a
                      crash mid-checkpoint (``kill`` mode leaves the
                      torn .tmp restore_latest must skip)
    gang.host         agent/host_wrapper.main, after the gang barrier
                      and before exec'ing the command — one host of a
                      slice dying at start-of-run
    train.step        recipes' training loops, after each optimizer
                      step — preempt/crash a run mid-epoch at a
                      deterministic step (``skip=K`` + ``kill``)
    engine.spill      decode_engine._spill_block, before the D2H
                      copy of an evicted KV block — a failed spill
                      degrades that eviction to drop-on-evict (the
                      engine never crashes on a tier fault)
    lb.stream         load_balancer._read1, fired once per upstream
                      read while proxying a response body — kill a
                      stream mid-flight after K reads (``skip=K`` +
                      ``raise``) to drive the LB's journal resume /
                      upstream_aborted accounting
    replica.preempt_notice
                      recipes/serve_llm.preempt_notice_watch — the
                      injected fault IS the provider's preemption
                      notice: the replica flips /health to
                      ``preempt_notice: true`` and the controller
                      replaces it ahead of the kill
"""
from __future__ import annotations

import contextlib
import os
import random
import threading
from typing import Dict, Iterator, List, Optional

ENV = "STPU_FAULTS"
SEED_ENV = "STPU_FAULTS_SEED"

# Hot-path guard: True iff at least one rule is armed. Call sites read
# this module attribute before paying for the fire() call.
ENABLED = False


class InjectedFault(ConnectionError):
    """Raised at an armed fault point (see module docstring for why
    this is a ConnectionError)."""


class FaultSpecError(ValueError):
    """Malformed STPU_FAULTS spec."""


class _Rule:
    __slots__ = ("point", "mode", "p", "times", "delay", "skip",
                 "fired", "seen")

    def __init__(self, point: str, mode: str = "raise", p: float = 1.0,
                 times: Optional[int] = None, delay: float = 0.05,
                 skip: int = 0):
        if mode not in ("raise", "error", "delay", "kill"):
            raise FaultSpecError(
                f"{point}: unknown fault mode {mode!r} "
                "(expected raise/error/delay/kill)")
        if not 0.0 <= p <= 1.0:
            raise FaultSpecError(f"{point}: p={p} outside [0, 1]")
        self.point = point
        self.mode = mode
        self.p = float(p)
        self.times = None if times is None else int(times)
        self.delay = float(delay)
        self.skip = int(skip)     # eligible hits ignored before firing
        self.fired = 0            # times this rule actually triggered
        self.seen = 0             # eligible hits (incl. skipped ones)


_lock = threading.Lock()
_rules: Dict[str, _Rule] = {}
_rng = random.Random(0)


def _refresh_enabled() -> None:
    global ENABLED
    ENABLED = bool(_rules)


def parse_spec(spec: str) -> List[_Rule]:
    """Parse an STPU_FAULTS string into rules (see module docstring)."""
    rules: List[_Rule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise FaultSpecError(
                f"fault rule {part!r}: expected point:mode[:k=v,...]")
        point, mode = fields[0].strip(), fields[1].strip()
        kwargs: Dict[str, float] = {}
        if len(fields) > 2:
            for kv in ":".join(fields[2:]).split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if "=" not in kv:
                    raise FaultSpecError(
                        f"fault rule {part!r}: bad param {kv!r}")
                k, v = kv.split("=", 1)
                k = k.strip()
                if k not in ("p", "times", "s", "skip"):
                    raise FaultSpecError(
                        f"fault rule {part!r}: unknown param {k!r}")
                try:
                    kwargs[k] = float(v)
                except ValueError as e:
                    raise FaultSpecError(
                        f"fault rule {part!r}: {k}={v!r} not numeric"
                    ) from e
        rules.append(_Rule(
            point, mode, p=kwargs.get("p", 1.0),
            times=(int(kwargs["times"]) if "times" in kwargs else None),
            delay=kwargs.get("s", 0.05),
            skip=int(kwargs.get("skip", 0))))
    return rules


def configure(spec: str, seed: Optional[int] = None) -> None:
    """Replace all armed rules with the parsed ``spec`` and reseed the
    RNG (``seed`` falls back to STPU_FAULTS_SEED, then 0)."""
    rules = parse_spec(spec)
    if seed is None:
        seed = int(os.environ.get(SEED_ENV, "0"))
    with _lock:
        _rules.clear()
        for rule in rules:
            _rules[rule.point] = rule
        _rng.seed(seed)
        _refresh_enabled()


def activate(point: str, mode: str = "raise", p: float = 1.0,
             times: Optional[int] = None, delay: float = 0.05,
             skip: int = 0) -> None:
    """Arm one fault point programmatically (tests)."""
    rule = _Rule(point, mode, p=p, times=times, delay=delay, skip=skip)
    with _lock:
        _rules[point] = rule
        _refresh_enabled()


def deactivate(point: str) -> None:
    with _lock:
        _rules.pop(point, None)
        _refresh_enabled()


def clear() -> None:
    """Disarm every fault point (tests MUST call this in teardown)."""
    with _lock:
        _rules.clear()
        _refresh_enabled()


def fires(point: str) -> int:
    """How many times ``point``'s rule has actually triggered."""
    with _lock:
        rule = _rules.get(point)
        return rule.fired if rule is not None else 0


@contextlib.contextmanager
def inject(point: str, mode: str = "raise", p: float = 1.0,
           times: Optional[int] = None, delay: float = 0.05,
           skip: int = 0) -> Iterator[None]:
    """Arm ``point`` for the duration of the with-block."""
    activate(point, mode=mode, p=p, times=times, delay=delay, skip=skip)
    try:
        yield
    finally:
        deactivate(point)


def fire(point: str, **context) -> None:
    """Trigger ``point`` if armed: raises InjectedFault (raise/error
    mode) or sleeps (delay mode). ``context`` (e.g. the upstream url)
    lands in the fault message for chaos-log readability. No-op when
    the point is unarmed, over its ``times`` budget, or loses the
    probability roll."""
    with _lock:
        rule = _rules.get(point)
        if rule is None:
            return
        if rule.times is not None and rule.fired >= rule.times:
            return
        if rule.p < 1.0 and _rng.random() >= rule.p:
            return
        rule.seen += 1
        if rule.seen <= rule.skip:
            return
        rule.fired += 1
        mode, delay = rule.mode, rule.delay
    if mode == "delay":
        import time
        time.sleep(delay)
        return
    if mode == "kill":
        # Crash chaos: die the way a preempted host dies — instantly,
        # with no chance to flush or clean up.
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
        return  # unreachable; kill is synchronous on this thread
    detail = "".join(f" {k}={v}" for k, v in sorted(context.items()))
    raise InjectedFault(f"injected fault at {point}{detail}")


# Arm from the environment at import: operators export STPU_FAULTS for
# a game day and every process in the serving stack picks it up.
if os.environ.get(ENV):
    configure(os.environ[ENV])
