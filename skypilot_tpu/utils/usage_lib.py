"""Usage telemetry: local, append-only entrypoint records, with an
OPT-IN remote sink.

Reference analog: sky/usage/usage_lib.py (UsageMessageToReport schema,
the `entrypoint` decorator on every SDK call, yaml redaction, opt-out
env; `_send_to_loki`:296 fire-and-forgets to a hosted Loki). Difference
by design: this framework NEVER phones home by default — records go to
a local JSONL (``~/.stpu/usage/usage.jsonl``). An operator who wants
central collection configures their own sink:

    # ~/.stpu/config.yaml
    usage:
      loki_url: http://loki.internal:3100/loki/api/v1/push  # Loki shape
      # or
      endpoint: https://collector.internal/usage            # plain JSON

Remote sends are best-effort in a daemon thread (a dead collector
never slows or breaks a call). Opt out of everything with
``STPU_DISABLE_USAGE_COLLECTION=1`` (wins over any configured sink).
"""
from __future__ import annotations

import functools
import getpass
import hashlib
import json
import os
import threading
import time
from typing import Any, Callable

DISABLE_ENV = "STPU_DISABLE_USAGE_COLLECTION"


def _run_id() -> str:
    # Shared with the lifecycle event log (observability.events): one
    # ID correlates a CLI invocation's usage records, events, and the
    # job-side logs it spawned.
    from skypilot_tpu.observability import events
    return events.run_id()


def _enabled() -> bool:
    return os.environ.get(DISABLE_ENV, "0") != "1"


def _user_hash() -> str:
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        # No passwd entry / no USER env (bare-UID containers).
        user = f"uid-{os.getuid()}"
    return hashlib.md5(user.encode()).hexdigest()[:8]


def user_identity() -> str:
    """Stable identity for cluster ownership checks (reference:
    check_owner_identity, sky/backends/backend_utils.py:1536)."""
    return _user_hash()


def _record(payload: dict) -> None:
    from skypilot_tpu.utils import paths
    usage_dir = paths.home() / "usage"
    usage_dir.mkdir(parents=True, exist_ok=True)
    with open(usage_dir / "usage.jsonl", "a") as f:
        f.write(json.dumps(payload) + "\n")
    _maybe_send_remote(payload)


def _maybe_send_remote(payload: dict) -> None:
    """Fire-and-forget to the operator-configured sink (if any).
    Telemetry must never break the call: a malformed config.yaml (read
    here on the calling thread) is swallowed like any send failure."""
    try:
        from skypilot_tpu import config as config_lib
        loki_url = config_lib.get_nested(("usage", "loki_url"), None)
        endpoint = config_lib.get_nested(("usage", "endpoint"), None)
        if not loki_url and not endpoint:
            return
        if loki_url:
            # Loki push shape (reference: usage_lib._send_to_loki:296).
            body = json.dumps({"streams": [{
                "stream": {"type": "usage", "source": "skypilot_tpu"},
                "values": [[str(int(payload["ts"] * 1e9)),
                            json.dumps(payload)]],
            }]}).encode()
            url = loki_url
        else:
            body = json.dumps(payload).encode()
            url = endpoint
    except Exception:  # noqa: BLE001 — config/serialize errors
        return

    def post():
        import urllib.request
        try:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=3).close()
        except Exception:  # noqa: BLE001 — telemetry must never break
            pass

    # Decorated entrypoints run from multiple threads (serve replica
    # launchers), so the pending list is lock-guarded, and in-flight
    # sends are bounded: with a slow collector each POST can hang for
    # its full 3s timeout, so past the cap we drop the send rather than
    # pile up threads. Telemetry is lossy by design.
    with _pending_lock:
        _pending_sends[:] = [p for p in _pending_sends if p.is_alive()]
        if len(_pending_sends) >= _MAX_INFLIGHT_SENDS:
            return
        t = threading.Thread(target=post, daemon=True)
        t.start()  # inside the lock: an unstarted thread is not
        _pending_sends.append(t)  # alive, so a racing prune drops it


_MAX_INFLIGHT_SENDS = 8
_pending_sends: list = []
_pending_lock = threading.Lock()


def _drain_pending() -> None:
    """Give in-flight sends a bounded window at process exit — a daemon
    thread would otherwise be killed before the POST leaves a
    short-lived CLI process. Capped so a dead collector delays exit by
    at most ~2s, and ONLY when the operator configured a sink."""
    deadline = time.monotonic() + 2.0
    with _pending_lock:
        pending = list(_pending_sends)
    for t in pending:
        t.join(max(0.0, deadline - time.monotonic()))


import atexit  # noqa: E402
atexit.register(_drain_pending)


def entrypoint(fn: Callable) -> Callable:
    """Record one line per SDK entrypoint call: name, duration, outcome.
    Arguments are NOT recorded (no YAML/env contents — stricter than the
    reference's redaction, same spirit). The call also lands in the
    process metrics registry, so `stpu metrics` shows per-entrypoint
    latency for whatever this process did."""
    from skypilot_tpu.observability import metrics
    calls = metrics.counter(
        "stpu_entrypoint_calls_total",
        "SDK entrypoint invocations.", ("entrypoint", "outcome"))
    latency = metrics.histogram(
        "stpu_entrypoint_duration_seconds",
        "SDK entrypoint wall time.", ("entrypoint",))

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        if not _enabled():
            return fn(*args, **kwargs)
        t0 = time.time()
        t0_perf = time.perf_counter()
        outcome, exc_type = "ok", None
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            outcome = "error"
            exc_type = type(e).__name__
            raise
        finally:
            # Duration from the monotonic clock: an NTP step mid-call
            # must not record a negative (or wildly long) duration.
            duration = time.perf_counter() - t0_perf
            calls.labels(entrypoint=fn.__qualname__,
                         outcome=outcome).inc()
            latency.labels(entrypoint=fn.__qualname__).observe(duration)
            try:
                _record({
                    "ts": t0,
                    "run_id": _run_id(),
                    "user": _user_hash(),
                    "entrypoint": fn.__qualname__,
                    "duration_seconds": round(duration, 3),
                    "outcome": outcome,
                    "exception": exc_type,
                })
            except OSError:
                pass  # usage recording must never break the call

    return wrapper
