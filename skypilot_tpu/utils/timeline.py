"""Chrome trace-event profiling of client-side operations.

Reference analog: sky/utils/timeline.py (Event:21, @timeline.event
decorator :73, dump-at-exit gated on env). Enable by setting
``STPU_TIMELINE_FILE`` to an output path; open the result in
chrome://tracing or Perfetto.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_registered = False


def _enabled() -> Optional[str]:
    return os.environ.get("STPU_TIMELINE_FILE")


class Event:
    """Records a complete (ph=X) trace event around a with-block."""

    def __init__(self, name: str, message: Optional[str] = None):
        self._name = name
        self._message = message
        self._start = 0.0
        self._start_perf = 0.0

    def __enter__(self) -> "Event":
        # Wall clock for the trace's absolute placement (ts aligns
        # events across processes/hosts); monotonic for the duration —
        # an NTP step mid-block must not yield a negative dur.
        self._start = time.time()
        self._start_perf = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if _enabled() is None:
            return
        event = {
            "name": self._name,
            "cat": "stpu",
            "ph": "X",
            "ts": self._start * 1e6,
            "dur": (time.perf_counter() - self._start_perf) * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if self._message:
            event["args"] = {"message": self._message}
        global _registered
        with _lock:
            _events.append(event)
            if not _registered:
                atexit.register(save)
                _registered = True


def event(fn: Callable = None, *, name: Optional[str] = None) -> Callable:
    """Decorator recording fn's wall time as a trace event."""
    def decorator(func):
        event_name = name or f"{func.__module__}.{func.__qualname__}"

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with Event(event_name):
                return func(*args, **kwargs)
        return wrapper
    if fn is not None:
        return decorator(fn)
    return decorator


def save() -> None:
    path = _enabled()
    if path is None:
        return
    with _lock:
        payload = {"traceEvents": list(_events)}
    with open(os.path.expanduser(path), "w") as f:
        json.dump(payload, f)
