"""Shared plumbing for self-hosted jobs/serve controllers.

Reference analog: sky/utils/controller_utils.py (Controllers:88 enum with
name detection, get_controller_resources:384) plus the deployment pattern
of sky/jobs/core.py:30 / templates/jobs-controller.yaml.j2: the control
plane runs **on a launched controller cluster**, not on the client — a
closed client laptop must not kill spot recovery.

The client's SDK calls here resolve to three primitives:
  * ensure_controller_up(kind)   — launch/reuse the controller cluster
  * controller_handle(kind)      — passive lookup (None if absent)
  * run_on_controller(...)       — execute a framework command on the
    controller head with the controller's own isolated state dir
    (STPU_HOME=$HOME/.stpu), returning parsed JSON.

On the hermetic local provider the controller head is a directory +
subprocess; on SSH providers the same commands run over the wheel-installed
package. Controller resources come from config
``{jobs,serve}.controller.resources`` (default: the local provider).
"""
from __future__ import annotations

import enum
import json
import os
import shlex
import sys
from typing import Any, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.resources import Resources
from skypilot_tpu.status_lib import ClusterStatus


class Controllers(enum.Enum):
    JOBS = ("jobs", "stpu-jobs-controller")
    SERVE = ("serve", "stpu-serve-controller")

    @property
    def config_key(self) -> str:
        return self.value[0]

    @property
    def cluster_name(self) -> str:
        return self.value[1]


def controller_mode(kind: Controllers) -> str:
    """'cluster' (self-hosted, default) or 'local' (controller processes on
    the client — debugging and controller-logic unit tests)."""
    return config_lib.get_nested(
        (kind.config_key, "controller", "mode"), "cluster")


def controller_resources(kind: Controllers) -> Resources:
    from skypilot_tpu import clouds as clouds_lib
    spec = config_lib.get_nested(
        (kind.config_key, "controller", "resources"), None)
    res = (Resources.from_yaml_config(dict(spec)) if spec
           else Resources(cloud="local"))
    if kind.config_key == "serve" and \
            clouds_lib.cloud_manages_ports(res):
        # The serve controller hosts every service's LB: open the whole
        # LB port range at controller bring-up so each `serve up`
        # endpoint is reachable without a per-service firewall
        # round-trip (reference: serve controllers open
        # LB_PORT_RANGE the same way). Gated on the cloud actually
        # implementing OPEN_PORTS: on docker (ports published out of
        # band) the injected range would make the optimizer reject the
        # controller resources outright.
        from skypilot_tpu.serve.core import LB_PORT_RANGE_SPEC
        if LB_PORT_RANGE_SPEC not in res.ports:
            res = res.copy(ports=tuple(res.ports) + (LB_PORT_RANGE_SPEC,))
    return res


def controller_handle(kind: Controllers) -> Optional[Any]:
    """The controller cluster's handle if self-hosting is in effect and
    the cluster is UP, else None. Never launches anything.

    In 'local' mode this returns None even when a controller cluster
    exists (e.g. left over from earlier cluster-mode use), so local-mode
    jobs/services stay visible and cancellable on the client."""
    if controller_mode(kind) == "local":
        return None
    record = global_user_state.get_cluster_from_name(kind.cluster_name)
    if record is None or record["handle"] is None:
        return None
    if record["status"] != ClusterStatus.UP:
        return None
    return record["handle"]


def ensure_controller_up(kind: Controllers) -> Any:
    """Launch (or reuse/restart) the controller cluster; returns handle.

    Reference: jobs-controller.yaml.j2 filled and launched by
    sky/jobs/core.py:30. The init task is trivial — the cluster exists to
    host controller processes submitted per managed job / service.
    """
    from skypilot_tpu import execution
    from skypilot_tpu.task import Task

    handle = controller_handle(kind)
    if handle is not None:
        return handle
    task = Task(f"{kind.config_key}-controller-init", run="true")
    task.set_resources(controller_resources(kind))
    _, handle = execution.launch(task, cluster_name=kind.cluster_name,
                                 detach_run=True, stream_logs=False)
    return handle


def _repo_root() -> str:
    import skypilot_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(skypilot_tpu.__file__)))


def _controller_python(handle) -> str:
    """Interpreter for controller-side commands: the client's own
    interpreter on the local provider (same machine), the wheel-installed
    environment's python3 on SSH hosts (the client's sys.executable path
    does not exist there)."""
    if getattr(handle, "provider_name", None) == "local":
        return sys.executable
    return "python3"


def controller_command(handle, argv: list) -> str:
    """Wrap a framework command for execution on a controller host: state
    isolated under the host's own $HOME, package importable (PYTHONPATH
    covers the local provider; SSH hosts have the wheel installed). On
    the local provider the client's fake-bucket root is exported so
    translated storage mounts stay resolvable (the local analog of GCS
    being globally visible)."""
    inner = " ".join(shlex.quote(a) for a in argv)
    prefix = (f'export STPU_HOME="$HOME/.stpu"; '
              f'export PYTHONPATH={shlex.quote(_repo_root())}:'
              f'"$PYTHONPATH"; ')
    if getattr(handle, "provider_name", None) == "local":
        from skypilot_tpu.utils import paths
        bucket_root = os.environ.get(
            "STPU_BUCKET_ROOT", str(paths.home() / "buckets"))
        prefix += f"export STPU_BUCKET_ROOT={shlex.quote(bucket_root)}; "
    return prefix + inner


def run_on_controller(handle, module_argv: list, *,
                      parse_json: bool = True,
                      stream: bool = False) -> Any:
    """Run `python -m <module> ...` on the controller head.

    `module_argv` is [module, *args] (see module_command). With
    parse_json, the command's stdout must be a JSON document (the
    framework's remote-RPC convention — reference: codegen strings over
    SSH, sky/skylet/job_lib.py:803)."""
    runner = handle.get_command_runners()[0]
    argv = [_controller_python(handle), "-m", *module_argv]
    cmd = controller_command(handle, argv)
    if stream:
        return runner.run(cmd, stream_logs=True)
    rc, out, err = runner.run(cmd, require_outputs=True)
    if rc != 0:
        raise exceptions.CommandError(
            rc, f"controller command {module_argv}", f"{out}\n{err}")
    if not parse_json:
        return out
    try:
        # Tolerate stray warnings above the payload: parse the last line.
        payload = out.strip().splitlines()[-1]
        return json.loads(payload)
    except (json.JSONDecodeError, IndexError) as e:
        raise exceptions.SkyTpuError(
            f"Controller returned non-JSON output: {out!r} "
            f"(stderr: {err!r})") from e


def module_command(module: str, *args: str) -> list:
    """[module, *args] for run_on_controller (interpreter resolved
    per-provider there)."""
    return [module, *args]


# ------------------------------------------------ local-mount translation
def _translation_store() -> str:
    """Store type for translated mounts: explicit config wins; else GCS
    when GCP is enabled; else the hermetic local store."""
    configured = config_lib.get_nested(("controller", "bucket_store"),
                                       None)
    if configured:
        return str(configured)
    enabled = global_user_state.get_enabled_clouds()
    return "gcs" if "gcp" in (enabled or []) else "local"


def maybe_translate_local_file_mounts_and_sync_up(task,
                                                  run_id: str) -> None:
    """Rewrite client-local workdir/file_mounts into bucket storage
    mounts, uploading NOW (reference:
    sky/utils/controller_utils.py:568).

    A task handed to a self-hosted controller otherwise references paths
    that exist only on the client: the controller cluster can't see
    them, and preemption recovery would re-sync nothing. After this
    call the task carries no client-local paths:

      * ``workdir:`` → bucket ``stpu-jobs-wd-<run_id>`` COPY-mounted at
        ``~/stpu_workdir`` (where run/setup already cd to);
      * each local ``file_mounts`` entry → bucket
        ``stpu-jobs-fm-<n>-<run_id>`` COPY-mounted at its destination;
      * cloud-store URIs (gs://, s3://, http...) stay as file_mounts —
        they are already recoverable from anywhere.

    Buckets are marked non-persistent (job-scoped intermediates).
    Mutates ``task`` in place. No-op when nothing is client-local.
    """
    from skypilot_tpu.data import cloud_stores
    from skypilot_tpu.data import storage as storage_lib

    store = _translation_store()

    def bucket_name(tag: str) -> str:
        # Bucket names: lowercase, no underscores (GCS naming rules).
        return f"stpu-jobs-{tag}-{run_id}".lower().replace("_", "-")

    def translated(tag: str, src: str) -> Any:
        sto = storage_lib.Storage(
            name=bucket_name(tag), source=src, store=store,
            persistent=False, mode="COPY")
        sto.sync()  # upload while the client-local path still exists
        # Drop the local source: the controller must never re-sync from
        # a client path, and to_yaml_config must not ship one.
        sto.source = None
        sto.store.source = None
        return sto

    from skypilot_tpu.agent import constants as agent_constants
    new_storage = {}
    if task.workdir is not None:
        # Mounted where setup/run already cd to (slice_backend prepends
        # `cd ~/{WORKDIR}` to both).
        new_storage[f"~/{agent_constants.WORKDIR}"] = translated(
            "wd", task.workdir)
        task.workdir = None

    remaining = {}
    for i, (dst, src) in enumerate(sorted(
            (task.file_mounts or {}).items())):
        if cloud_stores.is_cloud_store_url(src):
            remaining[dst] = src
            continue
        src_abs = os.path.abspath(os.path.expanduser(src))
        if os.path.isfile(src_abs):
            # A single FILE must stay a file at dst — a bucket mount
            # would turn dst into a directory. Upload it and rewrite the
            # mount as a bucket URI the backend downloads file-to-file.
            sto = translated(f"fm{i}", src)
            if store == "ibm":
                # cos:// URLs are region-first (reference shape:
                # cos://<region>/<bucket>/<key>).
                from skypilot_tpu.data import storage as storage_lib2
                remaining[dst] = (
                    f"cos://{storage_lib2.ibm_cos_region()}/"
                    f"{sto.name}/{os.path.basename(src_abs)}")
            else:
                remaining[dst] = (f"{_SCHEME.get(store, store)}://"
                                  f"{sto.name}/"
                                  f"{os.path.basename(src_abs)}")
        else:
            new_storage[dst] = translated(f"fm{i}", src)
    task.file_mounts = remaining
    if new_storage:
        task.storage_mounts = {**(task.storage_mounts or {}),
                               **new_storage}


# URI scheme <-> store-type mapping for translated single-file mounts.
_SCHEME = {"gcs": "gs", "s3": "s3", "r2": "r2", "ibm": "cos",
           "local": "local"}
_STORE_BY_SCHEME = {v: k for k, v in _SCHEME.items()}


def cleanup_translated_buckets(dag_or_task) -> None:
    """Delete the job-scoped buckets translation created, when the
    managed job / service that owns them ends (the reference deletes
    intermediate buckets at job termination). Identified by the
    non-persistent flag (storage mounts) and the ``stpu-jobs-`` bucket
    prefix (translated single-file URIs). Best-effort: a half-deleted
    bucket set must never fail job finalization."""
    from skypilot_tpu.data import storage as storage_lib
    tasks = getattr(dag_or_task, "tasks", None) or [dag_or_task]
    for task in tasks:
        for sto in (task.storage_mounts or {}).values():
            if getattr(sto, "persistent", True):
                continue
            try:
                sto.delete()
            except Exception:  # noqa: BLE001
                pass
        for src in (task.file_mounts or {}).values():
            scheme, sep, rest = str(src).partition("://")
            parts = rest.split("/") if sep else []
            # cos:// URLs are region-first; the bucket is the SECOND
            # path component.
            if scheme == "cos":
                bucket = parts[1] if len(parts) > 1 else ""
            else:
                bucket = parts[0] if parts else ""
            if (not bucket.startswith("stpu-jobs-")
                    or scheme not in _STORE_BY_SCHEME):
                continue
            try:
                storage_lib.Storage(
                    name=bucket, store=_STORE_BY_SCHEME[scheme],
                    persistent=False).delete()
            except Exception:  # noqa: BLE001
                pass
