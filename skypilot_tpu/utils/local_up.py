"""`stpu local up/down`: a Kind-backed local Kubernetes cluster.

Reference analog: `sky local up` (sky/cli.py:5054-5185) — creates a
Kind cluster so the kubernetes provider has a real, free, laptop-local
target. Tasks then run against it with ``resources: {cloud:
kubernetes}``. Hermetic tests monkeypatch the ``_run`` seam; the
``--kind-live`` pytest flag exercises the real path when the binaries
exist.
"""
from __future__ import annotations

import shutil
import subprocess
from typing import List, Optional, Tuple

from skypilot_tpu import exceptions

DEFAULT_CLUSTER = "stpu-local"


def _run(argv: List[str], timeout: int = 600) -> Tuple[int, str]:
    proc = subprocess.run(argv, capture_output=True, text=True,
                          timeout=timeout)
    return proc.returncode, (proc.stdout + proc.stderr).strip()


def _which(binary: str) -> Optional[str]:
    return shutil.which(binary)


def check_binaries() -> Optional[str]:
    """None when kind+kubectl exist; otherwise a human explanation."""
    missing = [b for b in ("kind", "kubectl") if _which(b) is None]
    if missing:
        return (f"missing {' and '.join(missing)} on PATH — install "
                "Kind (https://kind.sigs.k8s.io) and kubectl, then "
                "re-run `stpu local up`.")
    return None


def cluster_exists(name: str = DEFAULT_CLUSTER) -> bool:
    rc, out = _run(["kind", "get", "clusters"])
    return rc == 0 and name in out.split()


def up(name: str = DEFAULT_CLUSTER) -> str:
    """Create (or adopt) the Kind cluster; returns its kube context."""
    problem = check_binaries()
    if problem:
        raise exceptions.SkyTpuError(f"`stpu local up`: {problem}")
    if cluster_exists(name):
        return f"kind-{name}"
    rc, out = _run(["kind", "create", "cluster", "--name", name])
    if rc != 0:
        raise exceptions.SkyTpuError(
            f"kind create cluster failed (rc {rc}): {out[-500:]}")
    # Sanity: the API server answers through the context kind wrote.
    rc, out = _run(["kubectl", "--context", f"kind-{name}",
                    "get", "nodes"])
    if rc != 0:
        raise exceptions.SkyTpuError(
            f"kind cluster up but kubectl cannot reach it: {out[-300:]}")
    return f"kind-{name}"


def down(name: str = DEFAULT_CLUSTER) -> None:
    problem = check_binaries()
    if problem:
        raise exceptions.SkyTpuError(f"`stpu local down`: {problem}")
    rc, out = _run(["kind", "delete", "cluster", "--name", name])
    if rc != 0:
        raise exceptions.SkyTpuError(
            f"kind delete cluster failed (rc {rc}): {out[-500:]}")
