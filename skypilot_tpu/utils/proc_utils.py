"""Process-table checks for pidfile-kill paths.

Recorded pids (serve controller / load-balancer rows) can outlive the
process they named: after a controller-host reboot or long downtime the
kernel may have recycled the pid for an unrelated process, and a blind
SIGTERM would kill it. Before signalling a recorded pid, callers verify
the live process still looks like the one that was recorded.

Reference analog: sky/serve keeps single-owner pid assumptions in its
service supervisor; we make the recycled-pid case explicit instead.
"""
from __future__ import annotations


def cmdline_matches(pid: int, marker: str) -> bool:
    """True if pid is alive AND its cmdline contains ``marker``.

    Reads /proc/<pid>/cmdline (argv joined by NULs). Any read failure —
    process gone, permission, non-Linux /proc — returns False so the
    caller skips the kill rather than signalling an unknown process.
    """
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            argv = f.read().replace(b"\x00", b" ").decode(
                "utf-8", "replace")
    except OSError:
        return False
    return marker in argv


def pid_state(pid: int) -> str:
    """'dead', 'zombie', or 'running' for ``pid``.

    A zombie (exited, unreaped — detached children whose parent is
    gone) stays kill-0-able forever, so liveness checks that gate
    adoption or teardown grace must not treat it as running.
    PermissionError means the process exists but belongs to someone
    else — still 'running' (the /proc files below are world-readable
    on Linux regardless).
    """
    import os
    if not pid or pid <= 0:
        return "dead"
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return "dead"
    except PermissionError:
        pass
    except OSError:
        return "dead"
    try:
        with open(f"/proc/{pid}/stat") as f:
            if f.read().rsplit(")", 1)[-1].split()[0] == "Z":
                return "zombie"
    except (OSError, IndexError):
        pass  # no /proc (non-linux): kill-0 is the answer
    return "running"
