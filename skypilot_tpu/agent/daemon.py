"""On-host daemon: the skylet analog that runs on every cluster head.

Reference analog: sky/skylet/skylet.py:17-34 (event loop) and
sky/skylet/events.py (AutostopEvent:90 — idle countdown then
self-stop/down; JobSchedulerEvent:62 — job-queue pump). The TPU-native
simplification: gang scheduling is slice-atomic and handled by gang_exec,
so the daemon's job event reduces to *reconciliation* — detecting gangs
whose driver died without recording a terminal status.

The daemon is started detached at provision time (local provider:
spawned by the backend; SSH hosts: provisioner._AGENT_START_CMD) and
self-terminates when its cluster stops or is torn down. Autostop is
enforced HERE, on the cluster, with zero client involvement: the client
writing ``autostop.json`` is the last it has to do — an idle cluster then
stops itself exactly like the reference's AutostopEvent, even if the
client machine is gone.

State layout (under the host's $HOME):
    .stpu_agent/cluster.json   — identity + provider config (provision)
    .stpu_agent/autostop.json  — {"idle_minutes", "down", "set_at"}
    .stpu_agent/daemon.pid     — liveness marker
    .stpu_agent/daemon.log     — event log
    .stpu_agent/health.json    — TPU topology probe result
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import time
from typing import Any, Dict, Optional

AGENT_DIR = ".stpu_agent"


class Daemon:

    def __init__(self, home: Optional[str] = None,
                 interval: Optional[float] = None):
        self.home = pathlib.Path(home or os.path.expanduser("~"))
        self.agent_dir = self.home / AGENT_DIR
        self.agent_dir.mkdir(parents=True, exist_ok=True)
        self.cluster: Dict[str, Any] = self._load_json("cluster.json") or {}
        self.interval = float(
            interval if interval is not None
            else self.cluster.get("daemon_interval", 30.0))
        # The local provider keeps cluster metadata under the *client's*
        # STPU_HOME; carry it over so provision.local resolves the same
        # tree from inside the daemon process.
        stpu_home = self.cluster.get("stpu_home")
        if stpu_home:
            os.environ["STPU_HOME"] = stpu_home
        self.started_at = time.time()
        # My own code's version, computed once: the on-disk stamp
        # (written LAST by setup_agent_runtime) moving away from this
        # means a newer runtime was shipped — exit so the re-shipper's
        # restart (or the next one) runs the new code. Reference:
        # sky/skylet/attempt_skylet.py:42-47.
        try:
            from skypilot_tpu.utils import wheel_utils
            self._my_version: Optional[str] = \
                wheel_utils.runtime_version()
        except Exception:  # noqa: BLE001 — never block daemon boot
            self._my_version = None
        self._stale_ticks = 0
        # Host-agent gauges, dumped to .stpu_agent/metrics.prom each
        # tick (textfile-collector pattern: a node_exporter picks it
        # up; the daemon itself binds no port).
        from skypilot_tpu.observability import metrics
        self._heartbeat = metrics.gauge(
            "stpu_agent_heartbeat_timestamp_seconds",
            "Wall-clock time of the daemon's last completed tick.")
        self._uptime = metrics.gauge(
            "stpu_agent_uptime_seconds", "Daemon uptime.")
        self._running_jobs = metrics.gauge(
            "stpu_agent_running_jobs",
            "RUNNING jobs with a live gang driver on this host.")
        self._reconciled = metrics.counter(
            "stpu_agent_reconciled_jobs_total",
            "RUNNING jobs marked FAILED because their driver died.")
        self._started_mono = time.monotonic()

    def export_metrics(self) -> None:
        """Write the registry's exposition text next to health.json
        (atomic replace: a textfile collector reading mid-write must
        never see a truncated file)."""
        from skypilot_tpu.observability import metrics
        self._heartbeat.set(time.time())
        self._uptime.set(time.monotonic() - self._started_mono)
        metrics.dump_to_file(self.agent_dir / "metrics.prom")

    def runtime_stale(self) -> bool:
        """True after TWO consecutive ticks of version mismatch (one
        tick of slack absorbs the bring-up window where the new daemon
        starts just before the stamp is written)."""
        if self._my_version is None:
            return False
        from skypilot_tpu.agent import constants as agent_constants
        try:
            stamp = (self.agent_dir /
                     agent_constants.RUNTIME_VERSION_BASENAME
                     ).read_text().strip()
        except OSError:
            return False
        if not stamp or stamp == self._my_version:
            self._stale_ticks = 0
            return False
        self._stale_ticks += 1
        return self._stale_ticks >= 2

    # ------------------------------------------------------------ plumbing
    def _load_json(self, name: str) -> Optional[Dict[str, Any]]:
        path = self.agent_dir / name
        try:
            return json.loads(path.read_text())
        except (OSError, ValueError):
            return None

    def log(self, msg: str) -> None:
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        try:
            with open(self.agent_dir / "daemon.log", "a") as f:
                f.write(f"[{stamp}] {msg}\n")
        except OSError:
            # After autostop --down the terminate path may have deleted
            # agent_dir itself (local provider); exit quietly.
            pass

    # -------------------------------------------------------------- events
    def reconcile_jobs(self) -> None:
        """Mark RUNNING jobs whose gang driver died as FAILED (reference:
        skylet reconciles ray-job state drift, job_lib.update_job_status).
        """
        from skypilot_tpu.agent import job_lib
        from skypilot_tpu.observability import events
        running = 0
        for job in job_lib.queue(home=str(self.home), all_jobs=False):
            status = job_lib.JobStatus(job["status"])
            pid = job.get("pid")
            if status != job_lib.JobStatus.RUNNING or not pid:
                continue
            try:
                os.kill(pid, 0)
                running += 1
            except ProcessLookupError:
                self.log(f"job {job['job_id']}: driver pid {pid} gone; "
                         "marking FAILED")
                job_lib.set_status(job["job_id"], job_lib.JobStatus.FAILED,
                                   home=str(self.home))
                events.emit("agent",
                            self.cluster.get("cluster_name", "?"),
                            "job_reconciled_failed",
                            job_id=job["job_id"], driver_pid=pid)
                self._reconciled.inc()
            except PermissionError:
                running += 1  # pid exists under another uid: alive
        self._running_jobs.set(running)

    def check_autostop(self) -> bool:
        """Stop/down the cluster when idle long enough. Returns True when
        the daemon should exit (cluster no longer running)."""
        from skypilot_tpu.agent import job_lib
        cfg = self._load_json("autostop.json")
        if not cfg:
            return False
        idle_minutes = cfg.get("idle_minutes", -1)
        if idle_minutes is None or idle_minutes < 0:
            return False
        if not job_lib.is_cluster_idle(home=str(self.home)):
            return False
        baseline = max(
            job_lib.last_activity_time(home=str(self.home)),
            float(cfg.get("set_at", self.started_at)))
        idle_for = time.time() - baseline  # noqa: stpu-wallclock baseline mixes job-DB wall stamps with autostop set_at written by the remote client
        # Even at -i 0, give an in-flight submission a moment: the
        # client sets autostop at PRE_EXEC and then ships the job spec
        # to this head — terminating inside that window would kill the
        # cluster between rsync and submit.
        grace = float(os.environ.get("STPU_AUTOSTOP_GRACE_SECONDS", 10))
        if idle_for < max(idle_minutes * 60, grace):
            return False
        down = bool(cfg.get("down"))
        self.log(f"idle {idle_for:.0f}s >= {idle_minutes}m threshold; "
                 f"{'terminating' if down else 'stopping'} cluster")
        from skypilot_tpu.observability import events
        events.emit("agent", self.cluster.get("cluster_name", "?"),
                    "autostop", down=down,
                    idle_seconds=round(idle_for, 1))
        # Only exit when the action actually succeeded; a transient API
        # failure is retried on the next tick instead of silently
        # disabling autostop forever.
        return self._self_stop(down)

    def _self_stop(self, down: bool) -> bool:
        from skypilot_tpu import provision as provision_api
        name = self.cluster.get("cluster_name")
        provider = self.cluster.get("provider_name")
        pconfig = self.cluster.get("provider_config", {})
        if not name or not provider:
            self.log("no cluster identity recorded; cannot autostop")
            return False
        try:
            if down:
                provision_api.terminate_instances(provider, name, pconfig)
            else:
                provision_api.stop_instances(provider, name, pconfig)
            return True
        except Exception as e:  # noqa: BLE001 — daemon must not die here
            self.log(f"autostop action failed (will retry): {e!r}")
            return False

    def cluster_gone(self) -> bool:
        """True once the provider no longer reports us running — the
        daemon's cue to exit (covers client-initiated stop/down too)."""
        from skypilot_tpu import provision as provision_api
        name = self.cluster.get("cluster_name")
        provider = self.cluster.get("provider_name")
        if not name or not provider:
            return False
        try:
            statuses = provision_api.query_instances(
                provider, name, self.cluster.get("provider_config", {}))
        except Exception:
            return False
        return not statuses or all(
            s in ("stopped", "terminated") for s in statuses.values())

    # ---------------------------------------------------------------- loop
    def run(self) -> None:
        from skypilot_tpu.agent import tpu_health
        from skypilot_tpu.observability import events
        (self.agent_dir / "daemon.pid").write_text(str(os.getpid()))
        expected = int(self.cluster.get("chips_per_host", 0))
        report = tpu_health.probe(expected)
        tpu_health.write_report(report, home=str(self.home))
        tpu_health.export_gauges(report)
        self.log(f"daemon up (pid {os.getpid()}, "
                 f"interval {self.interval}s, health: {report['detail']})")
        events.emit("agent", self.cluster.get("cluster_name", "?"),
                    "daemon_up", pid=os.getpid(),
                    tpu_healthy=report["ok"])
        last_ok = report["ok"]
        while True:
            try:
                self.reconcile_jobs()
                # RE-probe every tick (a /dev/accel* glob — cheap): a
                # chip lost an hour in must flip the exported gauge,
                # not fossilize the boot-time verdict next to a fresh
                # heartbeat.
                report = tpu_health.probe(expected)
                tpu_health.export_gauges(report)
                if report["ok"] != last_ok:
                    tpu_health.write_report(report, home=str(self.home))
                    self.log(f"TPU health changed: {report['detail']}")
                    events.emit("agent",
                                self.cluster.get("cluster_name", "?"),
                                "tpu_health_changed",
                                ok=report["ok"],
                                detail=report["detail"])
                    last_ok = report["ok"]
                self.export_metrics()
                if self.check_autostop() or self.cluster_gone():
                    break
                if self.runtime_stale():
                    self.log("runtime version stamp changed on disk; "
                             "exiting so the new runtime's daemon "
                             "takes over")
                    break
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                self.log(f"event error: {e!r}")
            time.sleep(self.interval)
        self.log("daemon exiting")
        try:
            (self.agent_dir / "daemon.pid").unlink()
        except OSError:
            pass


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--home", default=None,
                        help="host $HOME override (local provider)")
    parser.add_argument("--interval", type=float, default=None,
                        help="event-loop period in seconds")
    args = parser.parse_args()
    Daemon(home=args.home, interval=args.interval).run()


if __name__ == "__main__":
    main()
