"""The env-var contract between the framework and user workloads.

Reference analog: sky/skylet/constants.py:258-261 (SKYPILOT_NODE_RANK /
NODE_IPS / NUM_NODES / NUM_GPUS_PER_NODE). We keep the same names so
reference-style recipes port unchanged, and add the TPU-native
coordinator/slice variables that feed ``jax.distributed.initialize`` over
ICI/DCN instead of NCCL's MASTER_ADDR.
"""

# Reference-compatible contract (host granularity).
NODE_RANK = "SKYPILOT_NODE_RANK"
NODE_IPS = "SKYPILOT_NODE_IPS"           # newline-separated, rank order
NUM_NODES = "SKYPILOT_NUM_NODES"          # total hosts across all slices
TASK_ID = "SKYPILOT_TASK_ID"
CLUSTER_NAME = "SKYPILOT_CLUSTER_INFO_CLUSTER_NAME"
NUM_CHIPS_PER_NODE = "SKYPILOT_NUM_TPU_CHIPS_PER_NODE"

# TPU-native additions.
COORDINATOR_ADDR = "SKYPILOT_COORDINATOR_ADDR"   # head_ip:port for
                                                 # jax.distributed
COORDINATOR_PORT = 8476
NUM_SLICES = "SKYPILOT_NUM_SLICES"
SLICE_INDEX = "SKYPILOT_SLICE_INDEX"             # which slice this host
                                                 # belongs to
# Multi-slice (DCN-spanning) jax runs read MEGASCALE_* from these.
MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"

# Gang-agent coordination (native host-agent core, agent/native.py):
# the gang driver runs a coordinator; each host's job wrapper connects,
# barriers before exec (reference pg.ready() semantics) and heartbeats
# during the run. For SSH hosts the coordinator is reached through an SSH
# reverse tunnel bound on this fixed remote port.
GANG_COORD_ADDR = "STPU_GANG_COORD_ADDR"         # host:port for the wrapper
# Auth token for the DIRECT-CONNECT coordinator mode (agent transport:
# the coordinator binds the pod network instead of hiding behind an
# ssh reverse tunnel). Rides the worker's env script, never argv.
GANG_COORD_TOKEN = "STPU_GANG_COORD_TOKEN"
# Remote-exec agent (agent/exec_server.py): the sshd replacement for
# kubernetes worker pods. The token is an independent random secret
# generated next to the cluster keypair and shipped at bring-up —
# presenting it grants exec on worker pods, so it must never be
# derivable from public material.
EXEC_PORT = 8479
EXEC_TOKEN_PATH = "~/.stpu_agent/exec_token"
# Fixed auth-token width shared by the exec protocol and the
# direct-connect gang coordinator (hostagent.cc kTokenLen is the one
# unavoidable duplicate).
TOKEN_LEN = 32


def pad_token(token: str) -> str:
    """Normalize to exactly TOKEN_LEN chars; empty stays empty (it
    selects the loopback-only, unauthenticated coordinator mode and is
    REJECTED outright by the exec server)."""
    if not token:
        return ""
    return token[:TOKEN_LEN].ljust(TOKEN_LEN, "0")
GANG_BARRIER_TIMEOUT_SECONDS = 600               # slowest-host allowance
HEARTBEAT_TIMEOUT_MS = 15_000
# Exit code recorded for ranks force-cancelled because the gang failed
# (reference get_or_fail semantics, cloud_vm_ray_backend.py:296-331).
GANG_FAILED_RC = 137

# Cluster-internal SSH key (on the head, installed by the provisioner):
# lets the head-resident gang driver reach workers over the slice's
# internal network with no client involvement.
INTERNAL_KEY_PATH = "~/.ssh/stpu_internal_key"

# Wheel tree-hash of the runtime shipped to the cluster, written by
# provisioner.setup_agent_runtime. A reused cluster whose stamp differs
# from the client's current wheel gets the runtime re-shipped and the
# daemon restarted (reference: sky/skylet/attempt_skylet.py:42-47
# restarts skylet on version mismatch) — otherwise head-side job_cli /
# daemon code silently drifts from the client after an upgrade.
RUNTIME_VERSION_BASENAME = "runtime_version"
RUNTIME_VERSION_PATH = f"~/.stpu_agent/{RUNTIME_VERSION_BASENAME}"

# On-host layout (under the host's $HOME).
AGENT_DIR = ".stpu_agent"
JOBS_DB = f"{AGENT_DIR}/jobs.db"
LOGS_DIR = "stpu_logs"
WORKDIR = "stpu_workdir"

# Job queue statuses considered terminal.
TERMINAL = ("SUCCEEDED", "FAILED", "FAILED_SETUP", "CANCELLED")
