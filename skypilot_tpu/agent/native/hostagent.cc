// Host-agent core: gang membership, rank barrier, heartbeat failure
// detection over TCP. The native replacement for the coordination slice of
// Ray that the reference leans on (STRICT_SPREAD placement-group ready +
// node liveness; reference: sky/backends/cloud_vm_ray_backend.py:361-505).
//
// One coordinator runs next to the gang driver (head host); one client runs
// in each host's job wrapper. Protocol: fixed 16-byte little-endian
// messages over TCP:
//   { uint32 magic; uint32 type; int32 rank; int32 arg; }
// Types: REGISTER(1: rank), ACK(2), BARRIER_REQ(3: generation),
//        BARRIER_REL(4: generation), HEARTBEAT(5), FAIL(6: failed rank).
//
// Failure semantics (slice-atomic, reference get_or_fail rc-137): the
// coordinator declares a rank dead on connection EOF/reset or missed
// heartbeats, then broadcasts FAIL to every client; blocked barriers
// return an error and stpu_*_failed_rank() reports the rank.
//
// Exposed as a C ABI for ctypes (skypilot_tpu/agent/native.py); a
// pure-Python protocol twin exists for hosts without a toolchain.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <mutex>
#include <set>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x53545055;  // "STPU"
constexpr int kRegisterTimeoutSec = 10;
// Fixed-width pre-register auth token (hex chars). Empty token => the
// legacy loopback-only mode; non-empty => the coordinator binds the
// network (pod/VM internal IP) and every connection must present the
// token before its REGISTER — the authenticated direct-connect mode
// the sshd-free Kubernetes transport uses (no reverse tunnel).
constexpr size_t kTokenLen = 32;

bool TokenMatches(const char* got, const std::string& want) {
  // Constant-time-ish compare: no early exit on mismatch.
  unsigned diff = 0;
  for (size_t i = 0; i < kTokenLen; ++i)
    diff |= static_cast<unsigned>(got[i] ^ want[i]);
  return diff == 0;
}

enum MsgType : uint32_t {
  kRegister = 1,
  kAck = 2,
  kBarrierReq = 3,
  kBarrierRel = 4,
  kHeartbeat = 5,
  kFail = 6,
  kGoodbye = 7,  // clean departure: subsequent EOF is not a failure
};

struct Msg {
  uint32_t magic;
  uint32_t type;
  int32_t rank;
  int32_t arg;
};

using Clock = std::chrono::steady_clock;

bool SendAll(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool SendMsg(int fd, uint32_t type, int32_t rank, int32_t arg) {
  Msg m{kMagic, type, rank, arg};
  return SendAll(fd, &m, sizeof(m));
}

// ---------------------------------------------------------------------------
// Coordinator
// ---------------------------------------------------------------------------

class Coordinator {
 public:
  Coordinator(int port, int num_hosts, int heartbeat_timeout_ms,
              const char* token)
      : num_hosts_(num_hosts),
        heartbeat_timeout_ms_(heartbeat_timeout_ms),
        token_(token ? token : ""),
        failed_rank_(-1),
        stop_(false) {
    if (!token_.empty()) token_.resize(kTokenLen, '0');
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    // Loopback only WITHOUT a token: local hosts and SSH hosts both
    // reach the coordinator via 127.0.0.1 (reverse tunnel,
    // gang_exec.py); the unauthenticated protocol must not be
    // network-reachable. WITH a token, bind the network: direct-connect
    // transports (kubernetes pods) authenticate per connection.
    addr.sin_addr.s_addr =
        token_.empty() ? htonl(INADDR_LOOPBACK) : htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd_, num_hosts + 8) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread(&Coordinator::AcceptLoop, this);
    monitor_thread_ = std::thread(&Coordinator::MonitorLoop, this);
  }

  ~Coordinator() {
    stop_.store(true);
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (listen_fd_ >= 0) ::close(listen_fd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    if (monitor_thread_.joinable()) monitor_thread_.join();
    std::vector<std::thread> readers;
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (auto& kv : conns_) ::shutdown(kv.second.fd, SHUT_RDWR);
      // Connections that never completed REGISTER would otherwise park a
      // reader in RecvAll forever and deadlock the joins below.
      for (int fd : pending_fds_) ::shutdown(fd, SHUT_RDWR);
      readers.swap(reader_threads_);
    }
    for (auto& t : readers)
      if (t.joinable()) t.join();
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : conns_) ::close(kv.second.fd);
    for (int fd : pending_fds_) ::close(fd);
  }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }
  int failed_rank() const { return failed_rank_.load(); }

  int registered_count() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int>(conns_.size());
  }

  // Blocks until all hosts registered, a failure, or timeout.
  // 0 = ready; -1 = timeout; -2-r = rank r failed.
  int WaitReady(int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    bool done = cv_.wait_for(
        lk, std::chrono::milliseconds(timeout_ms), [&] {
          return failed_rank_.load() >= 0 ||
                 static_cast<int>(conns_.size()) == num_hosts_;
        });
    int fr = failed_rank_.load();
    if (fr >= 0) return -2 - fr;
    if (!done) return -1;
    return 0;
  }

 private:
  struct Conn {
    int fd;
    Clock::time_point last_heartbeat;
  };

  void AcceptLoop() {
    while (!stop_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stop_.load()) return;
        if (errno == EINTR) continue;
        return;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // A peer that connects but never sends REGISTER must not hold a
      // reader forever: bound the registration read.
      timeval tv{};
      tv.tv_sec = kRegisterTimeoutSec;
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      std::lock_guard<std::mutex> lk(mu_);
      pending_fds_.insert(fd);
      reader_threads_.emplace_back(&Coordinator::ReaderLoop, this, fd);
    }
  }

  void DropPending(int fd) {
    std::lock_guard<std::mutex> lk(mu_);
    pending_fds_.erase(fd);
  }

  void ReaderLoop(int fd) {
    if (!token_.empty()) {
      char got[kTokenLen];
      if (!RecvAll(fd, got, sizeof(got)) || !TokenMatches(got, token_)) {
        DropPending(fd);
        ::close(fd);
        return;
      }
    }
    Msg m{};
    if (!RecvAll(fd, &m, sizeof(m)) || m.magic != kMagic ||
        m.type != kRegister) {
      DropPending(fd);
      ::close(fd);
      return;
    }
    int rank = m.rank;
    {
      std::lock_guard<std::mutex> lk(mu_);
      pending_fds_.erase(fd);
      if (rank < 0 || rank >= num_hosts_ || conns_.count(rank)) {
        ::close(fd);
        return;
      }
      conns_[rank] = Conn{fd, Clock::now()};
    }
    // Registered: post-registration reads are bounded by heartbeats, not
    // the socket timeout.
    timeval tv{};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    SendMsg(fd, kAck, rank, 0);
    cv_.notify_all();
    while (!stop_.load()) {
      if (!RecvAll(fd, &m, sizeof(m)) || m.magic != kMagic) {
        if (!stop_.load()) DeclareFailed(rank);
        return;
      }
      if (m.type == kHeartbeat) {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = conns_.find(rank);
        if (it != conns_.end()) it->second.last_heartbeat = Clock::now();
      } else if (m.type == kBarrierReq) {
        OnBarrierReq(rank, m.arg);
      } else if (m.type == kGoodbye) {
        // Clean departure (host's command finished): stop tracking;
        // EOF that follows is not a failure.
        std::lock_guard<std::mutex> lk(mu_);
        auto it = conns_.find(rank);
        if (it != conns_.end()) {
          ::close(it->second.fd);
          conns_.erase(it);
        }
        return;
      }
    }
  }

  void OnBarrierReq(int rank, int gen) {
    std::lock_guard<std::mutex> lk(mu_);
    // Set semantics: a retried BARRIER_REQ from the same rank must not
    // double-count (matches the Python twin).
    barrier_waiters_[gen].insert(rank);
    if (static_cast<int>(barrier_waiters_[gen].size()) == num_hosts_) {
      for (auto& kv : conns_) SendMsg(kv.second.fd, kBarrierRel, -1, gen);
      barrier_waiters_.erase(gen);
    }
  }

  void MonitorLoop() {
    while (!stop_.load()) {
      std::this_thread::sleep_for(std::chrono::milliseconds(
          std::min(heartbeat_timeout_ms_ / 4 + 1, 500)));
      if (heartbeat_timeout_ms_ <= 0) continue;
      int dead = -1;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto now = Clock::now();
        for (auto& kv : conns_) {
          auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                        now - kv.second.last_heartbeat)
                        .count();
          if (ms > heartbeat_timeout_ms_) {
            dead = kv.first;
            break;
          }
        }
      }
      if (dead >= 0) DeclareFailed(dead);
    }
  }

  void DeclareFailed(int rank) {
    int expected = -1;
    if (!failed_rank_.compare_exchange_strong(expected, rank)) return;
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : conns_) {
      if (kv.first != rank) SendMsg(kv.second.fd, kFail, rank, 0);
    }
    cv_.notify_all();
  }

  int num_hosts_;
  int heartbeat_timeout_ms_;
  std::string token_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<int> failed_rank_;
  std::atomic<bool> stop_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<int, Conn> conns_;
  std::set<int> pending_fds_;  // accepted, not yet registered
  std::map<int, std::set<int>> barrier_waiters_;
  std::vector<std::thread> reader_threads_;
  std::thread accept_thread_;
  std::thread monitor_thread_;
};

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------

class Client {
 public:
  Client(const char* host, int port, int rank, int timeout_ms,
         int heartbeat_interval_ms, const char* token)
      : rank_(rank),
        heartbeat_interval_ms_(heartbeat_interval_ms),
        token_(token ? token : ""),
        failed_rank_(-1),
        registered_(false),
        stop_(false) {
    if (!token_.empty()) token_.resize(kTokenLen, '0');
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      Close();
      return;
    }
    auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
    while (::connect(fd_, reinterpret_cast<sockaddr*>(&addr),
                     sizeof(addr)) != 0) {
      ::close(fd_);
      if (Clock::now() >= deadline) {
        fd_ = -1;
        return;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    }
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (!token_.empty() &&
        !SendAll(fd_, token_.data(), kTokenLen)) {
      Close();
      return;
    }
    if (!SendMsg(fd_, kRegister, rank_, 0)) {
      Close();
      return;
    }
    reader_thread_ = std::thread(&Client::ReaderLoop, this);
    {
      // Registration ack gates success.
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait_until(lk, deadline,
                     [&] { return registered_ || fd_ < 0; });
      if (!registered_) {
        lk.unlock();
        Close();
        return;
      }
    }
    heartbeat_thread_ = std::thread(&Client::HeartbeatLoop, this);
  }

  ~Client() {
    stop_.store(true);
    if (fd_ >= 0) SendMsg(fd_, kGoodbye, rank_, 0);
    Close();
    if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
    if (reader_thread_.joinable()) reader_thread_.join();
  }

  bool ok() const { return fd_ >= 0; }
  int failed_rank() const { return failed_rank_.load(); }

  // Dirty close — no goodbye; the coordinator will declare this rank
  // failed (test hook simulating host death).
  void Abort() { Close(); }

  // 0 = released; -1 = timeout/disconnect; -2-r = rank r failed.
  int Barrier(int gen, int timeout_ms) {
    if (fd_ < 0) return -1;
    if (!SendMsg(fd_, kBarrierReq, rank_, gen)) return -1;
    std::unique_lock<std::mutex> lk(mu_);
    bool done = cv_.wait_for(
        lk, std::chrono::milliseconds(timeout_ms), [&] {
          return released_.count(gen) > 0 || failed_rank_.load() >= 0 ||
                 fd_ < 0;
        });
    // A released barrier is a success even if a failure arrived right
    // after: all ranks did reach this generation.
    if (released_.count(gen)) return 0;
    int fr = failed_rank_.load();
    if (fr >= 0) return -2 - fr;
    if (!done) return -1;
    return -1;
  }

 private:
  void Close() {
    int fd;
    {
      // Hold mu_ across the state change + notify so a Barrier() waiter
      // can't evaluate its predicate between them and miss the wakeup.
      std::lock_guard<std::mutex> lk(mu_);
      fd = fd_;
      fd_ = -1;
      cv_.notify_all();
    }
    if (fd >= 0) {
      ::shutdown(fd, SHUT_RDWR);
      ::close(fd);
    }
  }

  void ReaderLoop() {
    Msg m{};
    while (!stop_.load() && fd_ >= 0) {
      if (!RecvAll(fd_, &m, sizeof(m)) || m.magic != kMagic) {
        std::lock_guard<std::mutex> lk(mu_);
        if (fd_ >= 0 && !stop_.load()) {
          // Coordinator vanished: treat as gang failure, rank unknown.
          int expected = -1;
          failed_rank_.compare_exchange_strong(expected, INT32_MAX);
        }
        cv_.notify_all();
        return;
      }
      std::lock_guard<std::mutex> lk(mu_);
      if (m.type == kAck) {
        registered_ = true;
      } else if (m.type == kBarrierRel) {
        released_.insert(m.arg);
      } else if (m.type == kFail) {
        int expected = -1;
        failed_rank_.compare_exchange_strong(expected, m.rank);
      }
      cv_.notify_all();
    }
  }

  void HeartbeatLoop() {
    while (!stop_.load() && fd_ >= 0) {
      if (!SendMsg(fd_, kHeartbeat, rank_, 0)) return;
      std::this_thread::sleep_for(
          std::chrono::milliseconds(heartbeat_interval_ms_));
    }
  }

  int rank_;
  int heartbeat_interval_ms_;
  std::string token_;
  std::atomic<int> fd_{-1};
  std::atomic<int> failed_rank_;
  bool registered_;
  std::atomic<bool> stop_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::set<int> released_;
  std::thread reader_thread_;
  std::thread heartbeat_thread_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* stpu_coord_create(int port, int num_hosts,
                        int heartbeat_timeout_ms, const char* token) {
  auto* c = new Coordinator(port, num_hosts, heartbeat_timeout_ms,
                            token);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

int stpu_coord_port(void* h) {
  return static_cast<Coordinator*>(h)->port();
}

int stpu_coord_wait_ready(void* h, int timeout_ms) {
  return static_cast<Coordinator*>(h)->WaitReady(timeout_ms);
}

int stpu_coord_registered_count(void* h) {
  return static_cast<Coordinator*>(h)->registered_count();
}

int stpu_coord_failed_rank(void* h) {
  return static_cast<Coordinator*>(h)->failed_rank();
}

void stpu_coord_destroy(void* h) { delete static_cast<Coordinator*>(h); }

void* stpu_client_connect(const char* host, int port, int rank,
                          int timeout_ms, int heartbeat_interval_ms,
                          const char* token) {
  auto* c = new Client(host, port, rank, timeout_ms,
                       heartbeat_interval_ms, token);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

int stpu_client_barrier(void* h, int gen, int timeout_ms) {
  return static_cast<Client*>(h)->Barrier(gen, timeout_ms);
}

int stpu_client_failed_rank(void* h) {
  return static_cast<Client*>(h)->failed_rank();
}

void stpu_client_abort(void* h) { static_cast<Client*>(h)->Abort(); }

void stpu_client_destroy(void* h) { delete static_cast<Client*>(h); }

}  // extern "C"
