"""Client for agent/exec_server.py — the `ssh` drop-in the gang driver
uses for Kubernetes worker pods.

Reads the script from STDIN (env exports + command, same privacy
contract as the ssh transport: nothing secret in argv), streams the
remote output to stdout, exits with the remote return code. Killing
this process closes the socket, which makes the server kill the remote
command's process group — ssh-session semantics.
"""
from __future__ import annotations

import argparse
import socket
import struct
import sys

from skypilot_tpu.agent.constants import pad_token
from skypilot_tpu.agent.exec_server import RC_TRAILER, read_token


def run(host: str, port: int, script: bytes, token: str,
        out=None) -> int:
    out = out or sys.stdout.buffer
    with socket.create_connection((host, port), timeout=30) as sock:
        sock.sendall(pad_token(token).encode())
        sock.sendall(struct.pack(">I", len(script)) + script)
        sock.settimeout(None)
        buf = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
            # Stream everything before a potential trailer; keep a tail
            # large enough that a split trailer is never flushed early.
            keep = len(RC_TRAILER) + 16
            if len(buf) > keep:
                out.write(buf[:-keep])
                out.flush()
                buf = buf[-keep:]
    idx = buf.rfind(RC_TRAILER)
    if idx < 0:
        out.write(buf)
        out.flush()
        return 255  # server died before reporting a return code
    out.write(buf[:idx])
    out.flush()
    try:
        return int(buf[idx + len(RC_TRAILER):].split()[0])
    except (ValueError, IndexError):
        return 255


def main() -> None:
    import os
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", required=True)
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--token-file", default=None)
    args = parser.parse_args()
    # Token sources, most-specific first: explicit file, process env
    # (the gang driver passes it this way — local env, never argv),
    # the head's own ~/.stpu_agent/exec_token.
    if args.token_file:
        with open(args.token_file) as f:
            token = f.read().strip()
    elif os.environ.get("STPU_EXEC_TOKEN"):
        token = os.environ["STPU_EXEC_TOKEN"]
    else:
        token = read_token()
    script = sys.stdin.buffer.read()
    sys.exit(run(args.host, args.port, script, token))


if __name__ == "__main__":
    main()
