"""Gang executor: run one job command on every host of a cluster, atomically.

The driver-program analog of the reference's generated Ray driver
(RayCodeGen, sky/backends/cloud_vm_ray_backend.py:211,361-505,525-637):
where the reference builds a STRICT_SPREAD placement group and
`run_bash_command_with_log.remote()` per node, the TPU gang is the slice
itself — this process just fans the command out to every host with the
rank/env contract and enforces slice-atomic failure:

  * all hosts start together (the provisioner guaranteed co-boot);
  * the first host to fail cancels all others; their exit is recorded as
    rc 137 (reference get_or_fail semantics :296-331);
  * SIGTERM from `job cancel` tears down every host's process.

Runs detached on the head host (local provider: on the client machine,
which *is* every host). Invoked as:
    python3 -m skypilot_tpu.agent.gang_exec /path/to/spec.json
"""
from __future__ import annotations

import json
import os
import pathlib
import shlex
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional

from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing

GANG_FAILED_RC = constants.GANG_FAILED_RC

_GANG_RUNS = metrics.counter(
    "stpu_gang_runs_total", "Gang executions by outcome.", ("outcome",))


def _build_env(spec: Dict, rank: int) -> Dict[str, str]:
    ips: List[str] = spec["node_ips"]
    host = spec["hosts"][rank]
    # The submitting client stamped its run ID into the spec
    # (slice_backend._build_job_spec); hand it to every host so job-side
    # telemetry correlates with the originating CLI invocation.
    run_id = spec.get("run_id") or events_lib.run_id()
    env = {
        events_lib.RUN_ID_ENV: run_id,
        constants.NODE_RANK: str(rank),
        constants.NODE_IPS: "\n".join(ips),
        constants.NUM_NODES: str(len(ips)),
        constants.TASK_ID: spec["task_id"],
        constants.CLUSTER_NAME: spec["cluster_name"],
        constants.NUM_CHIPS_PER_NODE: str(
            spec.get("chips_per_host", 0)),
        constants.COORDINATOR_ADDR:
            f"{ips[0]}:{constants.COORDINATOR_PORT}",
        constants.NUM_SLICES: str(spec.get("num_slices", 1)),
        constants.SLICE_INDEX: str(host.get("slice_index", 0)),
    }
    if spec.get("num_slices", 1) > 1:
        env[constants.MEGASCALE_COORDINATOR] = \
            f"{ips[0]}:{constants.COORDINATOR_PORT + 1}"
    if host.get("kind") == "local":
        # Simulated slice hosts have no /dev/accel*; the TPU health gate
        # (host_wrapper) only makes sense on real TPU VMs.
        env["STPU_SKIP_HEALTH_PROBE"] = "1"
    # Traced launch: hand every host the gang span's context (plus the
    # arming flag) so job-side spans nest under this driver's —
    # host-to-host propagation through env, like STPU_RUN_ID above.
    env.update(tracing.child_env())
    env.update(spec.get("envs", {}))
    return env


def _ssh_argv_and_script(host: Dict, cmd: str, env: Dict[str, str],
                         coord_port: Optional[int],
                         coord_token: str = ""):
    """Build the ssh argv and the stdin script for one worker.

    Separated (and env-free in argv) so tests can assert no secret ever
    reaches the process list; the script runs under `bash --login -s`.
    """
    from skypilot_tpu.utils import command_runner
    opts = list(command_runner.SSH_COMMON_OPTS)
    if host.get("proxy_command"):
        opts += ["-o", f"ProxyCommand={host['proxy_command']}"]
    if coord_port is not None:
        # The coordinator lives in this (driver) process; hosts reach it
        # through an SSH reverse tunnel so NAT between driver and slice
        # doesn't matter. The remote tunnel port reuses the coordinator's
        # (OS-assigned, driver-unique) port number so concurrent gangs
        # don't collide; a bind failure must kill the ssh (fail fast)
        # rather than silently cross-wire two gangs.
        env = dict(env)
        env[constants.GANG_COORD_ADDR] = f"127.0.0.1:{coord_port}"
        if coord_token:
            # Mixed gang with agent workers: the coordinator is in
            # token mode, so ssh ranks must present the token too.
            env[constants.GANG_COORD_TOKEN] = coord_token
        opts += ["-o", "ExitOnForwardFailure=yes",
                 "-R", f"{coord_port}:127.0.0.1:{coord_port}"]
        cmd = (f"python3 -m skypilot_tpu.agent.host_wrapper "
               f"{shlex.quote(cmd)}")
    exports = "\n".join(
        f"export {k}={shlex.quote(str(v))}" for k, v in env.items())
    script = f"{exports}\n{cmd}\n"
    argv = (["ssh"] + opts +
            ["-i", os.path.expanduser(host["ssh_key_path"]),
             "-p", str(host.get("ssh_port", 22)),
             f"{host['ssh_user']}@{host['ip']}",
             "bash --login -s"])
    return argv, script


class _HostProc:
    """One host's command, run via the appropriate transport."""

    def __init__(self, host: Dict, rank: int, cmd: str,
                 env: Dict[str, str], log_path: str,
                 coord_port: Optional[int] = None,
                 coord_token: str = "", head_ip: str = ""):
        self.rank = rank
        self.host = host
        self.returncode: Optional[int] = None
        log_f = open(log_path, "ab")
        if host["kind"] == "exec":
            # The driver runs ON this host (head-resident submission):
            # its own rank is a plain subprocess, no SSH-to-self.
            if coord_port is not None:
                env = dict(env)
                env[constants.GANG_COORD_ADDR] = f"127.0.0.1:{coord_port}"
                if coord_token:
                    # Mixed gang (agent workers): the coordinator runs
                    # token-authenticated, so EVERY rank must present
                    # the token — including the head's own.
                    env[constants.GANG_COORD_TOKEN] = coord_token
                cmd = (f"{sys.executable} -m "
                       f"skypilot_tpu.agent.host_wrapper "
                       f"{shlex.quote(cmd)}")
            full_env = dict(os.environ)
            full_env.update(env)
            self.proc = subprocess.Popen(
                ["bash", "--login", "-c", cmd], stdout=log_f,
                stderr=subprocess.STDOUT, env=full_env,
                cwd=os.path.expanduser("~"), start_new_session=True)
        elif host["kind"] == "local":
            if coord_port is not None:
                env = dict(env)
                env[constants.GANG_COORD_ADDR] = \
                    f"127.0.0.1:{coord_port}"
                if coord_token:
                    env[constants.GANG_COORD_TOKEN] = coord_token
                # The wrapper runs with cwd=host_dir; make the package
                # importable from wherever this driver imported it.
                import skypilot_tpu
                pkg_root = os.path.dirname(
                    os.path.dirname(skypilot_tpu.__file__))
                existing = env.get("PYTHONPATH") or \
                    os.environ.get("PYTHONPATH", "")
                env["PYTHONPATH"] = (
                    f"{pkg_root}:{existing}" if existing else pkg_root)
                cmd = (f"{sys.executable} -m "
                       f"skypilot_tpu.agent.host_wrapper "
                       f"{shlex.quote(cmd)}")
            full_env = dict(os.environ)
            full_env["HOME"] = host["host_dir"]
            full_env.update(env)
            self.proc = subprocess.Popen(
                ["bash", "-c", cmd], stdout=log_f,
                stderr=subprocess.STDOUT, env=full_env,
                cwd=host["host_dir"], start_new_session=True)
        elif host["kind"] == "agent":
            # sshd-free worker transport (kubernetes pods): the exec
            # agent on the worker runs the script; this local client
            # process streams its output and mirrors its rc, so the
            # ssh-shaped wait/terminate machinery applies unchanged
            # (killing the client drops the socket, which makes the
            # server kill the remote process group). The worker reaches
            # the gang coordinator DIRECTLY over the pod network, token
            # authenticated — no reverse tunnel.
            if coord_port is not None:
                env = dict(env)
                env[constants.GANG_COORD_ADDR] = \
                    f"{head_ip}:{coord_port}"
                env[constants.GANG_COORD_TOKEN] = coord_token
                cmd = (f"python3 -m skypilot_tpu.agent.host_wrapper "
                       f"{shlex.quote(cmd)}")
            exports = "\n".join(
                f"export {k}={shlex.quote(str(v))}"
                for k, v in env.items())
            script = f"{exports}\n{cmd}\n"
            argv = [sys.executable, "-m",
                    "skypilot_tpu.agent.exec_client",
                    "--host", host["ip"],
                    "--port", str(host.get("port",
                                           constants.EXEC_PORT))]
            client_env = dict(os.environ)
            if coord_token:
                # Exec-server auth token for the client, via its LOCAL
                # process env (never argv).
                client_env["STPU_EXEC_TOKEN"] = coord_token
            self.proc = subprocess.Popen(
                argv, stdin=subprocess.PIPE, stdout=log_f,
                stderr=subprocess.STDOUT, start_new_session=True,
                env=client_env)
            assert self.proc.stdin is not None
            self.proc.stdin.write(script.encode())
            self.proc.stdin.close()
        else:  # ssh
            argv, script = _ssh_argv_and_script(host, cmd, env,
                                                coord_port, coord_token)
            # The env exports (including user secrets from `envs:`) and
            # the command travel on STDIN, never in argv: ssh argv is
            # visible to every user on a shared host via `ps`.
            self.proc = subprocess.Popen(
                argv, stdin=subprocess.PIPE, stdout=log_f,
                stderr=subprocess.STDOUT, start_new_session=True)
            assert self.proc.stdin is not None
            self.proc.stdin.write(script.encode())
            self.proc.stdin.close()
        self._log_f = log_f

    def wait(self) -> int:
        self.returncode = self.proc.wait()
        self._log_f.close()
        return self.returncode

    def terminate(self) -> None:
        try:
            os.killpg(self.proc.pid, signal.SIGTERM)
        except (ProcessLookupError, OSError):
            try:
                self.proc.terminate()
            except OSError:
                pass


def run_gang(spec: Dict) -> int:
    """Execute the job across all hosts; returns the job's exit code."""
    job_id = spec["job_id"]
    home = spec.get("agent_home")  # head-host home (None = real $HOME)
    log_dir = pathlib.Path(spec["log_dir"])
    log_dir.mkdir(parents=True, exist_ok=True)

    # Adopt the submitting client's run ID so this driver's own events
    # (and its children's, via env inheritance) correlate end to end.
    if spec.get("run_id"):
        os.environ[events_lib.RUN_ID_ENV] = str(spec["run_id"])
    # Adopt the submitting client's trace context (stamped into the
    # spec by slice_backend when the client traced the launch): arms
    # tracing here and parents this driver's span on the client's.
    tracing.adopt_ctx(spec.get("trace_ctx"))
    job_lib.set_pid(job_id, os.getpid(), home)
    job_lib.set_status(job_id, job_lib.JobStatus.RUNNING, home)
    task_id = spec.get("task_id", str(job_id))
    events_lib.emit("gang", task_id, "start", job_id=job_id,
                    num_hosts=len(spec["hosts"]),
                    cluster=spec.get("cluster_name"))
    span = tracing.start_span(
        "gang.run", kind="gang", parent=tracing.from_env(),
        attrs={"job_id": job_id, "hosts": len(spec["hosts"]),
               "cluster": spec.get("cluster_name")})
    # Hosts nest under THIS span (not the client's): _build_env reads
    # the env context when stamping each host's environment.
    tracing.set_env_context(span.context())

    def abort(detail: str) -> None:
        """A raise-path exit still gets a terminal event + counter —
        a gang that 'started and never ended' in the log would hide
        exactly the failures this telemetry exists to count."""
        job_lib.set_status(job_id, job_lib.JobStatus.FAILED, home)
        _GANG_RUNS.labels(outcome="error").inc()
        events_lib.emit("gang", task_id, "error", job_id=job_id,
                        detail=detail)
        span.end(status="error", error=detail)
        metrics.dump_to_file(log_dir / "metrics.prom")

    # Gang coordinator (native host-agent core): every host's wrapper
    # barriers here before exec — no host runs until all are up
    # (reference pg.ready()) — and heartbeats during the run so a hung
    # host is detected, not just an exited one.
    coord = None
    coord_port = None
    coord_token = ""
    # Agent-transport hosts (kubernetes pods) authenticate both the
    # exec server AND the gang coordinator with the cluster token the
    # provisioner shipped; the coordinator then network-binds so pods
    # connect DIRECTLY (no ssh reverse tunnel exists for them).
    if any(h.get("kind") == "agent" for h in spec["hosts"]):
        from skypilot_tpu.agent import exec_server
        try:
            coord_token = exec_server.read_token(home)
        except OSError:
            # Missing token file == empty token: without the fail-fast
            # below the job would sit RUNNING behind a 600s barrier
            # hang (or a raw traceback) until the pid reconcile.
            coord_token = ""
        if not coord_token:
            # An empty token would silently bind the coordinator
            # loopback-only while agent workers dial the head IP — a
            # 600s barrier hang instead of an error. Fail fast.
            abort("missing exec token")
            raise RuntimeError(
                "agent-transport gang needs a non-empty exec token "
                "(~/.stpu_agent/exec_token on the head)")
    if spec.get("use_gang_agent", True) and len(spec["hosts"]) > 1:
        from skypilot_tpu.agent import native
        try:
            coord = native.Coordinator(
                len(spec["hosts"]),
                heartbeat_timeout_ms=constants.HEARTBEAT_TIMEOUT_MS,
                token=coord_token)
            coord_port = coord.port
        except OSError:
            coord = None

    procs: List[_HostProc] = []
    cancelled = threading.Event()

    def handle_term(signum, frame):
        del signum, frame
        cancelled.set()
        for p in procs:
            p.terminate()
    signal.signal(signal.SIGTERM, handle_term)

    try:
        for rank, host in enumerate(spec["hosts"]):
            env = _build_env(spec, rank)
            procs.append(_HostProc(host, rank, spec["run_cmd"], env,
                                   str(log_dir / f"node-{rank}.log"),
                                   coord_port=coord_port,
                                   coord_token=coord_token,
                                   head_ip=spec["node_ips"][0]))
    except Exception as e:  # noqa: BLE001 — spawn failure (bad ssh key,
        # unreachable exec agent): kill whatever ranks already started
        # and record the terminal outcome before propagating.
        for p in procs:
            p.terminate()
        abort(f"host spawn failed: {e!r}")
        raise

    # Wait with gang semantics: first failure cancels the rest.
    failed_rank: Optional[int] = None
    lock = threading.Lock()
    all_done = threading.Event()

    def waiter(p: _HostProc):
        nonlocal failed_rank
        rc = p.wait()
        with lock:
            if rc != 0 and failed_rank is None and not cancelled.is_set():
                failed_rank = p.rank
                for other in procs:
                    if other is not p and other.returncode is None:
                        other.terminate()

    def agent_monitor():
        """Heartbeat-based failure detection: catches hosts that hang or
        lose connectivity without their ssh process exiting."""
        nonlocal failed_rank
        while not all_done.wait(0.5):
            if coord is None:
                return
            dead = coord.failed_rank
            if dead >= 0 and not cancelled.is_set():
                with lock:
                    if failed_rank is None:
                        failed_rank = dead if dead < len(procs) else 0
                        for p in procs:
                            if p.returncode is None:
                                p.terminate()
                return

    threads = [threading.Thread(target=waiter, args=(p,), daemon=True)
               for p in procs]
    if coord is not None:
        threads.append(threading.Thread(target=agent_monitor,
                                        daemon=True))
    for t in threads:
        t.start()
    for t in threads[:len(procs)]:
        t.join()
    all_done.set()
    # Join the monitor BEFORE closing the coordinator: it reads
    # coord.failed_rank and must never race the native destroy.
    for t in threads[len(procs):]:
        t.join()
    if coord is not None:
        coord.close()

    def finish(outcome: str, rc: int, **fields) -> int:
        _GANG_RUNS.labels(outcome=outcome).inc()
        events_lib.emit("gang", task_id, outcome, job_id=job_id,
                        **fields)
        # Status stays in the ok/error vocabulary list_traces ranks
        # by; the gang outcome rides as an attribute.
        span.end(status="ok" if outcome == "succeeded" else "error",
                 outcome=outcome, rc=rc, **fields)
        # The driver exits right after this: the .prom dump in the
        # job's log dir is its exposition path (same textfile pattern
        # as the daemon; sync_down/logs pick it up with node logs).
        metrics.dump_to_file(log_dir / "metrics.prom")
        return rc

    if cancelled.is_set():
        job_lib.set_status(job_id, job_lib.JobStatus.CANCELLED, home)
        return finish("cancelled", 1)
    if failed_rank is not None:
        # Annotate forced-cancel ranks with the gang rc in their logs.
        for p in procs:
            if p.rank != failed_rank and p.returncode not in (0, None):
                with open(log_dir / f"node-{p.rank}.log", "ab") as f:
                    f.write(
                        f"\n[gang] cancelled because node {failed_rank} "
                        f"failed (rc={GANG_FAILED_RC})\n".encode())
        job_lib.set_status(job_id, job_lib.JobStatus.FAILED, home)
        return finish("failed", GANG_FAILED_RC,
                      failed_rank=failed_rank)
    job_lib.set_status(job_id, job_lib.JobStatus.SUCCEEDED, home)
    return finish("succeeded", 0)


def main() -> None:
    argv = [a for a in sys.argv[1:] if a != "--delete-spec"]
    delete_spec = "--delete-spec" in sys.argv[1:]
    spec_path = argv[0]
    with open(spec_path) as f:
        spec = json.load(f)
    rc = run_gang(spec)
    if delete_spec:
        # One-shot submission-staged spec (job_cli.submit passes the
        # flag): deleted only AFTER the gang ran, so a driver that dies
        # mid-job leaves the spec on disk for debugging/resubmission.
        try:
            os.unlink(spec_path)
        except OSError:
            pass
    sys.exit(rc)  # preserves GANG_FAILED_RC=137 for wrappers


if __name__ == "__main__":
    main()
