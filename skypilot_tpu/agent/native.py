"""Gang coordination core: ctypes bindings over the C++ host agent, with a
protocol-compatible pure-Python fallback.

The native library (agent/native/hostagent.cc) implements membership,
rank barrier, heartbeats, and failure broadcast — the coordination slice
the reference delegates to Ray (placement-group ready + node liveness,
sky/backends/cloud_vm_ray_backend.py:361-505). It is compiled on first use
with g++ (cached under ~/.stpu/native/); hosts without a toolchain — or
with STPU_FORCE_PY_AGENT=1 — use the Python twin, which speaks the same
wire protocol, so mixed gangs work.

API (both implementations):
    coord = Coordinator(num_hosts, port=0, heartbeat_timeout_ms=10_000)
    coord.port; coord.wait_ready(timeout_ms); coord.failed_rank
    client = Client(host, port, rank, timeout_ms=...)
    client.barrier(generation, timeout_ms) -> 0 | -1 (timeout) | -2-r
    client.failed_rank; client.close()
"""
from __future__ import annotations

import ctypes
import os
import pathlib
import socket
import struct
import subprocess
import threading
import time
from typing import Dict, Optional

_MAGIC = 0x53545055
(_REGISTER, _ACK, _BARRIER_REQ, _BARRIER_REL, _HEARTBEAT, _FAIL,
 _GOODBYE) = 1, 2, 3, 4, 5, 6, 7
_MSG = struct.Struct("<IIii")

_SRC = pathlib.Path(__file__).parent / "native" / "hostagent.cc"


# --------------------------------------------------------------------------
# Native library build + load
# --------------------------------------------------------------------------
_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_lib() -> Optional[pathlib.Path]:
    from skypilot_tpu.utils import paths
    out_dir = paths.home() / "native"
    out_dir.mkdir(parents=True, exist_ok=True)
    src_mtime = int(_SRC.stat().st_mtime)
    so_path = out_dir / f"libstpu_agent_{src_mtime}.so"
    if so_path.exists():
        return so_path
    # pid-unique temp: concurrent first-use builds must not interleave
    # g++ output or clobber each other's os.replace.
    tmp_path = f"{so_path}.{os.getpid()}.tmp"
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
         "-o", tmp_path, str(_SRC), "-lpthread"],
        capture_output=True, text=True)
    if proc.returncode != 0:
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        return None
    os.replace(tmp_path, so_path)
    return so_path


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        if os.environ.get("STPU_FORCE_PY_AGENT"):
            return None
        so_path = None
        try:
            so_path = _build_lib()
            if so_path is None:
                return None
            lib = ctypes.CDLL(str(so_path))
        except (OSError, subprocess.SubprocessError):
            # Corrupt/unloadable artifact: fall back to the Python twin
            # rather than surfacing a spurious gang failure — and remove
            # the bad cache entry so the next run rebuilds it.
            if so_path is not None:
                try:
                    os.unlink(so_path)
                except OSError:
                    pass
            return None
        lib.stpu_coord_create.restype = ctypes.c_void_p
        lib.stpu_coord_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_char_p]
        lib.stpu_coord_port.argtypes = [ctypes.c_void_p]
        lib.stpu_coord_wait_ready.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int]
        lib.stpu_coord_registered_count.argtypes = [ctypes.c_void_p]
        lib.stpu_coord_failed_rank.argtypes = [ctypes.c_void_p]
        lib.stpu_coord_destroy.argtypes = [ctypes.c_void_p]
        lib.stpu_client_connect.restype = ctypes.c_void_p
        lib.stpu_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_char_p]
        lib.stpu_client_barrier.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.c_int]
        lib.stpu_client_failed_rank.argtypes = [ctypes.c_void_p]
        lib.stpu_client_abort.argtypes = [ctypes.c_void_p]
        lib.stpu_client_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_lib() is not None


# --------------------------------------------------------------------------
# Native wrappers
# --------------------------------------------------------------------------
class _NativeCoordinator:
    def __init__(self, num_hosts: int, port: int = 0,
                 heartbeat_timeout_ms: int = 10_000, token: str = ""):
        self._lib = _load_lib()
        self._h = self._lib.stpu_coord_create(
            port, num_hosts, heartbeat_timeout_ms,
            _pad_token(token).encode())
        if not self._h:
            raise OSError("host-agent coordinator failed to bind")
        self.port = self._lib.stpu_coord_port(self._h)

    def wait_ready(self, timeout_ms: int) -> int:
        return self._lib.stpu_coord_wait_ready(self._h, timeout_ms)

    @property
    def registered_count(self) -> int:
        return self._lib.stpu_coord_registered_count(self._h)

    @property
    def failed_rank(self) -> int:
        return self._lib.stpu_coord_failed_rank(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.stpu_coord_destroy(self._h)
            self._h = None


class _NativeClient:
    def __init__(self, host: str, port: int, rank: int,
                 timeout_ms: int = 30_000,
                 heartbeat_interval_ms: int = 1_000, token: str = ""):
        self._lib = _load_lib()
        host_ip = socket.gethostbyname(host)
        self._h = self._lib.stpu_client_connect(
            host_ip.encode(), port, rank, timeout_ms,
            heartbeat_interval_ms, _pad_token(token).encode())
        if not self._h:
            raise OSError(
                f"host-agent client rank {rank} failed to reach "
                f"{host}:{port}")

    def barrier(self, gen: int, timeout_ms: int) -> int:
        return self._lib.stpu_client_barrier(self._h, gen, timeout_ms)

    @property
    def failed_rank(self) -> int:
        return self._lib.stpu_client_failed_rank(self._h)

    def abort(self) -> None:
        """Dirty close (no goodbye): simulates host death."""
        if self._h:
            self._lib.stpu_client_abort(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.stpu_client_destroy(self._h)
            self._h = None


# --------------------------------------------------------------------------
# Pure-Python protocol twin
# --------------------------------------------------------------------------
def _recv_msg(sock: socket.socket):
    buf = b""
    while len(buf) < _MSG.size:
        chunk = sock.recv(_MSG.size - len(buf))
        if not chunk:
            return None
        buf += chunk
    magic, mtype, rank, arg = _MSG.unpack(buf)
    if magic != _MAGIC:
        return None
    return mtype, rank, arg


def _send_msg(sock: socket.socket, mtype: int, rank: int,
              arg: int) -> bool:
    try:
        sock.sendall(_MSG.pack(_MAGIC, mtype, rank, arg))
        return True
    except OSError:
        return False


# Pre-register auth token (hostagent.cc kTokenLen) used by the
# direct-connect (network-bound) coordinator mode.
from skypilot_tpu.agent.constants import TOKEN_LEN  # noqa: E402
from skypilot_tpu.agent.constants import pad_token as _pad_token  # noqa: E402


def _recv_token_ok(sock: socket.socket, want: str) -> bool:
    try:
        buf = b""
        while len(buf) < TOKEN_LEN:
            chunk = sock.recv(TOKEN_LEN - len(buf))
            if not chunk:
                return False
            buf += chunk
    except OSError:
        return False
    import hmac
    return hmac.compare_digest(buf, want.encode())


class _PyCoordinator:
    def __init__(self, num_hosts: int, port: int = 0,
                 heartbeat_timeout_ms: int = 10_000, token: str = ""):
        self.num_hosts = num_hosts
        self.heartbeat_timeout_ms = heartbeat_timeout_ms
        self._token = _pad_token(token)
        self._failed_rank = -1
        self._stop = False
        self._cond = threading.Condition()
        self._conns: Dict[int, socket.socket] = {}
        self._last_hb: Dict[int, float] = {}
        self._barrier_waiters: Dict[int, set] = {}
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Loopback only WITHOUT a token (matches hostagent.cc): the
        # unauthenticated protocol must not be network-reachable; remote
        # hosts come in via SSH reverse tunnel. WITH a token the
        # coordinator binds the network and each connection must present
        # the 32-char token before REGISTER (direct-connect transports —
        # kubernetes pods — need no tunnel).
        self._listen.bind(("" if self._token else "127.0.0.1", port))
        self._listen.listen(num_hosts + 8)
        self.port = self._listen.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        threading.Thread(target=self._monitor_loop, daemon=True).start()

    # -- public ---------------------------------------------------------
    def wait_ready(self, timeout_ms: int) -> int:
        deadline = time.time() + timeout_ms / 1000.0
        with self._cond:
            while True:
                if self._failed_rank >= 0:
                    return -2 - self._failed_rank
                if len(self._conns) == self.num_hosts:
                    return 0
                remaining = deadline - time.time()  # noqa: stpu-wallclock deadlines are exchanged with code stamping wall clock
                if remaining <= 0:
                    return -1
                self._cond.wait(remaining)

    @property
    def registered_count(self) -> int:
        with self._cond:
            return len(self._conns)

    @property
    def failed_rank(self) -> int:
        return self._failed_rank

    def close(self) -> None:
        self._stop = True
        try:
            self._listen.close()
        except OSError:
            pass
        with self._cond:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    # -- internals ------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._reader_loop, args=(conn,),
                             daemon=True).start()

    def _reader_loop(self, conn: socket.socket) -> None:
        conn.settimeout(10.0)  # bound the registration read
        if self._token and not _recv_token_ok(conn, self._token):
            conn.close()
            return
        try:
            msg = _recv_msg(conn)
        except OSError:
            msg = None
        if msg is None or msg[0] != _REGISTER:
            conn.close()
            return
        conn.settimeout(None)  # liveness is heartbeat-based from here on
        rank = msg[1]
        with self._cond:
            if rank < 0 or rank >= self.num_hosts or rank in self._conns:
                conn.close()
                return
            self._conns[rank] = conn
            self._last_hb[rank] = time.time()
            self._cond.notify_all()
        _send_msg(conn, _ACK, rank, 0)
        while not self._stop:
            try:
                msg = _recv_msg(conn)
            except OSError:
                msg = None
            if msg is None:
                if not self._stop:
                    self._declare_failed(rank)
                return
            mtype, _, arg = msg
            if mtype == _HEARTBEAT:
                with self._cond:
                    self._last_hb[rank] = time.time()
            elif mtype == _BARRIER_REQ:
                self._on_barrier_req(rank, arg)
            elif mtype == _GOODBYE:
                # Clean departure: EOF after this is not a failure.
                with self._cond:
                    self._conns.pop(rank, None)
                    self._last_hb.pop(rank, None)
                conn.close()
                return

    def _on_barrier_req(self, rank: int, gen: int) -> None:
        with self._cond:
            waiters = self._barrier_waiters.setdefault(gen, set())
            waiters.add(rank)
            if len(waiters) == self.num_hosts:
                for c in self._conns.values():
                    _send_msg(c, _BARRIER_REL, -1, gen)
                del self._barrier_waiters[gen]

    def _monitor_loop(self) -> None:
        while not self._stop:
            time.sleep(min(self.heartbeat_timeout_ms / 4000.0 + 0.001,
                           0.5))
            if self.heartbeat_timeout_ms <= 0:
                continue
            dead = -1
            now = time.time()
            with self._cond:
                for rank, last in self._last_hb.items():
                    if rank in self._conns and \
                            (now - last) * 1000 > \
                            self.heartbeat_timeout_ms:
                        dead = rank
                        break
            if dead >= 0:
                self._declare_failed(dead)

    def _declare_failed(self, rank: int) -> None:
        with self._cond:
            if self._failed_rank >= 0:
                return
            self._failed_rank = rank
            for r, c in self._conns.items():
                if r != rank:
                    _send_msg(c, _FAIL, rank, 0)
            self._cond.notify_all()


class _PyClient:
    def __init__(self, host: str, port: int, rank: int,
                 timeout_ms: int = 30_000,
                 heartbeat_interval_ms: int = 1_000, token: str = ""):
        self.rank = rank
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self._token = _pad_token(token)
        self._failed_rank = -1
        self._released = set()
        self._registered = False
        self._stop = False
        self._cond = threading.Condition()
        deadline = time.time() + timeout_ms / 1000.0
        last_err: Optional[Exception] = None
        self._sock = None
        while time.time() < deadline:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5)
                break
            except OSError as e:
                last_err = e
                time.sleep(0.1)
        if self._sock is None:
            raise OSError(f"client rank {rank}: cannot reach "
                          f"{host}:{port}: {last_err}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        if self._token:
            try:
                self._sock.sendall(self._token.encode())
            except OSError:
                raise OSError(f"client rank {rank}: token send failed")
        if not _send_msg(self._sock, _REGISTER, rank, 0):
            raise OSError(f"client rank {rank}: register failed")
        threading.Thread(target=self._reader_loop, daemon=True).start()
        with self._cond:
            remaining = deadline - time.time()  # noqa: stpu-wallclock deadlines are exchanged with code stamping wall clock
            self._cond.wait_for(lambda: self._registered,
                                max(remaining, 0.1))
            if not self._registered:
                self.close()
                raise OSError(f"client rank {rank}: no ack")
        threading.Thread(target=self._heartbeat_loop,
                         daemon=True).start()

    def barrier(self, gen: int, timeout_ms: int) -> int:
        if self._sock is None:
            return -1
        if not _send_msg(self._sock, _BARRIER_REQ, self.rank, gen):
            return -1
        deadline = time.time() + timeout_ms / 1000.0
        with self._cond:
            while True:
                # A released barrier wins over a failure that arrived just
                # after it: all ranks did reach this generation.
                if gen in self._released:
                    return 0
                if self._failed_rank >= 0:
                    return -2 - self._failed_rank
                remaining = deadline - time.time()  # noqa: stpu-wallclock deadlines are exchanged with code stamping wall clock
                if remaining <= 0 or self._sock is None:
                    return -1
                self._cond.wait(remaining)

    @property
    def failed_rank(self) -> int:
        return self._failed_rank

    def abort(self) -> None:
        """Dirty close (no goodbye): simulates host death."""
        self._stop = True
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        with self._cond:
            self._cond.notify_all()

    def close(self) -> None:
        self._stop = True
        sock, self._sock = self._sock, None
        if sock is not None:
            _send_msg(sock, _GOODBYE, self.rank, 0)
            try:
                sock.close()
            except OSError:
                pass
        with self._cond:
            self._cond.notify_all()

    def _reader_loop(self) -> None:
        while not self._stop and self._sock is not None:
            try:
                msg = _recv_msg(self._sock)
            except OSError:
                msg = None
            with self._cond:
                if msg is None:
                    if not self._stop and self._failed_rank < 0:
                        self._failed_rank = 2**31 - 1  # coord vanished
                    self._cond.notify_all()
                    return
                mtype, rank, arg = msg
                if mtype == _ACK:
                    self._registered = True
                elif mtype == _BARRIER_REL:
                    self._released.add(arg)
                elif mtype == _FAIL and self._failed_rank < 0:
                    self._failed_rank = rank
                self._cond.notify_all()

    def _heartbeat_loop(self) -> None:
        while not self._stop and self._sock is not None:
            if not _send_msg(self._sock, _HEARTBEAT, self.rank, 0):
                return
            time.sleep(self.heartbeat_interval_ms / 1000.0)


# --------------------------------------------------------------------------
# Public factories: native if buildable, Python otherwise.
# --------------------------------------------------------------------------
def Coordinator(num_hosts: int, port: int = 0,
                heartbeat_timeout_ms: int = 10_000, token: str = ""):
    """``token`` non-empty switches to the authenticated direct-connect
    mode: network bind + mandatory 32-char token per connection (the
    sshd-free kubernetes transport); empty keeps loopback-only."""
    if native_available():
        return _NativeCoordinator(num_hosts, port, heartbeat_timeout_ms,
                                  token)
    return _PyCoordinator(num_hosts, port, heartbeat_timeout_ms, token)


def Client(host: str, port: int, rank: int, timeout_ms: int = 30_000,
           heartbeat_interval_ms: int = 1_000, token: str = ""):
    if native_available():
        return _NativeClient(host, port, rank, timeout_ms,
                             heartbeat_interval_ms, token)
    return _PyClient(host, port, rank, timeout_ms,
                     heartbeat_interval_ms, token)
