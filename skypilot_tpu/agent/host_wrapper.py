"""Per-host job wrapper: barrier with the gang, run the command, report.

The host-side half of the native gang agent (agent/native.py). The gang
driver (gang_exec) wraps every host's command with this module:

    python3 -m skypilot_tpu.agent.host_wrapper <shell command>

Behavior (reference analog — the per-node Ray task body plus the
placement-group ready wait, sky/backends/cloud_vm_ray_backend.py:296-331,
361-505):
  1. connect to the coordinator at $STPU_GANG_COORD_ADDR as
     $SKYPILOT_NODE_RANK (no coordinator configured → just run);
  2. barrier generation 0 — no host starts until every host is up;
  3. run the command under bash, heartbeating in the background;
  4. exit 137 if the gang failed (another rank died), else the command's
     exit code.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading

from skypilot_tpu.agent import constants
from skypilot_tpu.utils import fault_injection

GANG_FAILED_RC = constants.GANG_FAILED_RC


def main() -> int:
    if len(sys.argv) < 2:
        print("usage: host_wrapper <shell command>", file=sys.stderr)
        return 2
    cmd = sys.argv[1]
    coord_addr = os.environ.get(constants.GANG_COORD_ADDR)
    rank = int(os.environ.get(constants.NODE_RANK, "0"))

    # Topology/health gate (the nvidia-smi analog): a host with missing
    # TPU devices fails the gang deterministically BEFORE the barrier
    # instead of hanging the collective later. Probe result is recorded
    # for the daemon/debugging.
    expected_chips = int(
        os.environ.get(constants.NUM_CHIPS_PER_NODE, "0") or 0)
    if expected_chips > 0 and \
            os.environ.get("STPU_SKIP_HEALTH_PROBE") != "1":
        from skypilot_tpu.agent import tpu_health
        report = tpu_health.probe(expected_chips)
        try:
            tpu_health.write_report(report)
        except OSError:
            pass
        if not report["ok"]:
            print(f"[wrapper rank {rank}] TPU health check failed: "
                  f"{report['detail']}", file=sys.stderr, flush=True)
            if coord_addr:
                from skypilot_tpu.agent import native
                host, port = coord_addr.rsplit(":", 1)
                try:
                    bad = native.Client(
                        host, int(port), rank, timeout_ms=5000,
                        token=os.environ.get(
                            constants.GANG_COORD_TOKEN, ""))
                    bad.abort()
                    bad.close()
                except OSError:
                    pass
            return GANG_FAILED_RC

    client = None
    if coord_addr:
        from skypilot_tpu.agent import native
        host, port = coord_addr.rsplit(":", 1)
        try:
            client = native.Client(
                host, int(port), rank,
                timeout_ms=constants.GANG_BARRIER_TIMEOUT_SECONDS * 1000,
                token=os.environ.get(constants.GANG_COORD_TOKEN, ""))
        except OSError as e:
            print(f"[wrapper rank {rank}] coordinator unreachable: {e}",
                  file=sys.stderr, flush=True)
            return GANG_FAILED_RC
        rc = client.barrier(
            0, timeout_ms=constants.GANG_BARRIER_TIMEOUT_SECONDS * 1000)
        if rc != 0:
            print(f"[wrapper rank {rank}] gang barrier failed "
                  f"(rc={rc})", file=sys.stderr, flush=True)
            client.close()
            return GANG_FAILED_RC

    # Chaos seam: one host of the slice dying right as the gang starts
    # (or, in ``kill`` mode, with no exit handshake at all) — the gang
    # driver must cancel every peer with rc 137, exactly like a real
    # preempted host. Sits AFTER the barrier so all peers are already
    # committed to the run.
    if fault_injection.ENABLED:
        try:
            fault_injection.fire("gang.host", rank=rank)
        except fault_injection.InjectedFault as e:
            print(f"[wrapper rank {rank}] {e}", file=sys.stderr,
                  flush=True)
            if client is not None:
                client.close()
            return GANG_FAILED_RC

    proc = subprocess.Popen(["bash", "-c", cmd],
                            start_new_session=True)

    def forward(signum, frame):
        del frame
        try:
            os.killpg(proc.pid, signum)
        except (ProcessLookupError, OSError):
            pass
    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)

    # If the gang fails while we run, kill our command (rc 137): a host
    # whose peers died must not keep training on a broken collective.
    stop = threading.Event()

    def watch_gang():
        while not stop.wait(0.5):
            if client is not None and client.failed_rank >= 0:
                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except (ProcessLookupError, OSError):
                    pass
                return

    watcher = None
    if client is not None:
        watcher = threading.Thread(target=watch_gang, daemon=True)
        watcher.start()

    rc = proc.wait()
    stop.set()
    if watcher is not None:
        watcher.join(timeout=2)
    gang_failed = client is not None and client.failed_rank >= 0
    if client is not None:
        client.close()
    if gang_failed and rc != 0:
        return GANG_FAILED_RC
    return rc


if __name__ == "__main__":
    sys.exit(main())
