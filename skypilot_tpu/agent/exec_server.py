"""Token-authenticated remote-exec server: the sshd replacement for
Kubernetes worker pods.

The reference reaches worker pods over pod-IP SSH, which forces every
multi-host image to run sshd (their bootstrap installs openssh). Here
the head's gang driver connects to this server instead — any image with
python3 works. Protocol (one TCP connection per command):

    client -> 32-byte token
    client -> 4-byte big-endian script length + script bytes
    server -> combined stdout/stderr stream of `bash --login -s` running
              the script (env exports INSIDE the script, never argv)
    server -> b"\\n__STPU_RC__ <rc>\\n" trailer, then EOF

Connection drop kills the command's whole process group — exactly the
ssh-session semantics the gang driver's terminate path relies on. The
token is a per-cluster random secret (``secrets.token_hex``, generated
next to the internal keypair in ``provision/provisioner.py``) shipped to
``~/.stpu_agent/exec_token`` at bring-up. It is deliberately NOT derived
from any key material: public keys are readable by anyone on the host
(authorized_keys), so a derivable token would grant remote exec to any
local reader. Threat model: possession of the token == permission to run
commands as the agent user on that cluster's hosts, nothing more — it is
scoped per cluster and dies with it.
"""
from __future__ import annotations

import argparse
import hmac
import os
import pathlib
import signal
import socket
import socketserver
import struct
import subprocess
import threading

from skypilot_tpu.agent.constants import (EXEC_PORT as DEFAULT_PORT,
                                          TOKEN_LEN, pad_token)

RC_TRAILER = b"\n__STPU_RC__ "
MAX_SCRIPT = 16 * 1024 * 1024


def read_token(home: str | None = None) -> str:
    base = pathlib.Path(home or os.path.expanduser("~"))
    return (base / ".stpu_agent" / "exec_token").read_text().strip()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


class _Handler(socketserver.BaseRequestHandler):
    token: str = ""
    home: str | None = None

    def handle(self) -> None:
        sock = self.request
        sock.settimeout(15)
        try:
            got = _recv_exact(sock, TOKEN_LEN)
            if not hmac.compare_digest(got, self.token.encode()):
                return  # silent close on bad token
            (length,) = struct.unpack(">I", _recv_exact(sock, 4))
            if length > MAX_SCRIPT:
                return
            script = _recv_exact(sock, length)
        except (OSError, ConnectionError):
            return
        sock.settimeout(None)
        env = dict(os.environ)
        if self.home:
            env["HOME"] = self.home
        proc = subprocess.Popen(
            ["bash", "--login", "-s"], stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.expanduser(self.home or "~"), env=env,
            start_new_session=True)
        assert proc.stdin is not None and proc.stdout is not None

        def feed():
            try:
                proc.stdin.write(script)
                proc.stdin.close()
            except OSError:
                pass

        threading.Thread(target=feed, daemon=True).start()

        # Watch for the CLIENT dropping the connection: that is the
        # terminate signal (ssh-session semantics) — kill the process
        # group so the command and its children die with the caller.
        done = threading.Event()

        def watch_peer():
            try:
                sock.settimeout(None)
                while not done.is_set():
                    try:
                        data = sock.recv(1, socket.MSG_DONTWAIT)
                    except BlockingIOError:
                        done.wait(0.5)
                        continue
                    except OSError:
                        data = b""
                    if not data:
                        break
                    # Clients never send post-script bytes; ignore any.
            finally:
                if not done.is_set():
                    try:
                        os.killpg(proc.pid, signal.SIGTERM)
                    except (ProcessLookupError, OSError):
                        pass

        threading.Thread(target=watch_peer, daemon=True).start()
        try:
            # read1: forward bytes as soon as ANY are available —
            # read() would buffer a full 64KiB before the head's
            # node log sees a line (ssh streams incrementally; so
            # must this).
            for chunk in iter(lambda: proc.stdout.read1(65536), b""):
                sock.sendall(chunk)
            rc = proc.wait()
            sock.sendall(RC_TRAILER + str(rc).encode() + b"\n")
        except OSError:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass
        finally:
            done.set()


class ExecServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, port: int, token: str,
                 home: str | None = None):
        if not token or not token.strip():
            # A server with a predictable/empty token on 0.0.0.0 would
            # be unauthenticated remote exec on the pod network.
            raise ValueError(
                "exec server refuses to start without a token "
                "(empty ~/.stpu_agent/exec_token?)")
        handler = type("Handler", (_Handler,),
                       {"token": pad_token(token.strip()),
                        "home": home})
        super().__init__(("0.0.0.0", port), handler)
        self.port = self.server_address[1]


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument("--home", default=None)
    args = parser.parse_args()
    server = ExecServer(args.port, read_token(args.home), args.home)
    server.serve_forever()


if __name__ == "__main__":
    main()
