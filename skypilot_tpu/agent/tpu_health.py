"""TPU topology/health probe: the `nvidia-smi` analog for slice hosts.

Reference analog: SURVEY §2.5 row 2 — the reference shells out to
`nvidia-smi`/Ray resource reporting for GPU health; a TPU host instead
exposes its chips as ``/dev/accel*`` (PCI DevFS nodes created by the TPU
driver) and via libtpu. The probe is deliberately cheap and import-free:
it must run at every gang start (host_wrapper) and daemon boot without
initializing a JAX backend, because grabbing the TPU runtime would
conflict with the user workload that is about to own the chips.
"""
from __future__ import annotations

import glob
import json
import os
import pathlib
import time
from typing import Any, Dict, Optional

_ACCEL_GLOBS = ("/dev/accel*", "/dev/vfio/*")


def count_local_chips() -> int:
    """Number of TPU chips visible on this host (0 on non-TPU hosts)."""
    for pattern in _ACCEL_GLOBS:
        found = [p for p in glob.glob(pattern)
                 if os.path.basename(p) != "vfio"]
        if found:
            return len(found)
    return 0


def probe(expected_chips: int = 0) -> Dict[str, Any]:
    """Health verdict for this host.

    ``expected_chips`` comes from the catalog (chips_per_host of the
    launched slice); 0 means a CPU host (local provider, controllers) and
    always passes. A TPU host with missing devices fails the gang *before*
    the barrier, turning a would-be hang into a deterministic rc-137 with
    a named culprit."""
    chips = count_local_chips()
    ok = expected_chips == 0 or chips >= expected_chips
    return {
        "ok": ok,
        "chips_found": chips,
        "chips_expected": expected_chips,
        "checked_at": time.time(),
        "detail": ("healthy" if ok else
                   f"expected {expected_chips} TPU chips, found {chips} "
                   f"(driver missing or device held by another process)"),
    }


def write_report(report: Dict[str, Any],
                 home: Optional[str] = None) -> pathlib.Path:
    root = pathlib.Path(home or os.path.expanduser("~"))
    path = root / ".stpu_agent" / "health.json"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report, indent=2))
    return path


def export_gauges(report: Dict[str, Any]) -> None:
    """Mirror a probe verdict into the process metrics registry (the
    daemon dumps it to ``.stpu_agent/metrics.prom`` each tick — the
    node_exporter textfile-collector pattern). Kept out of ``probe()``
    so the gang-start fast path (host_wrapper) stays import-free."""
    from skypilot_tpu.observability import metrics
    metrics.gauge("stpu_agent_tpu_healthy",
                  "1 when this host sees every expected TPU chip."
                  ).set(1 if report["ok"] else 0)
    metrics.gauge("stpu_agent_tpu_chips_found",
                  "TPU chips visible on this host."
                  ).set(report["chips_found"])
    metrics.gauge("stpu_agent_tpu_chips_expected",
                  "TPU chips the launched slice shape expects per host."
                  ).set(report["chips_expected"])
