"""Per-cluster job queue: sqlite on the head host.

Reference analog: sky/skylet/job_lib.py (JobStatus:86, FIFOScheduler:199,
update_job_status:512, JobLibCodeGen:803). Differences: no codegen strings
— the same module runs on the head host and is invoked either in-process
(local provider) or as ``python3 -m skypilot_tpu.agent.job_cli`` over SSH
(the shipped wheel provides it), and gang execution is handled by
``gang_exec`` rather than Ray placement groups.
"""
from __future__ import annotations

import enum
import json
import os
import pathlib
import signal
import sqlite3
import time
from typing import Any, Dict, List, Optional


class JobStatus(enum.Enum):
    INIT = "INIT"
    PENDING = "PENDING"
    SETTING_UP = "SETTING_UP"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    FAILED_SETUP = "FAILED_SETUP"
    CANCELLED = "CANCELLED"

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                        JobStatus.FAILED_SETUP, JobStatus.CANCELLED)


def _db_path(home: Optional[str] = None) -> pathlib.Path:
    root = pathlib.Path(home or os.path.expanduser("~"))
    p = root / ".stpu_agent" / "jobs.db"
    p.parent.mkdir(parents=True, exist_ok=True)
    return p


def _conn(home: Optional[str] = None) -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(home), timeout=10)
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("""CREATE TABLE IF NOT EXISTS jobs (
        job_id INTEGER PRIMARY KEY AUTOINCREMENT,
        job_name TEXT,
        username TEXT,
        submitted_at REAL,
        status TEXT,
        run_timestamp TEXT,
        start_at REAL,
        end_at REAL,
        pid INTEGER,
        log_dir TEXT)""")
    conn.commit()
    return conn


def add_job(job_name: str, username: str, run_timestamp: str,
            log_dir: str, home: Optional[str] = None) -> int:
    with _conn(home) as conn:
        cur = conn.execute(
            "INSERT INTO jobs (job_name, username, submitted_at, status, "
            "run_timestamp, log_dir) VALUES (?, ?, ?, ?, ?, ?)",
            (job_name, username, time.time(), JobStatus.INIT.value,
             run_timestamp, log_dir))
        return int(cur.lastrowid)


def set_status(job_id: int, status: JobStatus,
               home: Optional[str] = None) -> None:
    now = time.time()
    with _conn(home) as conn:
        if status == JobStatus.RUNNING:
            conn.execute(
                "UPDATE jobs SET status=?, start_at=? WHERE job_id=?",
                (status.value, now, job_id))
        elif status.is_terminal():
            conn.execute(
                "UPDATE jobs SET status=?, end_at=? WHERE job_id=? "
                "AND end_at IS NULL",
                (status.value, now, job_id))
            conn.execute("UPDATE jobs SET status=? WHERE job_id=?",
                         (status.value, job_id))
        else:
            conn.execute("UPDATE jobs SET status=? WHERE job_id=?",
                         (status.value, job_id))


def set_pid(job_id: int, pid: int, home: Optional[str] = None) -> None:
    with _conn(home) as conn:
        conn.execute("UPDATE jobs SET pid=? WHERE job_id=?", (pid, job_id))


def set_log_dir(job_id: int, log_dir: str,
                home: Optional[str] = None) -> None:
    with _conn(home) as conn:
        conn.execute("UPDATE jobs SET log_dir=? WHERE job_id=?",
                     (log_dir, job_id))


def get_job(job_id: int, home: Optional[str] = None
            ) -> Optional[Dict[str, Any]]:
    with _conn(home) as conn:
        row = conn.execute(
            "SELECT job_id, job_name, username, submitted_at, status, "
            "run_timestamp, start_at, end_at, pid, log_dir FROM jobs "
            "WHERE job_id=?", (job_id,)).fetchone()
    return _row_to_dict(row) if row else None


def get_statuses(job_ids: List[int], home: Optional[str] = None
                 ) -> Dict[int, Optional[str]]:
    out: Dict[int, Optional[str]] = {}
    for jid in job_ids:
        job = get_job(jid, home)
        out[jid] = job["status"] if job else None
    return out


def queue(home: Optional[str] = None,
          all_jobs: bool = True) -> List[Dict[str, Any]]:
    with _conn(home) as conn:
        rows = conn.execute(
            "SELECT job_id, job_name, username, submitted_at, status, "
            "run_timestamp, start_at, end_at, pid, log_dir FROM jobs "
            "ORDER BY job_id DESC").fetchall()
    jobs = [_row_to_dict(r) for r in rows]
    if not all_jobs:
        jobs = [j for j in jobs
                if not JobStatus(j["status"]).is_terminal()]
    return jobs


def cancel_jobs(job_ids: Optional[List[int]] = None,
                home: Optional[str] = None) -> List[int]:
    """Cancel running/pending jobs (all non-terminal if job_ids None).
    Sends SIGTERM to the gang_exec process group; gang_exec fans the
    cancellation out to every host."""
    jobs = queue(home)
    cancelled = []
    for job in jobs:
        if job_ids is not None and job["job_id"] not in job_ids:
            continue
        status = JobStatus(job["status"])
        if status.is_terminal():
            continue
        pid = job.get("pid")
        if pid:
            try:
                os.killpg(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    os.kill(pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    pass
        set_status(job["job_id"], JobStatus.CANCELLED, home)
        cancelled.append(job["job_id"])
    return cancelled


def is_cluster_idle(home: Optional[str] = None) -> bool:
    """No non-terminal jobs (reference: job_lib.is_cluster_idle:641)."""
    return len(queue(home, all_jobs=False)) == 0


def last_activity_time(home: Optional[str] = None) -> float:
    """Latest of: job submission, job end. Used by autostop."""
    jobs = queue(home)
    latest = 0.0
    for job in jobs:
        for key in ("submitted_at", "end_at"):
            v = job.get(key)
            if v:
                latest = max(latest, float(v))
    return latest


def _row_to_dict(row) -> Dict[str, Any]:
    (job_id, job_name, username, submitted_at, status, run_timestamp,
     start_at, end_at, pid, log_dir) = row
    return {
        "job_id": job_id, "job_name": job_name, "username": username,
        "submitted_at": submitted_at, "status": status,
        "run_timestamp": run_timestamp, "start_at": start_at,
        "end_at": end_at, "pid": pid, "log_dir": log_dir,
    }


def dump_queue_json(home: Optional[str] = None) -> str:
    return json.dumps(queue(home))
