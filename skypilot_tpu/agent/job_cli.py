"""Head-side job RPC: ``python3 -m skypilot_tpu.agent.job_cli <cmd> ...``.

Reference analog: sky/skylet/job_lib.py JobLibCodeGen:803 — the reference
ships ``python3 -u -c <codegen>`` strings over SSH to mutate the head's
job DB and submit drivers. Here the shipped wheel provides a real CLI
instead of codegen strings; the client (SliceBackend) invokes it through
a CommandRunner, so the SAME seam serves real SSH heads and the hermetic
local provider's directory-hosts.

Everything head-resident: the job DB (``~/.stpu_agent/jobs.db``), the job
logs (``~/stpu_logs/job-<id>/``), and the detached gang driver
(``gang_exec``) all live on the head host — the client can exit the
moment ``submit`` returns and the job still runs, is queryable, and
counts toward the daemon's idleness clock (autostop).

RPC framing: results are printed as one line ``STPU_RPC:{json}`` so the
client can pick it out of login-shell noise (motd, profile chatter).
``tail`` is the exception: it streams raw log lines and encodes the job's
final status in its exit code.
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time
from typing import Any, List, Optional

from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib

RPC_PREFIX = "STPU_RPC:"


def _reply(payload: Any) -> None:
    print(f"{RPC_PREFIX}{json.dumps(payload)}", flush=True)


def parse_reply(stdout: str) -> Any:
    """Client-side: extract the last RPC payload from mixed stdout."""
    result = None
    for line in stdout.splitlines():
        if line.startswith(RPC_PREFIX):
            result = json.loads(line[len(RPC_PREFIX):])
    if result is None:
        raise ValueError(f"no {RPC_PREFIX} line in job_cli output:\n"
                         f"{stdout[-2000:]}")
    return result


def submit(spec_path: str) -> None:
    """Register the job and launch its gang driver, detached.

    The client ships a spec WITHOUT job_id/log_dir; those are assigned
    here, on the head, so the job exists in the head DB before the
    client hears back — a dead client can never orphan a running job.
    """
    path = pathlib.Path(spec_path).expanduser()
    spec = json.loads(path.read_text())
    job_id = job_lib.add_job(
        spec.get("job_name") or "stpu-job",
        spec.get("username") or os.environ.get("USER", "unknown"),
        spec.get("run_timestamp") or time.strftime("%Y-%m-%d-%H-%M-%S"),
        log_dir="")
    log_dir = (pathlib.Path(os.path.expanduser("~"))
               / constants.LOGS_DIR / f"job-{job_id}")
    job_lib.set_log_dir(job_id, str(log_dir))
    spec["job_id"] = job_id
    spec["log_dir"] = str(log_dir)
    spec["task_id"] = (f"{spec.get('cluster_name', 'cluster')}-{job_id}-"
                       f"{spec.get('run_timestamp', '')}")
    spec["agent_home"] = None  # gang_exec runs here: real $HOME
    path.write_text(json.dumps(spec, indent=2))
    subprocess.Popen(
        [sys.executable, "-m", "skypilot_tpu.agent.gang_exec",
         str(path), "--delete-spec"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)
    _reply({"job_id": job_id, "log_dir": str(log_dir)})


def tail(job_id: Optional[int], follow: bool, node_rank: int) -> int:
    """Stream a job's log to stdout; exit 0 iff the job SUCCEEDED."""
    if job_id is None:
        jobs = job_lib.queue()
        if not jobs:
            print("No jobs on cluster.")
            return 1
        job_id = jobs[0]["job_id"]
    job = job_lib.get_job(job_id)
    if job is None:
        print(f"Job {job_id} not found.")
        return 1
    log_path = (pathlib.Path(os.path.expanduser("~")) / constants.LOGS_DIR
                / f"job-{job_id}" / f"node-{node_rank}.log")
    deadline = time.time() + 30
    while not log_path.exists():
        if time.time() > deadline or not follow:
            print(f"(no logs yet at {log_path})")
            return 1
        time.sleep(0.2)
    with open(log_path, "r", errors="replace") as f:
        while True:
            line = f.readline()
            if line:
                print(line, end="", flush=True)
                continue
            job = job_lib.get_job(job_id)
            done = job is None or job_lib.JobStatus(
                job["status"]).is_terminal()
            if not follow or done:
                rest = f.read()
                if rest:
                    print(rest, end="", flush=True)
                break
            time.sleep(0.2)
    job = job_lib.get_job(job_id)
    if job and job["status"] == job_lib.JobStatus.SUCCEEDED.value:
        return 0
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="job_cli", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("submit")
    p.add_argument("spec_path")

    sub.add_parser("queue")

    p = sub.add_parser("cancel")
    p.add_argument("--jobs", default="",
                   help="comma-separated job ids; empty = all live jobs")

    p = sub.add_parser("status")
    p.add_argument("job_id", type=int)

    p = sub.add_parser("tail")
    p.add_argument("job_id", type=int, nargs="?", default=None)
    p.add_argument("--no-follow", action="store_true")
    p.add_argument("--node-rank", type=int, default=0)

    args = parser.parse_args(argv)
    if args.cmd == "submit":
        submit(args.spec_path)
    elif args.cmd == "queue":
        _reply(job_lib.queue())
    elif args.cmd == "cancel":
        ids = ([int(x) for x in args.jobs.split(",") if x]
               if args.jobs else None)
        _reply(job_lib.cancel_jobs(ids))
    elif args.cmd == "status":
        job = job_lib.get_job(args.job_id)
        _reply({"status": job["status"] if job else None})
    elif args.cmd == "tail":
        return tail(args.job_id, follow=not args.no_follow,
                    node_rank=args.node_rank)
    return 0


if __name__ == "__main__":
    sys.exit(main())
