"""In-training step-timing callbacks feeding `stpu bench`.

Reference analog: sky/callbacks/sky_callback (base.py:20 BaseCallback +
_AsyncSummaryWriter writing benchmark_summary.json; api.py init/
step_begin/step_iterator). A recipe calls::

    from skypilot_tpu import callbacks as sky_callback
    sky_callback.init(total_steps=...)      # no-op unless benchmarking
    for batch in sky_callback.step_iterator(batches):
        ...

When the benchmark harness launched the task it exports
``STPU_BENCHMARK_LOG_DIR``; the callbacks then append a summary JSON the
harness later collects to compute seconds/step and $/step. Outside a
benchmark the calls cost one env lookup and do nothing, so recipes keep
them unconditionally (reference behavior).
"""
from __future__ import annotations

import json
import os
import time
from typing import Iterable, Iterator, Optional

ENV_LOG_DIR = "STPU_BENCHMARK_LOG_DIR"
SUMMARY_NAME = "benchmark_summary.json"

_state: Optional["_Recorder"] = None


class _Recorder:
    def __init__(self, log_dir: str, total_steps: Optional[int],
                 write_every: int = 10):
        self.path = os.path.join(os.path.expanduser(log_dir),
                                 SUMMARY_NAME)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.total_steps = total_steps
        self.write_every = write_every
        self.t0 = time.time()
        self.num_steps = 0
        self.first_step_done: Optional[float] = None
        self.last_step_done: Optional[float] = None

    def step_begin(self) -> None:
        # Timing derives from step_end timestamps only (steady-state
        # rate); step_begin exists for reference-API parity.
        pass

    def step_end(self) -> None:
        now = time.time()
        self.num_steps += 1
        if self.first_step_done is None:
            self.first_step_done = now
        self.last_step_done = now
        if self.num_steps % self.write_every == 0:
            self.flush()

    def summary(self) -> dict:
        # Steady-state seconds/step excludes the first step (compile).
        steady = None
        if (self.num_steps > 1 and self.first_step_done is not None
                and self.last_step_done is not None):
            steady = ((self.last_step_done - self.first_step_done) /
                      (self.num_steps - 1))
        return {
            "num_steps": self.num_steps,
            "total_steps": self.total_steps,
            "started_at": self.t0,
            "first_step_done_at": self.first_step_done,
            "last_step_done_at": self.last_step_done,
            "seconds_per_step": steady,
        }

    def flush(self) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.summary(), f)
        os.replace(tmp, self.path)


def init(total_steps: Optional[int] = None,
         log_dir: Optional[str] = None) -> bool:
    """Arm the callbacks. Returns True when benchmarking is active."""
    global _state
    log_dir = log_dir or os.environ.get(ENV_LOG_DIR)
    if not log_dir:
        _state = None
        return False
    _state = _Recorder(log_dir, total_steps)
    _state.flush()
    return True


def step_begin() -> None:
    if _state is not None:
        _state.step_begin()


def step_end() -> None:
    if _state is not None:
        _state.step_end()


def step_iterator(iterable: Iterable) -> Iterator:
    """Wrap a batch iterator, timing each loop body as one step."""
    for item in iterable:
        step_begin()
        yield item
        step_end()


def flush() -> None:
    if _state is not None:
        _state.flush()


def device_profile(log_dir: Optional[str] = None,
                   env_var: str = "STPU_PROFILE_DIR"):
    """Context manager: capture an on-device XLA profile when armed.

    The TPU analog the reference lacks (SURVEY §5: no on-device
    profiler): ``with callbacks.device_profile():`` around the training
    loop writes a TensorBoard-loadable trace (xplane) via
    ``jax.profiler`` when ``STPU_PROFILE_DIR`` (or ``log_dir``) is set,
    and is a zero-cost no-op otherwise — recipes can leave it on
    unconditionally. View: tensorboard --logdir <dir> (Profile tab).
    """
    import contextlib
    target = log_dir or os.environ.get(env_var)
    if not target:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(target)
