"""Engine autotuner: offline constant sweep + startup tuning manifest.

The serving engine's hot-path constants — split-KV attention tile
(``block``), prefill chunk / paged KV block size (``chunk``), paged
gather window (``window_blocks``), speculative draft depth
(``spec_k``) — were historically hand-pinned once and shared by every
model family and topology. This package closes the loop the ROADMAP
("Autotuned attention kernels + a self-improving perf loop") asks for:

* :mod:`skypilot_tpu.tune.manifest` — the sha256-pinned JSON manifest
  (``~/.stpu/tuning/manifest.json``) mapping a tuning key
  ``(family, batch-band, tp-degree, quant-mode)`` to tuned constants,
  with provenance (device kind, commit, bench leg, measured tok/s).
  Stdlib-only: the decode engine loads it at geometry resolution and
  must not pull anything heavy.
* :mod:`skypilot_tpu.tune.parity` — the correctness gate: a winner is
  persisted only after the greedy + seeded engine-vs-``models.decode``
  parity suite passes AT the tuned constants (tile-size changes are
  bit-identical only when aligned — the tuner proves it, never
  assumes it).
* :mod:`skypilot_tpu.tune.sweep` — the offline sweep driver behind
  ``stpu tune``: candidate configs measured through the existing
  ``decode_bench.measure_engine_{ragged,paged,spec,q8}`` legs (tok/s
  headline; stepstats dispatch/device means as diagnostics), losing
  configs pruned early at small step counts.

At engine startup, ``serve/decode_engine.resolve_kv_geometry`` looks
the manifest up (env ``STPU_TUNE_MANIFEST``; ``0`` disables, unset
falls back to the default path) so tuned geometry rides the gang
welcome handshake — a follower whose manifest drifted from the
leader's resolves different constants and dies at join, exactly like
a kv/quant config mismatch today.
"""
from skypilot_tpu.tune import manifest  # noqa: F401  (re-export)
